"""Typed option schema.

Role of the reference's src/common/options.cc: every config option is a
schema entry with type, default, level, and description; daemons read
through a typed get. This module carries the subset the framework uses,
plus the machinery to declare more. Schema names follow the reference
(erasure_code_dir: options.cc:295, osd_erasure_code_plugins: :1714,
fault-injection options: :1250-3953).
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["Option", "SCHEMA", "add_option"]

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"


@dataclass(frozen=True)
class Option:
    name: str
    type: type                  # str | int | float | bool
    default: object
    level: str = LEVEL_ADVANCED
    description: str = ""

    def cast(self, value):
        if self.type is bool and isinstance(value, str):
            if value.lower() in ("true", "1", "yes", "on"):
                return True
            if value.lower() in ("false", "0", "no", "off"):
                return False
            raise ValueError("invalid bool %r for %s" % (value, self.name))
        return self.type(value)


SCHEMA: dict[str, Option] = {}


def add_option(name, type_, default, level=LEVEL_ADVANCED, description=""):
    opt = Option(name, type_, default, level, description)
    SCHEMA[name] = opt
    return opt


def _declare_defaults():
    o = add_option
    # erasure code
    o("erasure_code_dir", str, "", LEVEL_ADVANCED,
      "directory for erasure-code plugins (dlopen path in the reference)")
    o("osd_erasure_code_plugins", str, "jerasure isa lrc shec jax_tpu",
      LEVEL_ADVANCED, "plugins preloaded at daemon start")
    o("ec_batch_max_stripes", int, 64, LEVEL_ADVANCED,
      "max stripes coalesced into one device encode call")
    o("ec_batch_linger_us", int, 200, LEVEL_ADVANCED,
      "how long the batching queue waits to fill a device batch")
    # logging
    o("log_to_stderr", bool, False, LEVEL_BASIC)
    o("log_max_recent", int, 500, LEVEL_ADVANCED,
      "size of the in-memory ring dumped on crash")
    o("debug_ec", int, 1, LEVEL_ADVANCED)
    o("debug_osd", int, 1, LEVEL_ADVANCED)
    o("debug_crush", int, 1, LEVEL_ADVANCED)
    o("debug_ms", int, 0, LEVEL_ADVANCED)
    o("debug_mon", int, 1, LEVEL_ADVANCED)
    # osd
    o("osd_pool_default_size", int, 3, LEVEL_BASIC)
    o("osd_pool_default_pg_num", int, 8, LEVEL_BASIC)
    o("osd_heartbeat_interval", float, 0.25, LEVEL_ADVANCED,
      "seconds between peer pings (scaled down for in-process clusters)")
    o("osd_heartbeat_grace", float, 1.0, LEVEL_ADVANCED,
      "seconds without a reply before reporting a peer failed")
    o("osd_max_write_size", int, 90 << 20, LEVEL_ADVANCED)
    o("osd_client_op_priority", int, 63, LEVEL_ADVANCED)
    o("osd_recovery_op_priority", int, 3, LEVEL_ADVANCED)
    o("osd_op_num_shards", int, 4, LEVEL_ADVANCED,
      "ShardedOpWQ shard count (src/osd/OSD.h:1623)")
    o("osd_op_queue", str, "wpq", LEVEL_ADVANCED,
      "op scheduling discipline: wpq | mclock_opclass | fifo")
    o("osd_op_queue_mclock_client_res", float, 0.0, LEVEL_ADVANCED,
      "dmclock reservation (ops/s) for client ops; 0 = none")
    o("osd_op_queue_mclock_client_wgt", float, 500.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_client_lim", float, 0.0, LEVEL_ADVANCED,
      "dmclock limit (ops/s) for client ops; 0 = unlimited")
    o("osd_op_queue_mclock_recovery_res", float, 0.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_recovery_wgt", float, 1.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_recovery_lim", float, 0.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_scrub_res", float, 0.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_scrub_wgt", float, 1.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_scrub_lim", float, 0.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_snaptrim_res", float, 0.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_snaptrim_wgt", float, 1.0, LEVEL_ADVANCED)
    o("osd_op_queue_mclock_snaptrim_lim", float, 0.0, LEVEL_ADVANCED)
    o("mds_beacon_interval", float, 0.25, LEVEL_ADVANCED,
      "seconds between MDS -> mon beacons (options.cc mds_beacon_interval, "
      "scaled for in-process clusters)")
    o("mds_beacon_grace", float, 1.5, LEVEL_ADVANCED,
      "seconds without a beacon before the mon fails an active MDS")
    o("osd_agent_interval", float, 0.25, LEVEL_ADVANCED,
      "seconds between tier-agent flush/evict passes "
      "(osd_agent_delay_time role, scaled for in-process clusters)")
    o("osd_tpu_coalesce", bool, True, LEVEL_ADVANCED,
      "batch concurrent EC device calls sharing a codec/decode matrix "
      "into one dispatch (osd/tpu_dispatch.py)")
    o("osd_tpu_coalesce_max_batch", int, 8, LEVEL_ADVANCED,
      "max ops fused into one device dispatch")
    o("osd_tpu_coalesce_max_delay_ms", float, 1.0, LEVEL_ADVANCED,
      "max milliseconds an op waits for batch-mates before dispatch")
    o("osd_device_index", int, -1, LEVEL_ADVANCED,
      "home device for this OSD's dispatcher/HBM tier pipeline "
      "(parallel/placement.py; ROADMAP direction D): an index into "
      "jax.local_devices() (modulo the device count); -1 = round-robin "
      "by osd id, so an 8-OSD MiniCluster on an 8-chip mesh lands one "
      "OSD per chip without per-daemon conf")
    o("osd_tpu_pipeline_depth", int, 2, LEVEL_ADVANCED,
      "fused batches in flight per dispatcher pipeline stage: h2d of "
      "batch n+1 overlaps compute of n and d2h of n-1 "
      "(osd/tpu_dispatch.py staging ring); 1 = the legacy synchronous "
      "coalesce-then-block loop")
    o("osd_mesh_rateless", bool, True, LEVEL_ADVANCED,
      "route bulk mesh encode/decode/repair-combine jobs through the "
      "rateless micro-batch work queue (parallel/rateless.py; ROADMAP "
      "direction J): idle devices steal micro-batches so a slow chip "
      "takes fewer instead of gating the batch; off = the fixed-shard "
      "mesh paths")
    o("osd_mesh_microbatch_factor", int, 4, LEVEL_ADVANCED,
      "micro-batches per device a bulk mesh job over-decomposes into "
      "(queue length = factor * n_devices): higher = finer-grained "
      "stealing and smoother straggler degradation, at more dispatch "
      "overhead per job")
    o("osd_mesh_microbatch_timeout_ms", float, 0.0, LEVEL_ADVANCED,
      "fixed per-micro-batch deadline before speculative re-dispatch; "
      "0 (default) derives the deadline from the executing device's "
      "rolling latency EWMA (osd_mesh_* deadline multiplier, "
      "parallel/rateless.py)")
    o("osd_mesh_blacklist_strikes", int, 3, LEVEL_ADVANCED,
      "consecutive timeouts/errors that move a device from healthy to "
      "the blacklist (probation re-admits it after an exponential "
      "backoff with one canary micro-batch)")
    o("osd_mesh_probation_base_ms", float, 50.0, LEVEL_ADVANCED,
      "base blacklist backoff; doubles per blacklist episode up to a "
      "bounded max before the probation canary is attempted")
    o("osd_hbm_tier_enable", bool, True, LEVEL_ADVANCED,
      "retain EC encode results device-resident in the HbmChunkTier "
      "keyed by (pg, object): scrub-repair rebuilds and recovery "
      "reconstruction read the resident copy instead of re-crossing "
      "PCIe (osd/hbm_tier.py; ROADMAP direction A)")
    o("osd_hbm_tier_capacity", int, 64, LEVEL_ADVANCED,
      "objects the HBM chunk tier keeps resident; inserts beyond it "
      "evict LRU (an evicted object pays h2d again on its next "
      "repair/recovery, exactly like any cache)")
    o("osd_hbm_tier_serve_reads", bool, False, LEVEL_ADVANCED,
      "serve whole-object EC client reads from the resident copy "
      "(zero sub-reads, zero decode). Default off: residency masks "
      "store-level fault injection and removes the sub_read/ec_decode "
      "spans observability tooling keys on, so reads-from-HBM is an "
      "explicit opt-in (scrub/recovery residency hits ride "
      "osd_hbm_tier_enable alone)")
    # fused write transform (osd/fused_transform.py, ROADMAP
    # direction F): one jitted program per staged batch computes
    # shard crcs + chunk digests + compressibility probe +
    # bit-plane compression + EC encode — one h2d, one d2h
    o("osd_fused_transform", bool, True, LEVEL_ADVANCED,
      "route whole-object EC writes through the fused device "
      "transform (digest + probe + compress + encode in one jitted "
      "program). Off = the classic host-hash + separate-encode path")
    o("osd_fused_compression_mode", str, "none", LEVEL_ADVANCED,
      "inline device compression for fused writes: 'none' stores "
      "raw (digests + encode still fused); 'bitplane' lets the "
      "device decide compress-vs-store per object from the entropy "
      "probe and the required ratio")
    o("osd_fused_required_ratio", float, 0.875, LEVEL_ADVANCED,
      "stored/raw ratio the device compression must beat for a "
      "fused write to store the compressed stream (compressor "
      "required_ratio analog, decided on device)")
    o("osd_fused_probe_entropy_max", float, 7.0, LEVEL_ADVANCED,
      "byte-entropy (bits/byte) above which the fused probe "
      "declares the object incompressible and stores raw without "
      "attempting bit-plane compression")
    # repair-bandwidth-optimal recovery (models/msr.py +
    # osd/ec_backend.py, ROADMAP direction C)
    o("osd_ec_repair_enable", bool, True, LEVEL_ADVANCED,
      "rebuild single lost EC shards from beta-fraction helper reads "
      "when the pool's codec is a regenerating code (plugin=msr): "
      "each of d helpers computes and ships chunk/alpha bytes instead "
      "of the primary pulling k whole chunks. Off = always the "
      "classic full-survivor decode. The product-matrix MSR knobs are "
      "derived from k, not free parameters: sub-packetization "
      "alpha = k-1, repair degree d = 2(k-1) (needs m >= k-1 so d "
      "helpers exist beside the target), per-helper fraction "
      "beta = chunk/alpha — so a repair moves d*chunk/alpha = "
      "2*chunk bytes vs k*chunk for a decode")
    o("osd_op_history_size", int, 20, LEVEL_ADVANCED,
      "completed ops kept for dump_historic_ops")
    o("osd_op_history_duration", float, 600.0, LEVEL_ADVANCED,
      "seconds a completed op stays in history")
    o("osd_op_complaint_time", float, 30.0, LEVEL_ADVANCED,
      "age after which an in-flight op counts as a slow request")
    o("osd_op_history_slow_size", int, 20, LEVEL_ADVANCED,
      "N slowest completed ops retained by the flight recorder "
      "(osd_op_history_slow_op_size role: the `dump_historic_ops` "
      "slowest_ops ring, kept beside the most-recent ring)")
    # device-runtime profiler (common/profiler.py)
    o("osd_profiler", bool, True, LEVEL_ADVANCED,
      "device-runtime profiler: per-(kernel, shape-signature) "
      "jit compile/cache-hit accounting, device-memory ledger, "
      "recompile-storm detection. Off = one attribute check per "
      "wrapped call (the bench cluster row pins this False like "
      "osd_tracing for methodology constancy)")
    o("osd_profiler_recompile_window", float, 60.0, LEVEL_ADVANCED,
      "sliding window (seconds) for the recompile-storm detector")
    o("osd_profiler_recompile_threshold", int, 24, LEVEL_ADVANCED,
      "compiles of ONE kernel within the window that raise "
      "DEVICE_RECOMPILE_STORM (per-kernel, so legitimate warm-up "
      "compiles spread across kernels never trip it)")
    o("osd_hbm_nearfull_ratio", float, 0.85, LEVEL_ADVANCED,
      "HBM chunk-tier occupancy (resident/capacity) above which the "
      "OSD reports device-memory pressure and the monitor raises "
      "DEVICE_MEM_NEARFULL (mon_osd_nearfull_ratio analog for the "
      "device tier)")
    # tracing (TracepointProvider/blkin gating).  The legacy
    # `trace_enable` option (utils.trace gate) is retired: the op-path
    # SpanCollector rides osd_tracing and the tail sampler below.
    o("osd_tracing", bool, True, LEVEL_ADVANCED,
      "collect ZTracer-style op spans end to end (client -> messenger "
      "-> op queue -> PG -> per-shard sub-ops -> store -> TPU device); "
      "default on at framework scale, false = the zero-allocation "
      "NULL_SPAN fast path")
    o("osd_tracing_sample", int, 1, LEVEL_ADVANCED,
      "trace 1 in N root ops (hot-path sampling knob; 1 = every op)")
    o("osd_tracing_max_spans", int, 8192, LEVEL_ADVANCED,
      "per-daemon bounded span ring capacity (oldest spans drop)")
    # tail-based trace retention (SLO forensics): the keep/drop call
    # happens at op COMPLETION on the root daemon, so slow and errored
    # ops are always kept and dropped traces cost zero wire bytes
    o("osd_trace_tail_sample_rate", float, 0.0, LEVEL_ADVANCED,
      "per-pool reservoir probability that a FAST, clean op's trace is "
      "still shipped to the mgr trace store (the baseline population "
      "behind the always-kept SLO-slow and errored traces); 0 ships "
      "only slow/errored traces, 1 ships everything")
    o("osd_trace_pending_ttl", float, 5.0, LEVEL_ADVANCED,
      "seconds a replica holds a trace's span fragments waiting for "
      "the root daemon's keep/drop verdict; expired fragments drop "
      "silently (the root died or dropped the trace)")
    o("mgr_trace_store_bytes", int, 4 << 20, LEVEL_ADVANCED,
      "byte budget for the mgr trace store (stitched cross-daemon "
      "trees); over budget the coldest/fastest traces evict first, "
      "slowest-N and errored traces last")
    o("mgr_trace_protect_slowest", int, 16, LEVEL_ADVANCED,
      "per-pool slowest-N traces protected from trace-store eviction "
      "(the flight-recorder slowest_ops discipline, cluster-wide)")
    # per-principal perf queries (osd/perf_query.py + mgr/perf_query.py)
    o("osd_perf_query_max_keys", int, 256, LEVEL_ADVANCED,
      "bound on distinct keys one OSD-side perf query accumulates; "
      "beyond it the least-recently-updated key is evicted, so a "
      "million clients cannot grow OSD memory past the table "
      "(osd_perf_query top-K table role)")
    o("osd_perf_query_key_age", float, 30.0, LEVEL_ADVANCED,
      "seconds a perf-query key may sit idle before the OSD drops it "
      "(a disconnected client's key stops riding MMgrReport)")
    o("mgr_perf_query_client_age", float, 10.0, LEVEL_ADVANCED,
      "seconds without fresh samples before a client/pool key ages "
      "out of the mgr's merged iotop views and the prometheus page")
    o("mgr_perf_query_prom_top_n", int, 10, LEVEL_ADVANCED,
      "labeled per-client series exported to prometheus: only the "
      "top-N keys by op rate get ceph_client_* series, so exposition "
      "cardinality stays capped by construction")
    o("mgr_slo_pool_targets", str, "", LEVEL_ADVANCED,
      "per-pool latency SLOs as 'pool:latency_ms:objective' entries "
      "separated by commas (e.g. 'rbd:50:0.99,cold:200:0.95'): ops "
      "slower than latency_ms count as violations; when the rolling "
      "violation fraction exceeds 1-objective the burn ratio passes "
      "1.0 and POOL_SLO_VIOLATION raises")
    o("mgr_slo_window", float, 10.0, LEVEL_ADVANCED,
      "rolling window (seconds) over which the per-pool SLO "
      "violation fraction is computed")
    # adaptive QoS: mgr bumps a burning pool's dmclock reservation
    o("mgr_qos_adaptive", bool, False, LEVEL_ADVANCED,
      "when a pool's SLO burn ratio exceeds 1.0, post 'osd pool set "
      "<pool> qos_reservation' raising its dmclock reservation so the "
      "op queues shift capacity toward the burning pool")
    o("mgr_qos_adapt_min_res", float, 50.0, LEVEL_ADVANCED,
      "floor (ops/s) for an adaptively-granted pool reservation")
    o("mgr_qos_adapt_factor", float, 1.5, LEVEL_ADVANCED,
      "multiplicative bump applied to the current reservation each "
      "time the pool is still burning after the cooldown")
    o("mgr_qos_adapt_max_res", float, 10000.0, LEVEL_ADVANCED,
      "ceiling (ops/s) on adaptive reservations, so a miscalibrated "
      "SLO cannot starve every other class")
    o("mgr_qos_adapt_cooldown", float, 5.0, LEVEL_ADVANCED,
      "seconds between adaptive reservation bumps for one pool (the "
      "previous bump must propagate via osdmap before re-judging)")
    # mgr telemetry (the MMgrReport stream + the mgr-side aggregation)
    o("mgr_stats_period", float, 0.5, LEVEL_BASIC,
      "seconds between a daemon's MMgrReport perf/telemetry reports "
      "to the mgr (options.cc mgr_stats_period, scaled for in-process "
      "clusters); 0 disables reporting entirely — the bench cluster "
      "row pins this like osd_tracing=False for methodology constancy")
    o("mgr_stats_stale_after", float, 10.0, LEVEL_ADVANCED,
      "seconds without a report before a daemon's series age out of "
      "the mgr's aggregation and the prometheus exposition "
      "(DaemonStateIndex staleness window)")
    o("mgr_metrics_history", int, 128, LEVEL_ADVANCED,
      "timestamped perf snapshots the MetricsAggregator retains per "
      "daemon (the rate/percentile derivation ring)")
    o("mgr_metrics_window", float, 5.0, LEVEL_ADVANCED,
      "default lookback window (seconds) for derived rates — "
      "`ceph iostat`, per-daemon op rates, device MB/s gauges")
    o("mgr_metrics_mem_budget", int, 64 << 20, LEVEL_ADVANCED,
      "hard byte budget for the mgr's whole telemetry store (raw "
      "rings + rollup tiers + status/pg/pq payloads, byte-accounted "
      "per daemon); exceeding a shard's slice squeezes then evicts "
      "the coldest series first")
    o("mgr_metrics_tiers", str, "5:24,60:30,600:18", LEVEL_ADVANCED,
      "downsampling rollup tiers as 'bucket_seconds:buckets_kept' "
      "pairs — each tier keeps per-counter min/max/sum/count and the "
      "last histogram fills so derived rates/percentiles read "
      "transparently past the raw ring")
    o("mgr_ingest_shards", int, 4, LEVEL_ADVANCED,
      "ingest worker shards MMgrReport handling is hashed onto by "
      "daemon name (lock per shard, batched fold); 0 folds reports "
      "inline on the dispatch thread (the legacy single-threaded "
      "path)")
    o("mgr_ingest_lag_warn", float, 2.0, LEVEL_ADVANCED,
      "seconds of ingest lag p99 (report enqueue -> folded) above "
      "which the mgr raises MGR_INGEST_LAG")
    o("mgr_metrics_budget_full_ratio", float, 0.95, LEVEL_ADVANCED,
      "tracked-bytes / mem-budget occupancy at or above which the "
      "mgr raises MGR_MEM_BUDGET_FULL (eviction pressure is actively "
      "squeezing fresh series)")
    o("mgr_prom_series_cap", int, 2000, LEVEL_ADVANCED,
      "per-metric sample cap on the prometheus exposition: excess "
      "labeled series fold into one {overflow=\"true\"} bucket and "
      "count into ceph_mgr_series_dropped_total")
    o("mgr_progress", bool, True, LEVEL_BASIC,
      "mgr progress module: narrate recovery/backfill convergence as "
      "progress events ('Rebalancing after osd.N marked out') with a "
      "monotone completion fraction and ETA; False pins the module "
      "off (the bench cluster row pins this beside osd_tracing for "
      "methodology constancy)")
    o("mgr_progress_max_completed", int, 32, LEVEL_ADVANCED,
      "completed progress events retained in the bounded ring "
      "(progress module mgr_progress history window)")
    # mon
    o("mon_osd_down_out_interval", float, 2.0, LEVEL_ADVANCED,
      "seconds after down before an osd is marked out")
    o("mon_osd_min_down_reporters", int, 1, LEVEL_ADVANCED)
    # incremental-osdmap pipeline (ISSUE 19: map churn at scale)
    o("mon_min_osdmap_epochs", int, 500, LEVEL_ADVANCED,
      "committed osdmap incrementals each mon retains in its epoch->"
      "inc ring; a subscriber whose epoch falls behind the ring's "
      "trim floor gets exactly ONE full map instead of an inc chain "
      "(the OSDMonitor full/inc trim policy)")
    o("osd_map_message_max", int, 40, LEVEL_ADVANCED,
      "max incrementals batched into one MOSDMap frame; a rejoining "
      "subscriber catches up in ceil(behind/this) bounded messages, "
      "re-subscribing at its new epoch after each frame")
    o("osd_map_max_advance", int, 150, LEVEL_ADVANCED,
      "max osdmap epochs a daemon applies per advance slice; further "
      "incrementals queue in the MonClient backlog and drain on the "
      "next tick, so a long catch-up cannot stall op dispatch or "
      "re-peer every PG in one stop-the-world step")
    o("osd_peering_max_active", int, 64, LEVEL_ADVANCED,
      "peering slots per OSD (AsyncReserver lane beside recovery/"
      "backfill): a map-churn storm re-peers PGs in waves of this "
      "size instead of flooding the op queue; 0 disables the gate")
    o("paxos_propose_interval", float, 0.05, LEVEL_ADVANCED)
    o("ms_type", str, "simple", LEVEL_ADVANCED,
      "messenger transport: simple (thread-per-connection) | async "
      "(event-loop, the AsyncMessenger analog)")
    o("cephx_sign_messages", bool, True, LEVEL_ADVANCED,
      "HMAC-sign every post-auth frame with the connection's cephx "
      "session key; a bad signature resets the connection "
      "(CephxSessionHandler sign_message/check_message_signature)")
    # fault injection (dev-level, like options.cc:1250-3953)
    o("ms_inject_socket_failures", int, 0, LEVEL_DEV,
      "drop 1 in N messages at the messenger")
    o("ms_inject_delay_max", float, 0.0, LEVEL_DEV,
      "random extra delivery delay upper bound, seconds")
    o("objectstore_inject_read_err", bool, False, LEVEL_DEV,
      "make reads of marked objects return EIO")
    o("objectstore_inject_eio", int, 0, LEVEL_DEV,
      "object reads fail EIO for 1 in N objects (seeded hash "
      "selection; store/faults.py FaultSet)")
    o("objectstore_inject_bitrot", int, 0, LEVEL_DEV,
      "object reads return silently flipped bytes for 1 in N objects")
    o("objectstore_fault_seed", int, 0, LEVEL_DEV,
      "seed for the deterministic store fault selection")
    o("osd_inject_failure_on_write", float, 0.0, LEVEL_DEV,
      "probability a sub-write is dropped before commit")
    # scrub / repair
    o("osd_scrub_auto_repair", bool, True, LEVEL_ADVANCED,
      "scrub repairs inconsistencies it finds; False = detect only "
      "(errors persist as OSD_SCRUB_ERRORS until 'pg repair'). "
      "Default True keeps the historical always-repair behavior; the "
      "reference defaults false and repairs only on command.")
    # mon cluster log
    o("mon_log_max", int, 500, LEVEL_ADVANCED,
      "cluster log entries the LogMonitor keeps ('ceph log last' "
      "window; mon_cluster_log_* role)")
    o("mon_events_max", int, 500, LEVEL_ADVANCED,
      "structured cluster events the EventMonitor keeps ('ceph "
      "events last' / 'ceph events watch' window: health "
      "transitions, osdmap changes, progress open/close, thrash "
      "actions)")
    # bluestore / bluefs
    o("store_fsck_on_umount", bool, True, LEVEL_ADVANCED,
      "BlockStore.umount() cross-checks BlueFS extents, blob extents "
      "and the free list for overlap/leak and raises on errors — every "
      "store test doubles as an allocator check "
      "(bluestore_fsck_on_umount role; the reference defaults false)")
    o("bluefs_log_compact_threshold", int, 1 << 20, LEVEL_ADVANCED,
      "BlueFS journal extent size; when the log outgrows it the file "
      "table is compacted into a fresh extent "
      "(bluefs_log_compact_min_size role)")
    # filestore
    o("filestore_compression", str, "none", LEVEL_ADVANCED,
      "checkpoint blob compression: none|zlib|zstd|snappy|lz4")
    o("filestore_compression_required_ratio", float, 0.875,
      LEVEL_ADVANCED,
      "store compressed only if <= input * ratio "
      "(bluestore_compression_required_ratio analog)")
    # throttles
    o("objecter_inflight_ops", int, 1024, LEVEL_ADVANCED)
    o("osd_client_message_cap", int, 256, LEVEL_ADVANCED,
      "max undispatched+inflight client messages a public messenger "
      "admits before the reader stops pulling frames off the socket "
      "(dispatch-side Throttle -> TCP backpressure; "
      "Messenger::Policy throttler_messages role)")
    o("osd_client_message_size_cap", int, 256 << 20, LEVEL_ADVANCED,
      "max bytes of undispatched+inflight client message payload "
      "before the reader blocks (throttler_bytes role); 0 = unlimited")
    # recovery/backfill reservations (AsyncReserver slots)
    o("osd_max_backfills", int, 1, LEVEL_ADVANCED,
      "backfill reservations one OSD grants concurrently, local "
      "(primary) and remote (replica) sides each "
      "(options.cc osd_max_backfills)")
    o("osd_recovery_max_active", int, 3, LEVEL_ADVANCED,
      "log-based recovery reservations one OSD grants concurrently "
      "(osd_recovery_max_active role, counted in PGs not ops at "
      "framework scale)")
    o("osd_recovery_sleep", float, 0.0, LEVEL_ADVANCED,
      "baseline delay (seconds) injected before each recovery/backfill "
      "push through a BackoffThrottle: the effective sleep scales from "
      "this value toward 10x as concurrent pushes approach the "
      "reservation slot budget; 0 disables shaping")
    # cluster full-ratio ladder (mon-side thresholds against each
    # OSD's reported statfs utilization)
    o("mon_osd_nearfull_ratio", float, 0.85, LEVEL_ADVANCED,
      "store utilization above which an OSD raises OSD_NEARFULL "
      "(warning only)")
    o("mon_osd_backfillfull_ratio", float, 0.90, LEVEL_ADVANCED,
      "store utilization above which an OSD refuses NEW remote "
      "backfill reservations (PGs targeting it stall in "
      "backfill_toofull)")
    o("mon_osd_full_ratio", float, 0.95, LEVEL_ADVANCED,
      "store utilization above which the OSD rejects client writes "
      "with ENOSPC at admission (reads still served) and recovery "
      "into it pauses")


_declare_defaults()
