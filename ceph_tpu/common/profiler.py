"""Device-runtime profiler: JIT-compile and device-memory accounting.

The observability groundwork for ROADMAP direction E (the unified
DeviceProgram runtime wants "built-in trace spans + telemetry
counters"): every hand-rolled `jax.jit` site in the tree — the
dispatcher's donation path, the CRUSH batch kernels, the HBM tier's
digest, the ops/ GF kernels, the mesh collectives — registers with ONE
process-wide registry, so "why did streaming stall" decomposes into
per-(kernel, shape-signature) compile counts, compile wall time and
trace-cache hits instead of guesswork.

Two failure classes this makes visible:

* **Recompile storms**: a kernel re-traced for every call because its
  input shapes churn (the classic jax footgun: a new batch size or a
  new erasure signature per op).  The detector keeps a bounded ring of
  compile events and flags any kernel whose compiles-within-window
  cross the threshold; the OSD ships the verdict with its MPGStats
  report and the monitor raises DEVICE_RECOMPILE_STORM cluster-wide.

* **Device-memory creep**: HBM is small and nothing owned the ledger.
  Categories (hbm_tier residency, the dispatcher's staging ring,
  donated buffers, cached decode tables) account live bytes plus a
  high watermark each; the OSD derives DEVICE_MEM_NEARFULL from the
  tier's occupancy against osd_hbm_nearfull_ratio.

The registry is process-global (module-level jit sites have no daemon
context) and config-gated: `osd_profiler` off reduces every wrapped
call to one attribute check — the bench.py overhead gate holds the
on/off streaming delta under 3%.
"""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["DeviceProfiler", "PROFILER", "profiled_jit"]

# device-memory ledger categories (mem_* accept any string; these are
# the ones the OSD path populates)
MEM_CATEGORIES = ("hbm_tier", "staging_ring", "donated_buffers",
                  "decode_tables")


def _placement_token(a):
    """Device/sharding component of an array's signature.  jax keys its
    trace cache on committed placement and sharding as well as shape:
    the same (shape, dtype) on a second device is a fresh compile, and
    folding it into one signature would report false cache hits on one
    side and phantom recompile storms on the other.  Host arrays (no
    `.sharding`) contribute nothing, keeping their signatures stable."""
    sh = getattr(a, "sharding", None)
    if sh is None:
        return None
    try:
        devs = sorted("%s:%d" % (d.platform, d.id) for d in a.devices())
        token = ",".join(devs) if len(devs) <= 8 else "%dxdev" % len(devs)
        return (type(sh).__name__, str(getattr(sh, "spec", "")), token)
    except Exception:
        return type(sh).__name__


def _shape_sig(args, kwargs):
    """Cheap shape signature: (shape, dtype[, placement]) per
    array-like argument, repr-type for scalars/statics.  Two calls with
    the same signature hit the same jit trace-cache entry; a fresh
    signature is (to first order) a fresh trace/compile — which is
    exactly the event the storm detector wants, without hooking XLA
    internals."""
    def one(a):
        shape = getattr(a, "shape", None)
        if shape is not None:
            sig = (tuple(shape), str(getattr(a, "dtype", "")))
            placement = _placement_token(a)
            return sig if placement is None else sig + (placement,)
        if isinstance(a, (int, float, bool, str, bytes, type(None))):
            return a
        return type(a).__name__
    sig = tuple(one(a) for a in args)
    if kwargs:
        sig += tuple((k, one(v)) for k, v in sorted(kwargs.items()))
    return sig


class _Kernel:
    __slots__ = ("sigs", "compiles", "compile_wall", "cache_hits")

    def __init__(self):
        self.sigs: dict = {}          # sig -> [compiles, wall, hits]
        self.compiles = 0
        self.compile_wall = 0.0
        self.cache_hits = 0


class DeviceProfiler:
    """Process-wide jit registry + device-memory ledger (one instance,
    `PROFILER`, shared by every daemon in the process — module-level
    kernels have no per-daemon home)."""

    def __init__(self, recompile_window: float = 60.0,
                 recompile_threshold: int = 24):
        self.enabled = True
        self.recompile_window = recompile_window
        self.recompile_threshold = recompile_threshold
        self._lock = threading.Lock()
        self._kernels: dict[str, _Kernel] = {}
        # bounded compile-event ring: (monotonic stamp, kernel name)
        self._compile_events: deque = deque(maxlen=4096)
        # category -> [live_bytes, high_watermark]
        self._mem: dict[str, list] = {}

    def configure(self, conf) -> None:
        """Adopt the daemon's osd_profiler* knobs (idempotent: every
        OSD in a shared-process cluster applies the same conf)."""
        try:
            self.enabled = bool(conf.get_val("osd_profiler"))
            self.recompile_window = float(
                conf.get_val("osd_profiler_recompile_window"))
            self.recompile_threshold = int(
                conf.get_val("osd_profiler_recompile_threshold"))
        except Exception:
            pass

    # -- jit accounting -------------------------------------------------

    def record_compile(self, kernel: str, sig, wall: float) -> None:
        with self._lock:
            k = self._kernels.setdefault(kernel, _Kernel())
            row = k.sigs.setdefault(sig, [0, 0.0, 0])
            row[0] += 1
            row[1] += wall
            k.compiles += 1
            k.compile_wall += wall
            self._compile_events.append((time.monotonic(), kernel))

    def record_hit(self, kernel: str, sig) -> None:
        with self._lock:
            k = self._kernels.setdefault(kernel, _Kernel())
            row = k.sigs.setdefault(sig, [0, 0.0, 0])
            row[2] += 1
            k.cache_hits += 1

    def note_call(self, kernel: str, args=(), kwargs=None) -> bool:
        """Classify one call of `kernel`: True when its signature is
        new (caller should time the call and record_compile), False on
        a trace-cache hit (recorded here)."""
        sig = _shape_sig(args, kwargs or {})
        with self._lock:
            k = self._kernels.setdefault(kernel, _Kernel())
            if sig in k.sigs:
                k.sigs[sig][2] += 1
                k.cache_hits += 1
                return False
        return True

    def wrap_jit(self, kernel: str, fn):
        """Wrap an already-jitted callable: per-(kernel, shape-sig)
        compile/hit accounting with a single attribute check when the
        profiler is off.  First call with a fresh signature is counted
        as the compile and its wall time as the compile wall (jit
        trace-cache semantics, observed from outside)."""
        def wrapped(*args, **kwargs):
            if not self.enabled:
                return fn(*args, **kwargs)
            sig = _shape_sig(args, kwargs)
            with self._lock:
                k = self._kernels.setdefault(kernel, _Kernel())
                fresh = sig not in k.sigs
                if not fresh:
                    k.sigs[sig][2] += 1
                    k.cache_hits += 1
            if not fresh:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            self.record_compile(kernel, sig, time.perf_counter() - t0)
            return out
        wrapped.__wrapped__ = fn
        wrapped.__name__ = getattr(fn, "__name__", kernel)
        return wrapped

    # -- recompile-storm detection --------------------------------------

    def storm_report(self, now: float | None = None) -> dict:
        """Worst kernel by compiles-within-window.  {kernel, count,
        window_s, threshold, storming}."""
        now = time.monotonic() if now is None else now
        cutoff = now - self.recompile_window
        with self._lock:
            counts: dict[str, int] = {}
            for t, kernel in self._compile_events:
                if t >= cutoff:
                    counts[kernel] = counts.get(kernel, 0) + 1
        worst, count = None, 0
        for kernel, n in counts.items():
            if n > count:
                worst, count = kernel, n
        return {"kernel": worst, "count": count,
                "window_s": self.recompile_window,
                "threshold": self.recompile_threshold,
                "storming": count >= self.recompile_threshold}

    def storm_count(self) -> int:
        """The MPGStats feed: the worst kernel's in-window compile
        count when it crosses the threshold, else 0 (cheap; rides the
        heartbeat path)."""
        rep = self.storm_report()
        return rep["count"] if rep["storming"] else 0

    # -- device-memory ledger -------------------------------------------

    def mem_add(self, category: str, nbytes: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            row = self._mem.setdefault(category, [0, 0])
            row[0] += int(nbytes)
            if row[0] > row[1]:
                row[1] = row[0]

    def mem_sub(self, category: str, nbytes: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            row = self._mem.setdefault(category, [0, 0])
            row[0] = max(0, row[0] - int(nbytes))

    def mem_set(self, category: str, nbytes: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            row = self._mem.setdefault(category, [0, 0])
            row[0] = int(nbytes)
            if row[0] > row[1]:
                row[1] = row[0]

    def mem_dump(self) -> dict:
        with self._lock:
            out = {cat: {"bytes": row[0], "high_watermark": row[1]}
                   for cat, row in sorted(self._mem.items())}
        out["total_bytes"] = sum(r["bytes"] for r in out.values())
        return out

    # -- introspection (asok `profile dump` payload) --------------------

    def dump(self) -> dict:
        with self._lock:
            kernels = {}
            for name, k in sorted(self._kernels.items()):
                sigs = sorted(k.sigs.items(),
                              key=lambda kv: kv[1][0], reverse=True)
                kernels[name] = {
                    "compiles": k.compiles,
                    "compile_wall_s": round(k.compile_wall, 6),
                    "cache_hits": k.cache_hits,
                    "signatures": [
                        {"sig": repr(sig), "compiles": row[0],
                         "compile_wall_s": round(row[1], 6),
                         "cache_hits": row[2]}
                        for sig, row in sigs[:16]],
                    "num_signatures": len(k.sigs)}
        return {"enabled": self.enabled,
                "kernels": kernels,
                "recompile_storm": self.storm_report(),
                "memory": self.mem_dump()}

    def reset(self) -> None:
        """Zero the jit registry, the compile-event ring, and the
        memory high watermarks (live bytes stay — they are gauges of
        real residency, not statistics)."""
        with self._lock:
            self._kernels.clear()
            self._compile_events.clear()
            for row in self._mem.values():
                row[1] = row[0]


PROFILER = DeviceProfiler()


def profiled_jit(kernel: str, fn=None, **jit_kwargs):
    """`jax.jit` with registry accounting: profiled_jit("name", fn)
    or @profiled_jit("name", static_argnames=...).  Falls back to the
    bare function when jax is unavailable (host-only environments)."""
    def apply(f):
        try:
            import jax
            jitted = jax.jit(f, **jit_kwargs)
        except Exception:
            jitted = f
        return PROFILER.wrap_jit(kernel, jitted)
    if fn is None:
        return apply
    return apply(fn)
