"""Interval sets and interval maps.

Role of the reference's interval_set (src/include/interval_set.h) and
extent_map's backing interval_map (src/include/interval_map.h): sorted,
coalesced [offset, offset+len) ranges — the currency of the EC write
planner (extent_set of stripes to read/write) and the ExtentCache
(extent_map of offset -> bytes).
"""

from __future__ import annotations

import bisect

import numpy as np

__all__ = ["IntervalSet", "ExtentMap"]


class IntervalSet:
    """Coalesced set of half-open integer intervals (extent_set)."""

    def __init__(self, intervals=None):
        self._ivs: list[tuple[int, int]] = []  # sorted (start, end)
        if intervals:
            for start, length in intervals:
                self.union_insert(start, length)

    # -- mutation ------------------------------------------------------

    def union_insert(self, start: int, length: int) -> None:
        if length <= 0:
            return
        end = start + length
        out = []
        for s, e in self._ivs:
            if e < start or s > end:
                out.append((s, e))
            else:  # touching or overlapping: absorb
                start, end = min(s, start), max(e, end)
        bisect.insort(out, (start, end))
        self._ivs = out

    def erase(self, start: int, length: int) -> None:
        end = start + length
        out = []
        for s, e in self._ivs:
            if e <= start or s >= end:
                out.append((s, e))
            else:
                if s < start:
                    out.append((s, start))
                if e > end:
                    out.append((end, e))
        self._ivs = out

    def union_of(self, other: "IntervalSet") -> None:
        for s, e in other._ivs:
            self.union_insert(s, e - s)

    def intersect(self, other: "IntervalSet") -> "IntervalSet":
        out = IntervalSet()
        for s1, e1 in self._ivs:
            for s2, e2 in other._ivs:
                s, e = max(s1, s2), min(e1, e2)
                if s < e:
                    out.union_insert(s, e - s)
        return out

    # -- queries -------------------------------------------------------

    def __iter__(self):
        for s, e in self._ivs:
            yield s, e - s

    def __bool__(self) -> bool:
        return bool(self._ivs)

    def __len__(self) -> int:
        return len(self._ivs)

    def __eq__(self, other) -> bool:
        return isinstance(other, IntervalSet) and self._ivs == other._ivs

    def __repr__(self) -> str:
        return "IntervalSet(%s)" % [(s, e - s) for s, e in self._ivs]

    def empty(self) -> bool:
        return not self._ivs

    def size(self) -> int:
        return sum(e - s for s, e in self._ivs)

    def contains(self, start: int, length: int = 1) -> bool:
        end = start + length
        return any(s <= start and end <= e for s, e in self._ivs)

    def intersects(self, start: int, length: int) -> bool:
        end = start + length
        return any(s < end and start < e for s, e in self._ivs)

    def range_start(self) -> int:
        return self._ivs[0][0]

    def range_end(self) -> int:
        return self._ivs[-1][1]


class ExtentMap:
    """offset -> bytes map with interval semantics (extent_map over
    bufferlists in the reference). Later inserts overwrite overlaps."""

    def __init__(self):
        self._ivs: list[tuple[int, np.ndarray]] = []  # sorted (start, data)

    def insert(self, offset: int, data) -> None:
        arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) else \
            np.asarray(data, dtype=np.uint8).reshape(-1)
        if arr.size == 0:
            return
        end = offset + arr.size
        out = []
        for s, d in self._ivs:
            e = s + d.size
            if e <= offset or s >= end:
                out.append((s, d))
            else:
                if s < offset:
                    out.append((s, d[:offset - s]))
                if e > end:
                    out.append((end, d[end - s:]))
        bisect.insort(out, (offset, arr), key=lambda x: x[0])
        self._ivs = out
        self._coalesce()

    def _coalesce(self) -> None:
        out = []
        for s, d in self._ivs:
            if out:
                ps, pd = out[-1]
                if ps + pd.size == s:
                    out[-1] = (ps, np.concatenate([pd, d]))
                    continue
            out.append((s, d))
        self._ivs = out

    def erase(self, offset: int, length: int) -> None:
        end = offset + length
        out = []
        for s, d in self._ivs:
            e = s + d.size
            if e <= offset or s >= end:
                out.append((s, d))
            else:
                if s < offset:
                    out.append((s, d[:offset - s]))
                if e > end:
                    out.append((end, d[end - s:]))
        self._ivs = out

    def get(self, offset: int, length: int) -> np.ndarray | None:
        """Contiguous bytes [offset, offset+length) or None if any hole."""
        end = offset + length
        parts = []
        pos = offset
        for s, d in self._ivs:
            e = s + d.size
            if e <= pos or s >= end:
                continue
            if s > pos:
                return None
            parts.append(d[pos - s:min(e, end) - s])
            pos = min(e, end)
            if pos >= end:
                break
        if pos < end:
            return None
        return np.concatenate(parts) if len(parts) != 1 else parts[0]

    def intervals(self) -> IntervalSet:
        out = IntervalSet()
        for s, d in self._ivs:
            out.union_insert(s, d.size)
        return out

    def __iter__(self):
        return iter(self._ivs)

    def empty(self) -> bool:
        return not self._ivs
