"""Segmented buffers: the framework's bufferlist.

Role of the reference's bufferptr/bufferlist (src/include/buffer.h,
src/common/buffer.cc): zero-copy append/substr/splice over refcounted
segments, alignment control for codec input
(rebuild_aligned_size_and_memory, used by encode_prepare at
src/erasure-code/ErasureCode.cc:134), file IO helpers, crc32c.

TPU-first difference: segments are numpy uint8 arrays so a BufferList can
hand the device a contiguous view without a copy when it is already
coalesced; ``to_array()`` is the seam the batched codec path uses.
Python's refcounting replaces the reference's intrusive refcounts.
"""

from __future__ import annotations

import zlib

import numpy as np

__all__ = ["Buffer", "BufferList"]


class Buffer:
    """One refcounted segment (bufferptr): a view into a numpy array."""

    __slots__ = ("arr",)

    def __init__(self, data, copy: bool = False):
        if isinstance(data, Buffer):
            arr = data.arr
        elif isinstance(data, np.ndarray):
            arr = data.reshape(-1).view(np.uint8)
        elif isinstance(data, int):
            arr = np.zeros(data, dtype=np.uint8)
        else:
            arr = np.frombuffer(bytes(data) if not isinstance(
                data, (bytes, bytearray, memoryview)) else data,
                dtype=np.uint8)
        self.arr = arr.copy() if copy else arr

    def __len__(self) -> int:
        return self.arr.size

    def length(self) -> int:
        return self.arr.size

    def is_aligned(self, align: int) -> bool:
        return self.arr.ctypes.data % align == 0

    def substr(self, off: int, length: int) -> "Buffer":
        return Buffer(self.arr[off:off + length])

    def tobytes(self) -> bytes:
        return self.arr.tobytes()


class BufferList:
    """Ordered list of segments with bufferlist's surface."""

    def __init__(self, data=None):
        self._bufs: list[Buffer] = []
        self._len = 0
        if data is not None:
            self.append(data)

    # -- growth --------------------------------------------------------

    def append(self, data) -> None:
        if isinstance(data, BufferList):
            self._bufs.extend(data._bufs)
            self._len += data._len
            return
        buf = data if isinstance(data, Buffer) else Buffer(data)
        if len(buf):
            self._bufs.append(buf)
            self._len += len(buf)

    def append_zero(self, n: int) -> None:
        if n > 0:
            self.append(Buffer(n))

    def claim_append(self, other: "BufferList") -> None:
        self.append(other)
        other.clear()

    def clear(self) -> None:
        self._bufs = []
        self._len = 0

    # -- inspection ----------------------------------------------------

    def __len__(self) -> int:
        return self._len

    def length(self) -> int:
        return self._len

    def get_num_buffers(self) -> int:
        return len(self._bufs)

    def is_contiguous(self) -> bool:
        return len(self._bufs) <= 1

    def contents_equal(self, other: "BufferList") -> bool:
        if self._len != other._len:
            return False
        return np.array_equal(self.to_array(), other.to_array())

    def crc32c(self, seed: int = 0) -> int:
        # framework-wide integrity hash; the reference uses crc32c
        # (src/include/crc32c.h) — crc32 serves the same contract here
        # and stays consistent across the codebase
        return zlib.crc32(self.to_array().tobytes(), seed) & 0xFFFFFFFF

    # -- reshaping -----------------------------------------------------

    def to_array(self) -> np.ndarray:
        """Contiguous uint8 view; zero-copy when already coalesced."""
        if not self._bufs:
            return np.empty(0, dtype=np.uint8)
        if len(self._bufs) == 1:
            return self._bufs[0].arr
        return np.concatenate([b.arr for b in self._bufs])

    def tobytes(self) -> bytes:
        return self.to_array().tobytes()

    def rebuild(self) -> None:
        """Coalesce into one segment (bufferlist::rebuild)."""
        if len(self._bufs) > 1:
            arr = self.to_array()
            self._bufs = [Buffer(arr)]

    def rebuild_aligned(self, align: int) -> None:
        """Coalesce + pad to a multiple of align with zeros, like the
        benchmark's in.rebuild_aligned(SIMD_ALIGN) prep."""
        pad = (-self._len) % align
        if pad:
            self.append_zero(pad)
        self.rebuild()

    def substr(self, off: int, length: int) -> "BufferList":
        if off < 0 or off + length > self._len:
            raise IndexError("substr(%d, %d) of %d" % (off, length, self._len))
        return BufferList(self.to_array()[off:off + length])

    def splice(self, off: int, length: int) -> "BufferList":
        """Remove [off, off+length) and return it (bufferlist::splice)."""
        removed = self.substr(off, length)
        arr = self.to_array()
        rest = np.concatenate([arr[:off], arr[off + length:]])
        self._bufs = [Buffer(rest)] if rest.size else []
        self._len = rest.size
        return removed

    # -- file IO (non_regression / corpus tooling) ---------------------

    def write_file(self, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.to_array().tobytes())

    @classmethod
    def read_file(cls, path: str) -> "BufferList":
        with open(path, "rb") as f:
            return cls(f.read())
