"""Lock-order cycle detection.

Role of the reference's lockdep (src/common/lockdep.cc, enabled via
the lockdep config option and wired through common/Mutex): every
instrumented lock records, at acquire time, an order edge from each
lock already held by the thread; an edge that closes a cycle in the
global order graph is a potential deadlock and is reported with both
acquisition sites.

Names are per-INSTANCE (e.g. "pg:1.3", "osd:2"), so inversions
between two locks of the same class — the classic PG-A/PG-B deadlock —
are visible, and a pgA->osd, osd->pgB chain is not falsely aliased
into a pg<->osd cycle. (The reference registers by name string too;
instance-unique names are what make that sound.)

Usage: the daemon code creates its locks through make_rlock(name).
With lockdep disabled (the default) that returns a plain
threading.RLock — zero overhead. Enabled (enable(), or the
CEPH_TPU_LOCKDEP env var at process start), it returns a DebugRLock
that feeds the order graph; violations are collected in `violations`
(and raised immediately in strict mode, like the reference's
lockdep_force_backtrace + assert).
"""

from __future__ import annotations

import os
import threading
import traceback

__all__ = ["enable", "disable", "enabled", "make_rlock", "DebugRLock",
           "LockOrderError", "violations", "reset"]


class LockOrderError(RuntimeError):
    pass


_graph_lock = threading.Lock()
_edges: dict[str, set[str]] = {}     # held -> then-acquired
_edge_sites: dict[tuple, str] = {}   # (held, acquired) -> backtrace
_reported: set[tuple] = set()        # cycles already reported once
violations: list[str] = []
_tls = threading.local()

_state = {"enabled": bool(os.environ.get("CEPH_TPU_LOCKDEP")),
          "strict": False}


def enabled() -> bool:
    return _state["enabled"]


def enable(strict: bool = False) -> None:
    _state["enabled"] = True
    _state["strict"] = strict


def disable() -> None:
    _state["enabled"] = False
    _state["strict"] = False


def reset() -> None:
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()
        _reported.clear()
        violations.clear()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _reaches(src: str, dst: str) -> bool:
    """Is there a path src ->* dst in the order graph? (called with
    _graph_lock held)"""
    seen = set()
    work = [src]
    while work:
        cur = work.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        work.extend(_edges.get(cur, ()))
    return False


def _note_acquire(name: str) -> None:
    st = _stack()
    held = [h for h in st if h != name]
    if held:
        with _graph_lock:
            for h in held:
                if name in _edges.get(h, ()):
                    continue            # edge already known, no recheck
                if _reaches(name, h):
                    if (h, name) in _reported:
                        continue    # one report per offending pair —
                                    # a hot-path inversion must not
                                    # grow the list per acquire
                    _reported.add((h, name))
                    site = _edge_sites.get((name, h), "<unknown>")
                    msg = ("lock order cycle: acquiring %r while "
                           "holding %r, but %r -> %r was established "
                           "at:\n%s\nnow at:\n%s"
                           % (name, h, name, h, site,
                              "".join(traceback.format_stack(limit=8))))
                    violations.append(msg)
                    if _state["strict"]:
                        raise LockOrderError(msg)
                    continue
                _edges.setdefault(h, set()).add(name)
                _edge_sites[(h, name)] = \
                    "".join(traceback.format_stack(limit=8))
    st.append(name)


def _note_release(name: str) -> None:
    st = _stack()
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            return


class DebugRLock:
    """Named re-entrant lock feeding the order graph. API-compatible
    with threading.RLock including the private hooks Condition uses."""

    def __init__(self, name: str):
        self.name = name
        self._lk = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lk.acquire(blocking, timeout)
        if got and _state["enabled"]:
            try:
                _note_acquire(self.name)
            except LockOrderError:
                # strict mode: the report must not leave the lock held
                # forever (the with-body never runs, so no release)
                self._lk.release()
                raise
        return got

    def release(self) -> None:
        if _state["enabled"]:
            _note_release(self.name)
        self._lk.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    # threading.Condition compatibility
    def _is_owned(self):
        return self._lk._is_owned()

    def _acquire_restore(self, state):
        self._lk._acquire_restore(state)
        if _state["enabled"]:
            _note_acquire(self.name)

    def _release_save(self):
        if _state["enabled"]:
            _note_release(self.name)
        return self._lk._release_save()

    def __repr__(self):
        return "<DebugRLock %s>" % self.name


def make_rlock(name: str):
    """A named lock: DebugRLock under lockdep, plain RLock otherwise."""
    if _state["enabled"]:
        return DebugRLock(name)
    return threading.RLock()
