"""Backpressure primitives.

Role of the reference's Throttle (src/common/Throttle.{h,cc}): a counted
budget; get() blocks while the budget is exhausted, put() releases.
BackoffThrottle adds randomized delay shaping instead of a hard wall
(used by BlueStore's deferred-write shaping). These guard every queue
the daemons expose to untrusted producers (client message cap, objecter
inflight ops).
"""

from __future__ import annotations

import random
import threading
import time

__all__ = ["Throttle", "BackoffThrottle", "ThrottleTimeout"]


class ThrottleTimeout(Exception):
    pass


class Throttle:
    def __init__(self, name: str, max_: int):
        self.name = name
        self._max = max_
        self._current = 0
        self._waiters = 0
        self._cond = threading.Condition()

    # -- core ----------------------------------------------------------

    def get(self, count: int = 1, timeout: float | None = None) -> None:
        """Block until count fits within the budget (Throttle::get)."""
        if self._max <= 0:  # unlimited, like max=0 in the reference
            with self._cond:
                self._current += count
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._current + count > self._max and count <= self._max:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise ThrottleTimeout(
                        "%s: waited %.3fs for %d/%d" %
                        (self.name, timeout, count, self._max))
                self._waiters += 1
                try:
                    self._cond.wait(remaining)
                finally:
                    self._waiters -= 1
            self._current += count

    def get_or_fail(self, count: int = 1) -> bool:
        with self._cond:
            if self._max > 0 and self._current + count > self._max \
                    and count <= self._max:
                return False
            self._current += count
            return True

    def put(self, count: int = 1) -> None:
        with self._cond:
            self._current -= count
            self._cond.notify_all()

    # -- introspection -------------------------------------------------

    def num_waiters(self) -> int:
        """Threads currently parked inside get() (read under the cond
        lock, so >0 means a waiter is genuinely in wait())."""
        with self._cond:
            return self._waiters

    def get_current(self) -> int:
        with self._cond:
            return self._current

    def get_max(self) -> int:
        return self._max

    def past_midpoint(self) -> bool:
        with self._cond:
            return self._current >= self._max / 2

    class _Guard:
        __slots__ = ("t", "count")

        def __init__(self, t, count):
            self.t, self.count = t, count

        def __enter__(self):
            self.t.get(self.count)
            return self

        def __exit__(self, *exc):
            self.t.put(self.count)

    def guard(self, count: int = 1) -> "_Guard":
        return self._Guard(self, count)


class BackoffThrottle:
    """Delay-shaping throttle: instead of blocking at the wall, injects
    growing sleeps as utilization crosses low/high watermarks
    (src/common/Throttle.h BackoffThrottle)."""

    def __init__(self, name: str, max_: int,
                 low_threshold: float = 0.5, high_threshold: float = 0.9,
                 low_delay: float = 0.0005, high_delay: float = 0.01):
        self.name = name
        self._max = max_
        self._low = low_threshold
        self._high = high_threshold
        self._low_delay = low_delay
        self._high_delay = high_delay
        self._current = 0
        self._lock = threading.Lock()

    def _delay(self, util: float) -> float:
        if util < self._low:
            return 0.0
        if util < self._high:
            frac = (util - self._low) / (self._high - self._low)
            return self._low_delay + frac * (self._high_delay -
                                             self._low_delay)
        return self._high_delay

    def get(self, count: int = 1) -> float:
        with self._lock:
            self._current += count
            util = self._current / self._max if self._max else 0.0
        delay = self._delay(util)
        if delay:
            time.sleep(delay * (0.5 + random.random()))
        return delay

    def put(self, count: int = 1) -> None:
        with self._lock:
            self._current -= count

    def get_current(self) -> int:
        with self._lock:
            return self._current
