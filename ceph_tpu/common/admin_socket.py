"""Per-daemon command server.

Role of the reference's AdminSocket (src/common/admin_socket.{h,cc}): a
unix-domain socket in every daemon where operators run introspection
commands without touching the data path ("perf dump",
"config get/set/diff", "dump_ops_in_flight", ...). Commands register a
hook; the server answers each connection with JSON. Protocol here: one
JSON request line {"prefix": ..., **args} -> one JSON reply, vs the
reference's length-prefixed format — same operational surface.
"""

from __future__ import annotations

import json
import os
import socket
import threading

__all__ = ["AdminSocket", "AdminSocketClient"]


class AdminSocket:
    def __init__(self, path: str):
        self.path = path
        self._hooks: dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._server: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stopping = False
        self.register("help", self._help, "list available commands")
        self.register("version", lambda args: {"version": "1.0.0"},
                      "framework version")

    # -- hooks ---------------------------------------------------------

    def register(self, prefix: str, hook, help_: str = "") -> None:
        """hook: callable(args: dict) -> JSON-serializable reply."""
        with self._lock:
            if prefix in self._hooks:
                raise ValueError("command %r already registered" % prefix)
            self._hooks[prefix] = (hook, help_)

    def unregister(self, prefix: str) -> None:
        with self._lock:
            self._hooks.pop(prefix, None)

    def _help(self, args: dict) -> dict:
        with self._lock:
            return {prefix: help_ for prefix, (_, help_)
                    in sorted(self._hooks.items())}

    def execute(self, prefix: str, args: dict | None = None):
        """In-process dispatch (also what the socket server calls)."""
        with self._lock:
            entry = self._hooks.get(prefix)
        if entry is None:
            return {"error": "unknown command %r" % prefix}
        hook, _ = entry
        try:
            return hook(args or {})
        except Exception as e:  # a broken hook must not kill the daemon
            return {"error": "%s: %s" % (e.__class__.__name__, e)}

    # -- server --------------------------------------------------------

    def init(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(self.path)
        self._server.listen(8)
        self._server.settimeout(0.2)
        self._thread = threading.Thread(target=self._serve,
                                        name="admin-socket", daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            with conn:
                try:
                    data = b""
                    while not data.endswith(b"\n"):
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                    req = json.loads(data.decode() or "{}")
                    prefix = req.pop("prefix", "help")
                    reply = self.execute(prefix, req)
                    conn.sendall(json.dumps(reply).encode() + b"\n")
                except Exception:
                    pass

    def shutdown(self) -> None:
        self._stopping = True
        if self._server is not None:
            self._server.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if os.path.exists(self.path):
            os.unlink(self.path)


class AdminSocketClient:
    """The `ceph daemon <sock> <cmd>` side."""

    def __init__(self, path: str):
        self.path = path

    def do_request(self, prefix: str, **args):
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.connect(self.path)
            req = {"prefix": prefix}
            req.update(args)
            s.sendall(json.dumps(req).encode() + b"\n")
            data = b""
            while not data.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
        return json.loads(data.decode())
