"""End-to-end op tracing: the ZTracer/blkin analog.

Role of the reference's ZTracer::Trace + blkin integration
(src/common/zipkin_trace.h; spans threaded through the EC write path at
ECBackend.cc:1978-1983, one child span per shard) plus the
TracepointProvider config gating (src/common/TracepointProvider.h:
tracing is zero-cost until an option turns it on).

Pieces:

  Span           one named monotonic-clock interval with parent/child
                 links, keyval annotations and point events.  trace_id
                 ties spans of ONE logical op together across daemons;
                 (trace_id, parent_span) ride message envelopes so the
                 receiving daemon's spans stitch under the sender's.
  NULL_SPAN      the shared no-op span: the disabled-tracing fast path
                 (instrumented code pays one truthiness check).
  SpanCollector  per-daemon bounded span ring, config-gated on
                 `osd_tracing` with an `osd_tracing_sample` 1-in-N knob
                 for hot paths; serves `dump_tracing` / `trace reset`
                 over the admin socket.
  TailSampler    tail-based retention (Dapper/Canopy discipline): the
                 keep/drop call moves to op COMPLETION on the root
                 daemon — SLO-slow, errored, or reservoir-sampled
                 traces ship to the mgr trace store; replicas buffer
                 fragments until the verdict and dropped traces cost
                 zero wire bytes.
  trace_ctx      (trace_id, parent_span_id) for a message envelope.
  device_segments  the one device-call shape everyone shares: run a
                 codec call split into h2d / compute / d2h segments
                 (TpuDispatcher device spans and bench.py --trace both
                 ride it, so the bench breakdown and the production
                 spans measure the same thing).
  render_tree    the `ceph trace tree` renderer: stitched cross-daemon
                 span tree with per-span self-times.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque

import numpy as np

__all__ = ["Span", "NULL_SPAN", "SpanCollector", "TailSampler",
           "parse_slo_targets", "trace_ctx", "wire_span",
           "device_segments", "render_tree"]

# span ids must be unique ACROSS daemons for one trace (shards' spans
# from different OSDs land in one tree): a per-process random high part
# over a process-local counter keeps multi-process traces collision-free
_ids = itertools.count(1)
_ID_BASE = (int.from_bytes(os.urandom(3), "big") | 1) << 40


def _next_id() -> int:
    return _ID_BASE | next(_ids)


class Span:
    """One span: a named interval with keyvals, events and lineage."""

    __slots__ = ("collector", "name", "endpoint", "trace_id", "span_id",
                 "parent_id", "start", "start_wall", "end", "keyvals",
                 "events")

    def __init__(self, collector, name, endpoint="", trace_id=None,
                 parent_id=None):
        self.collector = collector
        self.name = name
        self.endpoint = endpoint
        self.span_id = _next_id()
        self.trace_id = trace_id if trace_id else self.span_id
        self.parent_id = parent_id
        self.start = time.monotonic()
        self.start_wall = time.time()
        self.end: float | None = None
        self.keyvals: dict = {}
        self.events: list[tuple[float, str]] = []

    def valid(self) -> bool:
        return True

    def child(self, name: str) -> "Span":
        return Span(self.collector, name, self.endpoint,
                    trace_id=self.trace_id, parent_id=self.span_id)

    def child_interval(self, name: str, start: float, end: float,
                       **keyvals) -> "Span":
        """Record an already-measured interval as a finished child
        (monotonic stamps) — how the dispatcher back-fills queue-delay
        and device-segment spans it could only time, not wrap."""
        s = self.child(name)
        s.start_wall = s.start_wall - (s.start - start)
        s.start = start
        s.keyvals.update(keyvals)
        s.end = end
        s.collector._record(s)
        return s

    def keyval(self, key: str, value) -> None:
        self.keyvals[key] = value

    def event(self, name: str) -> None:
        self.events.append((time.monotonic(), name))

    def finish(self) -> None:
        if self.end is None:
            self.end = time.monotonic()
            self.collector._record(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def dump(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "endpoint": self.endpoint, "start": self.start,
                "start_wall": self.start_wall,
                "duration": (self.end if self.end is not None
                             else time.monotonic()) - self.start,
                "keyvals": dict(self.keyvals),
                "events": list(self.events)}

    def dump_wire(self) -> list:
        """Compact fixed-order form for MTraceFragment payloads (see
        wire_span): a fragment carries dozens of spans, and encoding
        ten string keys per span would dominate the shipping cost.
        trace_id and start_wall are omitted — the fragment envelope
        carries the trace_id and the (anchor_wall, anchor_mono) pair
        that re-anchors `start`."""
        return [self.span_id, self.parent_id, self.name,
                self.endpoint, self.start,
                (self.end if self.end is not None
                 else time.monotonic()) - self.start,
                dict(self.keyvals), list(self.events)]


class _NullSpan:
    """Shared no-op span: the disabled-tracing fast path."""

    __slots__ = ()
    trace_id = 0
    span_id = 0

    def valid(self) -> bool:
        return False

    def child(self, name: str) -> "_NullSpan":
        return self

    def child_interval(self, name, start, end, **kv) -> "_NullSpan":
        return self

    def keyval(self, key: str, value) -> None:
        pass

    def event(self, name: str) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NULL_SPAN = _NullSpan()


def trace_ctx(span) -> tuple[int, int]:
    """(trace_id, parent_span_id) for a message envelope; (0, 0) rides
    when tracing is off, and a receiver seeing trace_id 0 stays null."""
    return (span.trace_id, span.span_id)


def wire_span(rec, trace_id: int) -> dict:
    """Expand one Span.dump_wire record back into the dict form the
    stores and render_tree consume."""
    return {"trace_id": trace_id, "span_id": rec[0],
            "parent_id": rec[1], "name": rec[2], "endpoint": rec[3],
            "start": rec[4], "duration": rec[5],
            "keyvals": rec[6], "events": rec[7]}


def parse_slo_targets(raw: str) -> dict:
    """'pool:latency_ms:objective,...' -> {pool: (threshold_s,
    objective)}; malformed entries are skipped, never fatal.  Shared
    by the mgr SLO evaluator and the OSD tail sampler so both judge
    "slow" against the identical per-pool threshold."""
    out = {}
    for entry in (raw or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.rsplit(":", 2)
        if len(parts) != 3:
            continue
        pool, lat_ms, objective = parts
        try:
            lat_s = float(lat_ms) / 1e3
            obj = float(objective)
        except ValueError:
            continue
        if not pool or lat_s <= 0 or not 0.0 < obj < 1.0:
            continue
        out[pool] = (lat_s, obj)
    return out


class SpanCollector:
    """Per-daemon bounded span store, `osd_tracing`-gated.

    Pass a Config to have enablement + the sampling knob follow
    `osd_tracing` / `osd_tracing_sample` (hot-toggling included via the
    config observer); without one, toggle `.enabled` directly.
    """

    def __init__(self, capacity: int = 8192, conf=None,
                 endpoint: str = ""):
        self.endpoint = endpoint
        self.enabled = False
        self.sample = 1
        self._sample_ctr = itertools.count()
        self._lock = threading.Lock()
        if conf is not None:
            try:
                capacity = int(conf.get_val("osd_tracing_max_spans"))
                self.enabled = bool(conf.get_val("osd_tracing"))
                self.sample = max(1, int(
                    conf.get_val("osd_tracing_sample")))
            except KeyError:
                pass  # options not in the schema: stay disabled
            else:
                collector = self

                class _Obs:  # md_config_obs_t contract
                    def get_tracked_keys(self):
                        return ("osd_tracing", "osd_tracing_sample")

                    def handle_conf_change(self, cfg, changed):
                        collector.enabled = bool(
                            cfg.get_val("osd_tracing"))
                        collector.sample = max(1, int(
                            cfg.get_val("osd_tracing_sample")))

                conf.add_observer(_Obs())
        self.capacity = capacity
        self._spans: deque[Span] = deque(maxlen=capacity)
        #: optional TailSampler: every recorded span is offered to it
        #: so replicas can buffer fragments pending the root's verdict
        self.tail = None

    # -- span minting --------------------------------------------------

    def start_trace(self, name: str, endpoint: str | None = None):
        """Root span (sampling applies here), or NULL_SPAN."""
        if not self.enabled:
            return NULL_SPAN
        if self.sample > 1 and next(self._sample_ctr) % self.sample:
            return NULL_SPAN
        return Span(self, name,
                    self.endpoint if endpoint is None else endpoint)

    def continue_trace(self, name: str, trace_id: int, parent_id: int,
                       endpoint: str | None = None):
        """Stitch onto a trace context from a message envelope; the
        sampling decision was the root's — a nonzero trace_id means the
        originator chose to trace this op."""
        if not self.enabled or not trace_id:
            return NULL_SPAN
        return Span(self, name,
                    self.endpoint if endpoint is None else endpoint,
                    trace_id=trace_id, parent_id=parent_id or None)

    # -- storage -------------------------------------------------------

    def _record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        tail = self.tail
        if tail is not None:
            tail.observe(span)

    def dump(self, trace_id: int | None = None) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        return [s.dump() for s in spans
                if trace_id is None or s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- admin socket surface ------------------------------------------

    def register_admin_commands(self, asok) -> None:
        def _dump(args: dict) -> dict:
            tid = args.get("trace_id") or args.get("key")
            tid = int(tid, 0) if isinstance(tid, str) else tid
            spans = self.dump(tid)
            return {"enabled": self.enabled, "sample": self.sample,
                    "num_spans": len(spans), "spans": spans}

        asok.register("dump_tracing", _dump,
                      "dump collected op spans (optional trace_id)")
        asok.register("trace reset",
                      lambda args: (self.clear(), {"reset": True})[1],
                      "drop all collected spans")


class TailSampler:
    """Tail-based trace retention: the keep/drop call at op COMPLETION.

    Two roles share one object per daemon:

      root side     `verdict(pool, duration, result, spans)` decides
                    keep/drop once the op's wall latency and result are
                    known — keep if latency exceeds the pool's SLO
                    threshold (`mgr_slo_pool_targets`, the same string
                    the mgr burns against), if the op errored or any
                    span logged an error event, or by a reservoir draw
                    (`osd_trace_tail_sample_rate`).
      replica side  `observe(span)` (fed by SpanCollector._record via
                    `.tail`) buffers finished span fragments keyed by
                    trace_id; `take(trace_id)` pops them when the
                    root's verdict arrives; fragments whose verdict
                    never comes expire after `osd_trace_pending_ttl`
                    seconds — a dropped trace costs zero wire bytes.

    The RNG is injectable so reservoir statistics are testable on a
    seeded stream.  The pending buffer is bounded (drop-oldest).
    """

    def __init__(self, conf=None, rng=None, max_pending: int = 4096):
        self._lock = threading.Lock()
        self.rng = rng if rng is not None else random.Random()
        self.rate = 0.0
        self.pending_ttl = 5.0
        self.slo_targets: dict = {}
        self.max_pending = max_pending
        self._pending: dict[int, tuple[float, list]] = {}
        self._last_sweep = time.monotonic()
        self.stats = {"kept_slo": 0, "kept_error": 0,
                      "kept_reservoir": 0, "dropped": 0,
                      "pending_expired": 0, "pending_overflow": 0}
        self.pool_stats: dict[str, dict] = {}
        if conf is not None:
            try:
                self.rate = float(
                    conf.get_val("osd_trace_tail_sample_rate"))
                self.pending_ttl = float(
                    conf.get_val("osd_trace_pending_ttl"))
                self.slo_targets = parse_slo_targets(
                    conf.get_val("mgr_slo_pool_targets"))
            except KeyError:
                pass  # options not in the schema: defaults stand
            else:
                sampler = self

                class _Obs:  # md_config_obs_t contract
                    def get_tracked_keys(self):
                        return ("osd_trace_tail_sample_rate",
                                "osd_trace_pending_ttl",
                                "mgr_slo_pool_targets")

                    def handle_conf_change(self, cfg, changed):
                        sampler.rate = float(
                            cfg.get_val("osd_trace_tail_sample_rate"))
                        sampler.pending_ttl = float(
                            cfg.get_val("osd_trace_pending_ttl"))
                        sampler.slo_targets = parse_slo_targets(
                            cfg.get_val("mgr_slo_pool_targets"))

                conf.add_observer(_Obs())

    # -- root side: the keep/drop call ---------------------------------

    def verdict(self, pool: str, duration: float, result,
                spans=None) -> tuple[bool, str]:
        """(keep, reason) for a completed root op; reason one of
        "slo" | "error" | "reservoir" | ""."""
        keep, reason = False, ""
        tgt = self.slo_targets.get(pool)
        if tgt is not None and duration > tgt[0]:
            keep, reason = True, "slo"
        elif (result is not None and result < 0) or \
                self._has_error_event(spans):
            keep, reason = True, "error"
        elif self.rate > 0.0 and self.rng.random() < self.rate:
            keep, reason = True, "reservoir"
        ps = self.pool_stats.setdefault(
            pool, {"seen": 0, "kept": 0})
        ps["seen"] += 1
        if keep:
            ps["kept"] += 1
            self.stats["kept_" + reason] += 1
        else:
            self.stats["dropped"] += 1
        return keep, reason

    @staticmethod
    def _has_error_event(spans) -> bool:
        for s in spans or ():
            events = s[7] if isinstance(s, (list, tuple)) \
                else s.get("events")
            for _, name in (events or ()):
                if str(name).startswith("error"):
                    return True
        return False

    # -- replica side: pending fragments -------------------------------

    def observe(self, span) -> None:
        """Buffer a finished span under its trace_id until the root's
        verdict arrives (or the TTL reaps it) — in the compact
        dump_wire form, ready to ship without another conversion."""
        if not span.trace_id:
            return
        now = time.monotonic()
        with self._lock:
            entry = self._pending.get(span.trace_id)
            if entry is None:
                if len(self._pending) >= self.max_pending:
                    oldest = min(self._pending,
                                 key=lambda t: self._pending[t][0])
                    del self._pending[oldest]
                    self.stats["pending_overflow"] += 1
                entry = self._pending[span.trace_id] = (now, [])
            entry[1].append(span.dump_wire())
        self._maybe_sweep(now)

    def take(self, trace_id: int):
        """Pop and return a trace's buffered span dumps (None if the
        TTL already reaped them or nothing was traced here)."""
        with self._lock:
            entry = self._pending.pop(trace_id, None)
        return entry[1] if entry is not None else None

    def pending_traces(self) -> int:
        with self._lock:
            return len(self._pending)

    def sweep(self, now: float | None = None) -> int:
        """Reap pending fragments older than the TTL (the root died or
        judged drop — drops send nothing).  Returns traces reaped."""
        now = time.monotonic() if now is None else now
        with self._lock:
            dead = [tid for tid, (t0, _) in self._pending.items()
                    if now - t0 > self.pending_ttl]
            for tid in dead:
                del self._pending[tid]
            self.stats["pending_expired"] += len(dead)
        return len(dead)

    def _maybe_sweep(self, now: float) -> None:
        # opportunistic, timer-free: at most ~1 sweep/second, driven
        # by whatever traffic flows through observe()
        if now - self._last_sweep >= 1.0:
            self._last_sweep = now
            self.sweep(now)


# -- shared device-call segmentation -----------------------------------

def device_segments(fn, batch):
    """Run fn(batch) as an explicit h2d -> compute -> d2h sequence and
    time each leg.  Returns (host ndarray result, {"h2d", "compute",
    "d2h"} seconds).  The TpuDispatcher's device spans and bench.py
    --trace both use this, so the artifact breakdown and production
    spans measure the identical call shape.  Falls back to one
    unsegmented call (all time under "compute") when jax is absent."""
    t0 = time.perf_counter()
    try:
        import jax
        import jax.numpy as jnp
    except Exception:
        out = fn(batch)
        out = out if isinstance(out, dict) else np.asarray(out)
        return out, {"h2d": 0.0, "compute": time.perf_counter() - t0,
                     "d2h": 0.0}
    dev = jax.block_until_ready(jnp.asarray(batch))
    t1 = time.perf_counter()
    out_dev = jax.block_until_ready(fn(dev))
    t2 = time.perf_counter()
    # fused programs return an output dict; drain it in ONE device_get
    out = jax.device_get(out_dev) if isinstance(out_dev, dict) \
        else np.asarray(out_dev)
    t3 = time.perf_counter()
    return out, {"h2d": t1 - t0, "compute": t2 - t1, "d2h": t3 - t2}


# -- tree rendering (the `ceph trace tree` surface) --------------------

def _fmt_dur(seconds: float) -> str:
    if seconds >= 1.0:
        return "%.3fs" % seconds
    if seconds >= 1e-3:
        return "%.2fms" % (seconds * 1e3)
    return "%.0fus" % (seconds * 1e6)


def render_tree(spans: list[dict], trace_id: int | None = None) -> str:
    """Render stitched spans (possibly gathered from several daemons'
    dump_tracing) as an indented tree with self-times.  Spans whose
    parent is not in the set render as roots — a partial gather still
    produces a readable forest.  Siblings sort by wall stamp (the
    anchor-aligned "wall" when the mgr stitched them, start_wall
    otherwise) — monotonic clocks don't compare across processes."""
    if trace_id is not None:
        spans = [s for s in spans if s.get("trace_id") == trace_id]
    if not spans:
        return "(no spans)"
    by_id = {s["span_id"]: s for s in spans}
    children: dict = {}
    roots: list = []
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)

    def order(kids: list) -> list:
        # sort siblings uniformly by the wall axis: "wall" is the
        # anchor-aligned stamp the mgr stitcher computes per fragment,
        # start_wall the span's own time.time() fallback.  Monotonic
        # `start` never orders spans across processes, and mixing the
        # two keys (the old endpoint-count special case) mis-ordered
        # same-endpoint siblings whenever a cross-daemon sibling sat
        # beside them.
        return sorted(kids, key=lambda s: (
            s.get("wall", s.get("start_wall", 0.0)),
            s.get("start", 0.0)))

    lines: list[str] = []
    traces = sorted({s.get("trace_id") for s in spans})
    endpoints = sorted({s.get("endpoint", "") for s in spans})
    lines.append("trace%s %s  (%d spans, %d endpoint(s): %s)"
                 % ("s" if len(traces) > 1 else "",
                    ", ".join(str(t) for t in traces), len(spans),
                    len(endpoints), ", ".join(e or "?"
                                              for e in endpoints)))

    def walk(s: dict, depth: int) -> None:
        kids = order(children.get(s["span_id"], []))
        dur = s.get("duration", 0.0)
        self_t = max(0.0, dur - sum(k.get("duration", 0.0)
                                    for k in kids))
        kv = s.get("keyvals") or {}
        kv_txt = ("  {%s}" % ", ".join(
            "%s=%s" % (k, v) for k, v in sorted(kv.items()))) if kv \
            else ""
        lines.append("%s%s @%s  %s (self %s)%s"
                     % ("  " * depth + ("- " if depth else ""),
                        s["name"], s.get("endpoint") or "?",
                        _fmt_dur(dur), _fmt_dur(self_t), kv_txt))
        for k in kids:
            walk(k, depth + 1)

    for root in order(roots):
        walk(root, 1)
    return "\n".join(lines)
