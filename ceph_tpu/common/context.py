"""Process context: one object wiring config, log, counters, watchdog.

Role of the reference's CephContext (src/common/ceph_context.{h,cc}):
every daemon/tool holds one context carrying its config, logger, perf
counter collection, heartbeat map, and (optionally) an admin socket —
created by global_init (src/global/global_init.cc), which also preloads
erasure-code plugins (global_init_preload_erasure_code, :484-519).
"""

from __future__ import annotations

from .admin_socket import AdminSocket
from .config import Config
from .heartbeat_map import HeartbeatMap
from .log import Log
from .perf_counters import PerfCountersCollection

__all__ = ["Context", "global_init"]


class Context:
    def __init__(self, overrides: dict | None = None, name: str = "ctx"):
        self.name = name
        self.conf = Config(overrides)
        self.log = Log(self.conf)
        self.perf = PerfCountersCollection()
        self.hbmap = HeartbeatMap(name + "-hb")
        self.admin_socket: AdminSocket | None = None

    def dout(self, subsys: str, level: int, msg: str) -> None:
        self.log.dout(subsys, level, msg)

    def derr(self, subsys: str, msg: str) -> None:
        self.log.derr(subsys, msg)

    def init_admin_socket(self, path: str) -> AdminSocket:
        sock = AdminSocket(path)
        sock.register("perf dump", lambda args: self.perf.perf_dump(),
                      "dump perf counters")
        sock.register("perf schema",
                      lambda args: self.perf.perf_schema(),
                      "counter kinds + histogram bucket bounds")
        sock.register("perf reset",
                      lambda args: {"reset": self.perf.perf_reset(
                          args.get("key") or args.get("logger"))},
                      "zero perf counters (optionally one logger)")
        sock.register("config get",
                      lambda args: {args["key"]:
                                    self.conf.get_val(args["key"])},
                      "get a config value")
        sock.register("config set", self._config_set, "set a config value")
        sock.register("config diff", lambda args: self.conf.diff(),
                      "options changed from default")
        sock.register("log dump", lambda args: self.log.dump_recent(),
                      "dump the recent-events ring")
        sock.register("health", lambda args: {
            "healthy": self.hbmap.is_healthy(),
            "unhealthy_workers": self.hbmap.unhealthy_workers()},
            "internal thread liveness")
        sock.init()
        self.admin_socket = sock
        return sock

    def _config_set(self, args: dict) -> dict:
        self.conf.set_val(args["key"], args["value"])
        changed = self.conf.apply_changes()
        return {"changed": sorted(changed)}

    def shutdown(self) -> None:
        if self.admin_socket is not None:
            self.admin_socket.shutdown()
            self.admin_socket = None


def global_init(overrides: dict | None = None, name: str = "ctx",
                preload_plugins: bool = True) -> Context:
    """Build a context and preload EC plugins like daemon start does."""
    ctx = Context(overrides, name)
    if preload_plugins:
        from .. import registry
        names = ctx.conf.get_val("osd_erasure_code_plugins").split()
        reg = registry.ErasureCodePluginRegistry.instance()
        for plugin in names:
            try:
                reg.load(plugin)
            except Exception as e:
                ctx.derr("ec", "failed to preload plugin %s: %s"
                         % (plugin, e))
    return ctx
