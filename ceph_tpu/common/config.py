"""Layered runtime configuration with observers.

Role of the reference's md_config_t (src/common/config.h:67): values
resolve default < file < env < argv < runtime set_val; set_val stages
changes and apply_changes() delivers them to registered observers
(md_config_obs_t, src/common/config_obs.h) under a lock, each observer
naming the keys it tracks — the mechanism TracepointProvider uses to
hot-enable tracing and the OSD uses for runtime tuning.
"""

from __future__ import annotations

import threading

from . import options as options_mod

__all__ = ["Config", "ConfigObserver"]


class ConfigObserver:
    """Observer contract (md_config_obs_t)."""

    def get_tracked_keys(self) -> tuple:
        return ()

    def handle_conf_change(self, conf: "Config", changed: set) -> None:
        pass


class Config:
    def __init__(self, overrides: dict | None = None):
        self._lock = threading.RLock()
        self._values: dict[str, object] = {}
        self._staged: dict[str, object] = {}
        self._observers: list[ConfigObserver] = []
        if overrides:
            for k, v in overrides.items():
                self.set_val(k, v)
            self.apply_changes()

    # -- reads ---------------------------------------------------------

    def get_val(self, name: str):
        with self._lock:
            if name in self._values:
                return self._values[name]
        opt = options_mod.SCHEMA.get(name)
        if opt is None:
            raise KeyError("unknown config option %r" % name)
        return opt.default

    def __getattr__(self, name: str):
        # conf.osd_heartbeat_interval sugar, like g_conf->name access
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self.get_val(name)
        except KeyError:
            raise AttributeError(name) from None

    # -- writes --------------------------------------------------------

    def set_val(self, name: str, value) -> None:
        """Stage a change; visible after apply_changes (config.h:117+)."""
        opt = options_mod.SCHEMA.get(name)
        if opt is None:
            raise KeyError("unknown config option %r" % name)
        with self._lock:
            self._staged[name] = opt.cast(value)

    def set_val_or_die(self, name: str, value) -> None:
        self.set_val(name, value)

    def apply_changes(self) -> set:
        with self._lock:
            changed = {k for k, v in self._staged.items()
                       if self._values.get(
                           k, options_mod.SCHEMA[k].default) != v}
            self._values.update(self._staged)
            self._staged.clear()
            observers = list(self._observers)
        for obs in observers:
            keys = set(obs.get_tracked_keys())
            hits = changed & keys if keys else set()
            if hits:
                obs.handle_conf_change(self, hits)
        return changed

    # -- observers -----------------------------------------------------

    def add_observer(self, obs: ConfigObserver) -> None:
        with self._lock:
            self._observers.append(obs)

    def remove_observer(self, obs: ConfigObserver) -> None:
        with self._lock:
            self._observers.remove(obs)

    # -- introspection (admin socket "config get/set/diff") ------------

    def dump(self) -> dict:
        with self._lock:
            out = {name: opt.default for name, opt in
                   options_mod.SCHEMA.items()}
            out.update(self._values)
            return out

    def diff(self) -> dict:
        with self._lock:
            return {k: v for k, v in self._values.items()
                    if v != options_mod.SCHEMA[k].default}
