"""Execution substrate: thread pools, sharded queues, finisher, timer.

Role of the reference's src/common/WorkQueue.h (ThreadPool,
ShardedThreadPool), Finisher, and SafeTimer:

  ThreadPool         N workers draining one queue
  ShardedThreadPool  work hashed to a fixed shard -> per-shard ordering
                     with cross-shard parallelism — the OSD's op
                     scheduling shape (ShardedOpWQ, src/osd/OSD.h:1623)
  Finisher           a dedicated completion-callback thread so IO paths
                     never run arbitrary callbacks inline
  SafeTimer          cancellable scheduled callbacks sharing one thread

All integrate with HeartbeatMap so a wedged worker is detectable.
"""

from __future__ import annotations

import heapq
import itertools
import queue
import threading
import time

__all__ = ["ThreadPool", "ShardedThreadPool", "Finisher", "SafeTimer"]

_SHUTDOWN = object()


class ThreadPool:
    def __init__(self, name: str, num_threads: int, hbmap=None,
                 grace: float = 30.0):
        self.name = name
        self._q: queue.Queue = queue.Queue()
        self._threads = []
        self._hbmap = hbmap
        self._grace = grace
        self._started = False
        self._num = num_threads

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self._num):
            t = threading.Thread(target=self._worker,
                                 name="%s-%d" % (self.name, i), daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self) -> None:
        handle = self._hbmap.add(threading.current_thread().name,
                                 self._grace) if self._hbmap else None
        while True:
            if handle:
                handle.renew()
            try:
                item = self._q.get(timeout=1.0)
            except queue.Empty:
                continue
            if item is _SHUTDOWN:
                break
            fn, args = item
            try:
                fn(*args)
            except Exception:
                import traceback
                traceback.print_exc()
        if handle:
            handle.remove()

    def queue(self, fn, *args) -> None:
        self._q.put((fn, args))

    def drain(self) -> None:
        while not self._q.empty():
            time.sleep(0.001)

    def stop(self) -> None:
        for _ in self._threads:
            self._q.put(_SHUTDOWN)
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        self._started = False


class ShardedThreadPool:
    """Work keyed by hashable -> stable shard; one worker per shard keeps
    per-key ordering (a PG's ops execute in order) while different keys
    run concurrently — the ShardedOpWQ contract."""

    def __init__(self, name: str, num_shards: int, hbmap=None):
        self.name = name
        self.num_shards = num_shards
        self._shards = [ThreadPool("%s-s%d" % (name, i), 1, hbmap)
                        for i in range(num_shards)]

    def start(self) -> None:
        for s in self._shards:
            s.start()

    def queue(self, key, fn, *args, **qos) -> None:
        # qos kwargs (klass/priority/cost) are accepted for signature
        # parity with QosShardedOpWQ; FIFO ignores them
        self._shards[hash(key) % self.num_shards].queue(fn, *args)

    def drain(self) -> None:
        for s in self._shards:
            s.drain()

    def stop(self) -> None:
        for s in self._shards:
            s.stop()


class Finisher:
    """Completion-callback thread (src/common/Finisher.h)."""

    def __init__(self, name: str = "finisher"):
        self._pool = ThreadPool(name, 1)

    def start(self) -> None:
        self._pool.start()

    def queue(self, fn, *args) -> None:
        self._pool.queue(fn, *args)

    def wait_for_empty(self) -> None:
        self._pool.drain()

    def stop(self) -> None:
        self._pool.stop()


class SafeTimer:
    """Cancellable timer events on one thread (src/common/Timer.h)."""

    def __init__(self, name: str = "safe-timer"):
        self.name = name
        self._heap: list = []
        self._counter = itertools.count()
        self._cond = threading.Condition()
        self._cancelled: set[int] = set()
        self._thread = None
        self._stopping = False

    def init(self) -> None:
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()

    def add_event_after(self, seconds: float, fn, *args) -> int:
        with self._cond:
            token = next(self._counter)
            heapq.heappush(self._heap,
                           (time.monotonic() + seconds, token, fn, args))
            self._cond.notify()
            return token

    def cancel_event(self, token: int) -> None:
        with self._cond:
            self._cancelled.add(token)
            self._cond.notify()

    def cancel_all_events(self) -> None:
        with self._cond:
            self._cancelled.update(t for _, t, _, _ in self._heap)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopping and (
                        not self._heap
                        or self._heap[0][0] > time.monotonic()):
                    delay = None if not self._heap else \
                        max(0.0, self._heap[0][0] - time.monotonic())
                    self._cond.wait(delay)
                if self._stopping:
                    return
                when, token, fn, args = heapq.heappop(self._heap)
                if token in self._cancelled:
                    self._cancelled.discard(token)
                    continue
            try:
                fn(*args)
            except Exception:
                import traceback
                traceback.print_exc()

    def shutdown(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify()
        if self._thread:
            self._thread.join(timeout=5)
