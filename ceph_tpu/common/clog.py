"""Cluster-log client channel.

Role of the reference's LogClient/LogChannel (src/common/LogClient.h,
the `clog` member every daemon logs operator-facing events through,
e.g. ECBackend.cc:999's shard-read-error clog): a daemon-side channel
that stamps entries and ships them to the monitor quorum as MLog
messages.  The LogMonitor replicates them via paxos; `ceph log last`
reads them back.

Entries are fire-and-forget over the lossless messenger connections,
broadcast to every monitor (peons forward to the leader, so a dead
mon — even the old leader — never loses the event); (name, seq)
dedups at the LogMonitor, so the fan-out can never duplicate a line.
"""

from __future__ import annotations

import itertools
import threading
import time

from ..msg.message import MLog

__all__ = ["ClogChannel", "PRIO_DEBUG", "PRIO_INFO", "PRIO_WARN",
           "PRIO_ERROR"]

PRIO_DEBUG = "DBG"
PRIO_INFO = "INF"
PRIO_WARN = "WRN"
PRIO_ERROR = "ERR"


class ClogChannel:
    def __init__(self, msgr, monmap: dict, name: str,
                 channel: str = "cluster"):
        self.msgr = msgr
        self.monmap = dict(monmap)
        self.name = name              # "osd.3" etc.
        self.channel = channel
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        # local ring of what this daemon said (crash forensics even if
        # the mon never saw it)
        self.recent: list[dict] = []
        self.RECENT_MAX = 100

    def _submit(self, prio: str, message: str) -> dict:
        entry = {"seq": next(self._seq), "stamp": time.time(),
                 "name": self.name, "channel": self.channel,
                 "prio": prio, "message": message}
        with self._lock:
            self.recent.append(entry)
            del self.recent[:-self.RECENT_MAX]
        msg = MLog(entries=[entry])
        for rank in sorted(self.monmap):
            try:
                self.msgr.send_message(msg, self.monmap[rank])
            except Exception:
                pass   # the clog must never take the data path down
        return entry

    def debug(self, message: str) -> dict:
        return self._submit(PRIO_DEBUG, message)

    def info(self, message: str) -> dict:
        return self._submit(PRIO_INFO, message)

    def warn(self, message: str) -> dict:
        return self._submit(PRIO_WARN, message)

    def error(self, message: str) -> dict:
        return self._submit(PRIO_ERROR, message)
