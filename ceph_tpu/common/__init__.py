"""Core runtime: the framework's equivalent of Ceph's src/common layer.

Components (reference citations in each module):

  buffer         segmented buffers (bufferlist, src/include/buffer.h)
  options        typed option schema (src/common/options.cc)
  config         layered config w/ observers (src/common/config.{h,cc})
  perf_counters  metrics registry (src/common/perf_counters.{h,cc})
  log            leveled in-memory-ring logger (src/log/, src/common/debug.h)
  throttle       backpressure primitives (src/common/Throttle.{h,cc})
  workqueue      thread pools, finisher, timer (src/common/WorkQueue.h)
  heartbeat_map  thread-liveness watchdog (src/common/HeartbeatMap.{h,cc})
  admin_socket   per-daemon command server (src/common/admin_socket.{h,cc})
  context        CephContext analog wiring the above together
"""

from .context import Context

__all__ = ["Context"]
