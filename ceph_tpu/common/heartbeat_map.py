"""Thread-liveness watchdog.

Role of the reference's HeartbeatMap (src/common/HeartbeatMap.{h,cc}):
worker threads hold a handle and renew a lease before each unit of work;
is_healthy() reports any thread whose lease expired (wedged on IO, a
lock, or a device). Daemons answer internal liveness probes with this,
so one stuck worker turns into a visible health failure instead of a
silent stall — the same signal the suicide_grace kill path uses.
"""

from __future__ import annotations

import threading
import time

__all__ = ["HeartbeatMap"]


class _Handle:
    __slots__ = ("hbmap", "name", "grace", "suicide_grace", "deadline",
                 "suicide_deadline")

    def __init__(self, hbmap, name, grace, suicide_grace):
        self.hbmap = hbmap
        self.name = name
        self.grace = grace
        self.suicide_grace = suicide_grace
        self.deadline = 0.0          # 0 = not currently on the clock
        self.suicide_deadline = 0.0

    def renew(self) -> None:
        now = time.monotonic()
        self.deadline = now + self.grace
        self.suicide_deadline = now + self.suicide_grace \
            if self.suicide_grace else 0.0

    def clear(self) -> None:
        """Off the clock (blocked intentionally, e.g. idle wait)."""
        self.deadline = 0.0
        self.suicide_deadline = 0.0

    def remove(self) -> None:
        self.hbmap.remove(self)


class HeartbeatMap:
    def __init__(self, name: str = "hbmap"):
        self.name = name
        self._lock = threading.Lock()
        self._handles: list[_Handle] = []

    def add(self, thread_name: str, grace: float,
            suicide_grace: float = 0.0) -> _Handle:
        h = _Handle(self, thread_name, grace, suicide_grace)
        h.renew()
        with self._lock:
            self._handles.append(h)
        return h

    def remove(self, handle: _Handle) -> None:
        with self._lock:
            if handle in self._handles:
                self._handles.remove(handle)

    def is_healthy(self) -> bool:
        return not self.unhealthy_workers()

    def unhealthy_workers(self) -> list[str]:
        now = time.monotonic()
        with self._lock:
            return [h.name for h in self._handles
                    if h.deadline and now > h.deadline]

    def check_touch(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {h.name: max(0.0, h.deadline - now) if h.deadline else None
                    for h in self._handles}
