"""Delta-encoded mgr telemetry: the sender half of the MMgrReport
delta protocol.

Role of the reference's DaemonServer/MgrClient session state
(/root/reference/src/mgr/MgrClient.cc): every reporting daemon used to
re-ship its FULL perf dump + FULL schema every mgr_stats_period, which
is O(counters) wire bytes per daemon per period — fine for a dozen
daemons, ruinous for thousands.  A `DeltaReporter` instead stamps each
report with a (incarnation, seq) identity plus a schema hash, and after
the first acknowledged full report ships only the counters whose values
changed since the last report the mgr ACKNOWLEDGED:

  sender                      mgr
    report seq=1 full+schema --->  ingest, remember (inc, 1)
    <---------------------- ack 1  promote snapshot 1 to delta base
    report seq=2 delta(base=1) ->  fold into state-as-of-1
    ...

The delta base is always an ACKED snapshot, so a lost report or lost
ack can only make the next delta a superset of what the mgr is missing
— never a gap.  The mgr requests a full resync (ack with resync=True)
on first contact, on a delta whose base it never ingested (seq gap
across a mgr restart), or on a schema-hash mismatch; the sender then
falls back to a full report + schema.  Old senders that never learned
the protocol keep shipping full reports with seq=0 and the mgr ingests
them unchanged — the appended MMgrReport fields default to exactly
that legacy shape.

Schema travels only on the first report and on hash change (for
gauges/counters the schema is immutable after construction, so in
steady state ZERO schema bytes ride the stream) — the hash rides every
report so the mgr can detect a stale schema without the payload.
"""

from __future__ import annotations

import hashlib
import itertools
import os

__all__ = ["DeltaReporter", "schema_hash", "perf_delta", "fold_delta",
           "approx_perf_bytes"]

_incarnation_salt = itertools.count(1)


def schema_hash(schema: dict) -> str:
    """Stable short hash of a perf schema ({group: {counter: {type,
    buckets?}}}) — equal schemas hash equal regardless of dict
    insertion order."""
    h = hashlib.sha1()
    for group in sorted(schema):
        h.update(group.encode())
        counters = schema[group]
        for name in sorted(counters):
            ent = counters[name]
            h.update(name.encode())
            h.update(repr(sorted(ent.items())
                          if isinstance(ent, dict) else ent).encode())
    return h.hexdigest()[:16]


def perf_delta(base: dict, perf: dict) -> dict:
    """Counters in `perf` whose values differ from `base` (group ->
    {counter: value}).  Equality is by value — avg dicts and histogram
    fill lists compare structurally, so an idle counter costs zero
    wire bytes."""
    out: dict = {}
    for group, counters in perf.items():
        bg = base.get(group)
        if bg is None:
            out[group] = counters
            continue
        changed = {c: v for c, v in counters.items() if bg.get(c) != v}
        if changed:
            out[group] = changed
    return out


def fold_delta(base: dict, delta: dict) -> dict:
    """Apply a `perf_delta` payload on top of a full perf state,
    returning a NEW dict (unchanged counter values are shared by
    reference with `base` — the delta stream's memory dividend)."""
    out = {g: dict(c) for g, c in base.items()}
    for group, counters in delta.items():
        out.setdefault(group, {}).update(counters)
    return out


def approx_perf_bytes(perf: dict) -> int:
    """Cheap size estimate of a perf payload (the aggregator's byte
    accounting and the ingest bytes/s counter both use it; exact wire
    bytes would mean encoding every report twice)."""
    n = 64
    for group, counters in perf.items():
        n += len(group) + 56
        for c, v in counters.items():
            n += len(c)
            if isinstance(v, dict):
                b = v.get("buckets")
                n += 96 + (8 * len(b) if b else 0)
            else:
                n += 48
    return n


class DeltaReporter:
    """Per-daemon sender state for the delta protocol.  NOT
    thread-safe on its own — each daemon calls prepare() from its one
    report loop and ack() from its dispatch thread, so the tiny
    critical sections are guarded by the caller being idempotent:
    ack() only ever advances/clears state."""

    def __init__(self, max_outstanding: int = 32):
        # incarnation distinguishes a restarted daemon reusing a name:
        # the mgr must never fold a new process's delta onto the old
        # process's state
        self.incarnation = "%s-%d" % (os.urandom(6).hex(),
                                      next(_incarnation_salt))
        self.seq = 0
        self.max_outstanding = max_outstanding
        self._acked_seq = -1
        self._acked_perf: dict | None = None      # the delta base
        self._acked_hash = ""
        self._outstanding: dict[int, tuple] = {}  # seq -> (perf, hash)
        self._sent_schema_hash = ""

    # -- sender side ---------------------------------------------------

    def prepare(self, perf: dict, schema: dict) -> dict:
        """Build the wire fields for one report: {'seq', 'incarnation',
        'schema_hash', 'delta_base', 'perf', 'schema'} where 'schema'
        is {} whenever the mgr already acked this schema hash and
        'perf' holds only changed counters whenever an acked base
        exists."""
        self.seq += 1
        h = schema_hash(schema)
        if self._acked_perf is not None and h == self._acked_hash:
            payload = perf_delta(self._acked_perf, perf)
            base = self._acked_seq
        else:
            payload = perf
            base = -1
        # schema rides only on first report / hash change (satellite:
        # the legacy full-report path stops re-shipping it every period
        # too); a lost schema heals through the mgr's resync request,
        # which clears _sent_schema_hash below
        send_schema = h != self._sent_schema_hash
        self._sent_schema_hash = h
        self._outstanding[self.seq] = (perf, h)
        while len(self._outstanding) > self.max_outstanding:
            self._outstanding.pop(min(self._outstanding))
        return {"seq": self.seq, "incarnation": self.incarnation,
                "schema_hash": h, "delta_base": base,
                "perf": payload, "schema": schema if send_schema else {}}

    def ack(self, seq: int, resync: bool = False) -> None:
        """Mgr acknowledged `seq`.  resync=True means the mgr wants a
        full report + schema next period (first contact, seq gap, or
        schema mismatch)."""
        if resync:
            self._acked_seq = -1
            self._acked_perf = None
            self._acked_hash = ""
            self._sent_schema_hash = ""
            return
        ent = self._outstanding.get(seq)
        if ent is None or seq <= self._acked_seq:
            return
        perf, h = ent
        self._acked_seq = seq
        self._acked_perf = perf
        self._acked_hash = h
        for s in [s for s in self._outstanding if s <= seq]:
            del self._outstanding[s]

    def status(self) -> dict:
        return {"incarnation": self.incarnation, "seq": self.seq,
                "acked_seq": self._acked_seq,
                "delta_capable": self._acked_perf is not None}
