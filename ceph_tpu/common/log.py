"""Subsystem-leveled logging with a crash ring.

Role of the reference's src/log/ + dout/derr (src/common/debug.h):
every entry carries (subsystem, level); entries at or below the
subsystem's configured level are emitted, and the most recent N entries
of ANY level are retained in a memory ring that dump_recent() flushes on
crash — the property that makes post-mortem debugging possible without
verbose steady-state logging. Config observers hot-reconfigure levels
(debug_<subsys> options).
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback

from .config import Config, ConfigObserver

__all__ = ["Log", "SUBSYS"]

SUBSYS = ("ec", "osd", "crush", "ms", "mon")


class Log(ConfigObserver):
    def __init__(self, conf: Config | None = None, sink=None):
        self._lock = threading.Lock()
        self.conf = conf
        self.sink = sink  # callable(str) or None -> stderr when enabled
        self.levels = {s: 1 for s in SUBSYS}
        self.max_recent = 500
        self.to_stderr = False
        self._recent = collections.deque(maxlen=self.max_recent)
        if conf is not None:
            for s in SUBSYS:
                self.levels[s] = conf.get_val("debug_" + s)
            self.max_recent = conf.get_val("log_max_recent")
            self.to_stderr = conf.get_val("log_to_stderr")
            self._recent = collections.deque(maxlen=self.max_recent)
            conf.add_observer(self)

    # -- config observer ----------------------------------------------

    def get_tracked_keys(self):
        return tuple("debug_" + s for s in SUBSYS) + (
            "log_max_recent", "log_to_stderr")

    def handle_conf_change(self, conf, changed):
        with self._lock:
            for key in changed:
                if key.startswith("debug_"):
                    self.levels[key[len("debug_"):]] = conf.get_val(key)
                elif key == "log_max_recent":
                    self.max_recent = conf.get_val(key)
                    self._recent = collections.deque(
                        self._recent, maxlen=self.max_recent)
                elif key == "log_to_stderr":
                    self.to_stderr = conf.get_val(key)

    # -- emit ----------------------------------------------------------

    def dout(self, subsys: str, level: int, msg: str) -> None:
        entry = (time.time(), subsys, level, msg)
        with self._lock:
            self._recent.append(entry)
            emit = level <= self.levels.get(subsys, 0)
        if emit:
            self._emit(entry)

    def derr(self, subsys: str, msg: str) -> None:
        self.dout(subsys, -1, msg)  # level -1 always emits

    def _emit(self, entry) -> None:
        ts, subsys, level, msg = entry
        line = "%.6f %s %2d : %s" % (ts, subsys, level, msg)
        if self.sink is not None:
            self.sink(line)
        elif self.to_stderr:
            print(line, file=sys.stderr)

    # -- crash ring ----------------------------------------------------

    def dump_recent(self, out=None) -> list[str]:
        """Flush the ring (the on-crash dump of src/log/Log.cc)."""
        with self._lock:
            entries = list(self._recent)
        lines = ["%.6f %s %2d : %s" % e for e in entries]
        if out is not None:
            out.write("--- begin dump of recent events ---\n")
            for line in lines:
                out.write(line + "\n")
            out.write("--- end dump of recent events ---\n")
        return lines

    def dump_on_exception(self, exc: BaseException) -> list[str]:
        lines = self.dump_recent()
        tb = "".join(traceback.format_exception(exc))
        if self.sink is not None:
            self.sink(tb)
        else:
            sys.stderr.write(tb)
        return lines
