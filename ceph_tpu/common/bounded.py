"""BoundedDict — insertion-ordered dict with size-capped eviction.

The dedup/replay caches (client-op replies, sub-op seen sets) all need
"record, bounded, oldest-out" semantics; one helper instead of three
inlined eviction loops."""

from __future__ import annotations

__all__ = ["BoundedDict"]


class BoundedDict(dict):
    def __init__(self, cap: int = 8192):
        super().__init__()
        self.cap = cap

    def __setitem__(self, key, value):
        # move-to-end on reassignment: eviction is then LRU-by-update,
        # not FIFO-by-first-insertion — a constantly-refreshed entry
        # (e.g. a hot object's atime) must never be the one evicted
        if key in self:
            super().__delitem__(key)
        super().__setitem__(key, value)
        while len(self) > self.cap:
            super().__delitem__(next(iter(self)))
