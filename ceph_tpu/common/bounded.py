"""BoundedDict — insertion-ordered dict with size-capped eviction.

The dedup/replay caches (client-op replies, sub-op seen sets) all need
"record, bounded, oldest-out" semantics; one helper instead of three
inlined eviction loops."""

from __future__ import annotations

__all__ = ["BoundedDict"]


class BoundedDict(dict):
    def __init__(self, cap: int = 8192):
        super().__init__()
        self.cap = cap

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        while len(self) > self.cap:
            super().__delitem__(next(iter(self)))
