"""Performance counters.

Role of the reference's PerfCounters (src/common/perf_counters.h:70):
each subsystem builds a named counter set (u64 counters, time sums,
averages with count+sum, histograms), registered in a per-context
collection and dumped as nested dicts by the admin socket's "perf dump".
A PerfCountersBuilder mirrors the add_u64_counter/add_time_avg/... API.
"""

from __future__ import annotations

import threading
import time

__all__ = ["PerfCounters", "PerfCountersBuilder", "PerfCountersCollection"]

U64 = "u64"
U64_COUNTER = "u64_counter"
TIME = "time"
TIME_AVG = "time_avg"
U64_AVG = "u64_avg"
HISTOGRAM = "histogram"

_HIST_BUCKETS = tuple(1 << i for i in range(1, 31))  # power-of-two buckets


class _Counter:
    __slots__ = ("kind", "value", "count", "buckets")

    def __init__(self, kind):
        self.kind = kind
        self.value = 0
        self.count = 0
        self.buckets = [0] * (len(_HIST_BUCKETS) + 1) \
            if kind == HISTOGRAM else None


class PerfCounters:
    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, _Counter] = {}

    def _add(self, name, kind):
        self._counters[name] = _Counter(kind)

    # -- update --------------------------------------------------------

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name].value += amount

    def dec(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name].value -= amount

    def set(self, name: str, value) -> None:
        with self._lock:
            self._counters[name].value = value

    def tinc(self, name: str, seconds: float) -> None:
        """Add a duration; averages also bump their sample count."""
        with self._lock:
            c = self._counters[name]
            c.value += seconds
            c.count += 1

    def hinc(self, name: str, sample: int) -> None:
        with self._lock:
            c = self._counters[name]
            c.count += 1
            c.value += sample
            for i, edge in enumerate(_HIST_BUCKETS):
                if sample <= edge:
                    c.buckets[i] += 1
                    break
            else:
                c.buckets[-1] += 1

    class _Timer:
        __slots__ = ("pc", "name", "t0")

        def __init__(self, pc, name):
            self.pc, self.name = pc, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.pc.tinc(self.name, time.perf_counter() - self.t0)

    def time(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    # -- read ----------------------------------------------------------

    def get(self, name: str):
        with self._lock:
            return self._counters[name].value

    def avg(self, name: str) -> float:
        with self._lock:
            c = self._counters[name]
            return c.value / c.count if c.count else 0.0

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for name, c in self._counters.items():
                if c.kind in (TIME_AVG, U64_AVG):
                    out[name] = {"avgcount": c.count, "sum": c.value}
                elif c.kind == HISTOGRAM:
                    out[name] = {"count": c.count, "sum": c.value,
                                 "buckets": list(c.buckets)}
                else:
                    out[name] = c.value
            return out

    def schema(self) -> dict:
        """Counter kinds + histogram bucket bounds (the 'perf schema'
        admin command payload; perf_counters.h's schema dump role —
        'perf dump' alone can't tell a gauge from a counter or name
        the bucket edges)."""
        with self._lock:
            out = {}
            for name, c in self._counters.items():
                entry: dict = {"type": c.kind}
                if c.kind == HISTOGRAM:
                    entry["buckets"] = list(_HIST_BUCKETS)
                out[name] = entry
            return out

    def reset(self) -> None:
        """Zero every counter (the 'perf reset' before/after-
        measurement surface)."""
        with self._lock:
            for c in self._counters.values():
                c.value = 0
                c.count = 0
                if c.buckets is not None:
                    c.buckets = [0] * len(c.buckets)


class PerfCountersBuilder:
    """add_* then create_perf_counters (perf_counters.h builder idiom)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64(self, name, desc=""):
        self._pc._add(name, U64)
        return self

    def add_u64_counter(self, name, desc=""):
        self._pc._add(name, U64_COUNTER)
        return self

    def add_u64_avg(self, name, desc=""):
        self._pc._add(name, U64_AVG)
        return self

    def add_time(self, name, desc=""):
        self._pc._add(name, TIME)
        return self

    def add_time_avg(self, name, desc=""):
        self._pc._add(name, TIME_AVG)
        return self

    def add_histogram(self, name, desc=""):
        self._pc._add(name, HISTOGRAM)
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    def __init__(self):
        self._lock = threading.Lock()
        self._loggers: dict[str, PerfCounters] = {}

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers[pc.name] = pc

    def remove(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers.pop(pc.name, None)

    def perf_dump(self) -> dict:
        with self._lock:
            return {name: pc.dump() for name, pc in self._loggers.items()}

    def perf_schema(self) -> dict:
        with self._lock:
            return {name: pc.schema()
                    for name, pc in self._loggers.items()}

    def perf_reset(self, logger: str | None = None) -> list[str]:
        """Reset one named logger, or every logger; returns the names
        that were reset."""
        with self._lock:
            targets = [pc for name, pc in self._loggers.items()
                       if logger is None or name == logger]
        for pc in targets:
            pc.reset()
        return sorted(pc.name for pc in targets)
