"""Slot reservations with priority preemption.

Role of the reference's AsyncReserver<T> (src/common/AsyncReserver.h):
a bounded set of concurrently-granted slots (osd_max_backfills /
osd_recovery_max_active), a priority-bucketed wait queue for everything
beyond the budget, and preemption — a request of strictly higher
priority evicts the lowest-priority current holder (its on_preempt
fires, it re-requests later) so degraded-object recovery is never
parked behind routine backfill.

Each OSD runs four of these (local/remote x recovery/backfill,
osd/osd_daemon.py); PGs are the items.  Grant/preempt callbacks run
OUTSIDE the reserver lock: a grant handler immediately requesting a
remote reservation (the PG reservation round-trip) must not deadlock.
"""

from __future__ import annotations

import threading

__all__ = ["AsyncReserver"]


class _Request:
    __slots__ = ("item", "prio", "on_grant", "on_preempt")

    def __init__(self, item, prio, on_grant, on_preempt):
        self.item = item
        self.prio = prio
        self.on_grant = on_grant
        self.on_preempt = on_preempt


class AsyncReserver:
    def __init__(self, name: str, max_allowed: int = 1):
        self.name = name
        self._max = max(0, int(max_allowed))
        self._lock = threading.Lock()
        self._queues: dict[int, list[_Request]] = {}  # prio -> FIFO
        self._granted: dict = {}                      # item -> _Request
        # lifetime counters for the observability riders
        # (l_osd_reservation_* perf lanes / dump_reservations asok)
        self.granted_total = 0
        self.preempted_total = 0

    # -- core ----------------------------------------------------------

    def request_reservation(self, item, on_grant, prio: int = 0,
                            on_preempt=None) -> None:
        """Queue a reservation; on_grant() fires (possibly immediately,
        on this thread) once a slot is held.  A duplicate request for a
        queued/granted item is ignored — the PG state machine re-enters
        its request path freely."""
        with self._lock:
            if item in self._granted:
                return
            for q in self._queues.values():
                if any(r.item == item for r in q):
                    return
            self._queues.setdefault(prio, []).append(
                _Request(item, prio, on_grant, on_preempt))
        self._do_queues()

    def cancel_reservation(self, item) -> bool:
        """Release a held slot or withdraw a queued request (both the
        completion and the interval-change path).  Returns True if the
        item was known."""
        found = False
        with self._lock:
            if self._granted.pop(item, None) is not None:
                found = True
            else:
                for prio, q in list(self._queues.items()):
                    keep = [r for r in q if r.item != item]
                    if len(keep) != len(q):
                        found = True
                        if keep:
                            self._queues[prio] = keep
                        else:
                            del self._queues[prio]
        if found:
            self._do_queues()
        return found

    def set_max(self, max_allowed: int) -> None:
        with self._lock:
            self._max = max(0, int(max_allowed))
        self._do_queues()

    def has_reservation(self, item) -> bool:
        with self._lock:
            return item in self._granted

    def _do_queues(self) -> None:
        """Grant free slots highest-priority-first; when none are free,
        preempt a strictly lower-priority holder (AsyncReserver
        do_queues + preempt_by_prio)."""
        grants: list[_Request] = []
        preempts: list[_Request] = []
        with self._lock:
            while True:
                prio = max(self._queues) if self._queues else None
                if prio is None:
                    break
                if len(self._granted) < self._max:
                    req = self._queues[prio].pop(0)
                    if not self._queues[prio]:
                        del self._queues[prio]
                    self._granted[req.item] = req
                    self.granted_total += 1
                    grants.append(req)
                    continue
                victim = min(self._granted.values(),
                             key=lambda r: r.prio) \
                    if self._granted else None
                if victim is None or victim.prio >= prio:
                    break          # nothing evictable: head waits
                del self._granted[victim.item]
                self.preempted_total += 1
                preempts.append(victim)
                # loop: the freed slot goes to the queue head
        for req in preempts:
            if req.on_preempt is not None:
                req.on_preempt()
        for req in grants:
            req.on_grant()

    # -- introspection -------------------------------------------------

    def num_granted(self) -> int:
        with self._lock:
            return len(self._granted)

    def num_waiting(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def dump(self) -> dict:
        """The `dump_reservations` asok payload for one reserver."""
        with self._lock:
            return {
                "max_allowed": self._max,
                "granted": [{"item": str(r.item), "prio": r.prio}
                            for r in self._granted.values()],
                "waiting": [{"item": str(r.item), "prio": r.prio}
                            for prio in sorted(self._queues,
                                               reverse=True)
                            for r in self._queues[prio]],
                "granted_total": self.granted_total,
                "preempted_total": self.preempted_total,
            }
