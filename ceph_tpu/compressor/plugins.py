"""Built-in compression algorithms.

Parity set with the reference's plugin dirs
(/root/reference/src/compressor/{zlib,snappy,zstd,lz4}/). zlib rides the
stdlib; zstd rides the `zstandard` package; snappy and lz4 depend on host
libraries that may be absent — their loaders raise ENOENT then, matching
a missing plugin .so in the reference.
"""

from __future__ import annotations

import errno as _errno
import zlib as _zlib

from .base import Compressor, CompressorError


class ZlibCompressor(Compressor):
    """Deflate (src/compressor/zlib/ZlibCompressor.cc); level matches the
    reference's compressor_zlib_level default of 5."""

    name = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return _zlib.compress(bytes(data), self.level)

    def decompress(self, data: bytes) -> bytes:
        try:
            return _zlib.decompress(bytes(data))
        except _zlib.error as e:
            raise CompressorError(_errno.EIO, "zlib decompress: %s" % e)


class ZstdCompressor(Compressor):
    """Zstandard (src/compressor/zstd/); level matches the reference's
    compressor_zstd_level default of 1."""

    name = "zstd"

    def __init__(self, level: int = 1):
        import zstandard
        self._mod = zstandard
        self.level = level
        # persistent contexts, like the reference plugin's zstd stream state
        self._cctx = zstandard.ZstdCompressor(level=level)
        self._dctx = zstandard.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._cctx.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        try:
            return self._dctx.decompress(bytes(data))
        except self._mod.ZstdError as e:
            raise CompressorError(_errno.EIO, "zstd decompress: %s" % e)


class SnappyCompressor(Compressor):
    name = "snappy"

    def __init__(self):
        import snappy
        self._mod = snappy

    def compress(self, data: bytes) -> bytes:
        return self._mod.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        try:
            return self._mod.decompress(bytes(data))
        except Exception as e:
            raise CompressorError(_errno.EIO, "snappy decompress: %s" % e)


class Lz4Compressor(Compressor):
    name = "lz4"

    def __init__(self):
        import lz4.block
        self._mod = lz4.block

    def compress(self, data: bytes) -> bytes:
        return self._mod.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        try:
            return self._mod.decompress(bytes(data))
        except Exception as e:
            raise CompressorError(_errno.EIO, "lz4 decompress: %s" % e)
