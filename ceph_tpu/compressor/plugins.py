"""Built-in compression algorithms.

Parity set with the reference's plugin dirs
(/root/reference/src/compressor/{zlib,snappy,zstd,lz4}/). zlib rides the
stdlib; zstd rides the `zstandard` package; snappy and lz4 depend on host
libraries that may be absent — their loaders raise ENOENT then, matching
a missing plugin .so in the reference.
"""

from __future__ import annotations

import errno as _errno
import importlib.util as _importlib_util
import struct as _struct
import zlib as _zlib

from .base import Compressor, CompressorError


def _probe(modname: str) -> bool:
    """Import-time availability probe for a host library. find_spec is
    the dlopen-existence check: it never executes the module, so a
    missing package degrades to `available() == False` instead of an
    ImportError at first use (the tier-1 environment lacks zstandard)."""
    try:
        return _importlib_util.find_spec(modname) is not None
    except (ImportError, ValueError):
        return False


HAVE_ZSTD = _probe("zstandard")
HAVE_SNAPPY = _probe("snappy")
HAVE_LZ4 = _probe("lz4")


class ZlibCompressor(Compressor):
    """Deflate (src/compressor/zlib/ZlibCompressor.cc); level matches the
    reference's compressor_zlib_level default of 5."""

    name = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return _zlib.compress(bytes(data), self.level)

    def decompress(self, data: bytes) -> bytes:
        try:
            return _zlib.decompress(bytes(data))
        except _zlib.error as e:
            raise CompressorError(_errno.EIO, "zlib decompress: %s" % e)


class ZstdCompressor(Compressor):
    """Zstandard (src/compressor/zstd/); level matches the reference's
    compressor_zstd_level default of 1."""

    name = "zstd"

    def __init__(self, level: int = 1):
        if not HAVE_ZSTD:
            raise ImportError("zstandard module not present")
        import zstandard
        self._mod = zstandard
        self.level = level
        # persistent contexts, like the reference plugin's zstd stream state
        self._cctx = zstandard.ZstdCompressor(level=level)
        self._dctx = zstandard.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._cctx.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        try:
            return self._dctx.decompress(bytes(data))
        except self._mod.ZstdError as e:
            raise CompressorError(_errno.EIO, "zstd decompress: %s" % e)


class SnappyCompressor(Compressor):
    name = "snappy"

    def __init__(self):
        if not HAVE_SNAPPY:
            raise ImportError("snappy module not present")
        import snappy
        self._mod = snappy

    def compress(self, data: bytes) -> bytes:
        return self._mod.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        try:
            return self._mod.decompress(bytes(data))
        except Exception as e:
            raise CompressorError(_errno.EIO, "snappy decompress: %s" % e)


class JaxDeviceCompressor(Compressor):
    """Bit-plane compressor from the fused write transform
    (osd/fused_transform.py). The OSD write path runs this stage inside
    the one jitted device program; the plugin exposes the same
    container standalone through the registry (`plugin=jax_device`), so
    pool options and tooling can name the algorithm like any other.

    Self-contained frame: 8-byte header (<II: raw_len, padded_len) +
    the bit-plane container — the fused path instead carries
    raw_len/padded_len in the object's HashInfo comp_info."""

    name = "jax_device"

    def __init__(self):
        from ..osd import fused_transform
        self._ft = fused_transform

    def compress(self, data: bytes) -> bytes:
        data = bytes(data)
        body, padded = self._ft.bitplane_compress_host(data)
        return _struct.pack("<II", len(data), padded) + body

    def decompress(self, data: bytes) -> bytes:
        data = bytes(data)
        try:
            raw_len, padded = _struct.unpack_from("<II", data, 0)
            if padded % 64 or padded < raw_len:
                raise ValueError("bad frame header")
            out = self._ft.bitplane_decompress(data[8:], padded)
            if len(out) < raw_len:
                raise ValueError("short frame")
            return out[:raw_len]
        except (ValueError, _struct.error) as e:
            raise CompressorError(
                _errno.EIO, "jax_device decompress: %s" % e)


class Lz4Compressor(Compressor):
    name = "lz4"

    def __init__(self):
        if not HAVE_LZ4:
            raise ImportError("lz4 module not present")
        import lz4.block
        self._mod = lz4.block

    def compress(self, data: bytes) -> bytes:
        return self._mod.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        try:
            return self._mod.decompress(bytes(data))
        except Exception as e:
            raise CompressorError(_errno.EIO, "lz4 decompress: %s" % e)
