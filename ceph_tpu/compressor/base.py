"""Compressor interface + compression policy.

Rendition of the reference's `Compressor` base
(/root/reference/src/compressor/Compressor.{h,cc}): named algorithms,
whole-buffer compress/decompress, and the BlueStore-facing compression
mode policy (`CompressionMode`: none / passive / aggressive / force) with
the required-ratio admission check
(bluestore_compression_required_ratio semantics).
"""

from __future__ import annotations

import abc
import errno as _errno

from ..errors import ErasureCodeError


class CompressorError(ErasureCodeError):
    """errno-carrying compressor failure (same idiom as the EC side)."""


# Compression modes (Compressor.h COMP_NONE/PASSIVE/AGGRESSIVE/FORCE).
MODE_NONE = "none"
MODE_PASSIVE = "passive"      # compress only if the client hints compressible
MODE_AGGRESSIVE = "aggressive"  # compress unless hinted incompressible
MODE_FORCE = "force"          # always compress

_MODES = (MODE_NONE, MODE_PASSIVE, MODE_AGGRESSIVE, MODE_FORCE)


class Compressor(abc.ABC):
    """A named compression algorithm over byte buffers."""

    name = "generic"

    @abc.abstractmethod
    def compress(self, data: bytes) -> bytes: ...

    @abc.abstractmethod
    def decompress(self, data: bytes) -> bytes: ...

    def get_type_name(self) -> str:
        return self.name


def should_compress(mode: str, hint_compressible: bool = False,
                    hint_incompressible: bool = False) -> bool:
    """BlueStore's admission policy for a write (Compressor.h modes)."""
    if mode not in _MODES:
        raise CompressorError(_errno.EINVAL,
                              "unknown compression mode %r" % mode)
    if mode == MODE_NONE:
        return False
    if mode == MODE_FORCE:
        return True
    if mode == MODE_PASSIVE:
        return hint_compressible
    return not hint_incompressible  # aggressive


def compress_if_worthwhile(compressor: Compressor | None, data: bytes,
                           required_ratio: float = 0.875):
    """Compress and keep the result only if it actually paid off.

    Returns (algorithm_name_or_None, payload). Mirrors BlueStore's
    required-ratio gate: a compressed blob is stored only when
    len(out) <= len(in) * required_ratio
    (bluestore_compression_required_ratio, default 0.875).
    """
    if compressor is None or not data:
        return None, data
    out = compressor.compress(data)
    if len(out) <= len(data) * required_ratio:
        return compressor.get_type_name(), out
    return None, data
