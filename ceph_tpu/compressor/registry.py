"""Compression plugin registry.

Same singleton/load-on-demand/version-gate contract as the erasure-code
registry — the reference deliberately reuses one plugin idiom for both
subsystems (src/compressor/CompressionPlugin.h vs
src/erasure-code/ErasureCodePlugin.h); so do we. `create` adds the
Compressor::create alias behavior ("" / "none" -> no compressor).
"""

from __future__ import annotations

import errno as _errno
import threading

from .base import Compressor, CompressorError

__compression_version__ = "1.0.0"


class CompressionPlugin:
    version = __compression_version__

    def __init__(self, factory_fn):
        self._factory_fn = factory_fn

    def factory(self) -> Compressor:
        return self._factory_fn()


def _builtin_loaders():
    from . import plugins

    def probe(cls):
        # Import errors surface at load() time, like a missing .so.
        def loader():
            try:
                cls()  # probe the host library once
            except ImportError as e:
                raise CompressorError(
                    _errno.ENOENT,
                    "load dlopen(libceph_%s.so): %s" % (cls.name, e))
            return CompressionPlugin(cls)
        return loader

    return {
        "zlib": probe(plugins.ZlibCompressor),
        "zstd": probe(plugins.ZstdCompressor),
        "snappy": probe(plugins.SnappyCompressor),
        "lz4": probe(plugins.Lz4Compressor),
        "jax_device": probe(plugins.JaxDeviceCompressor),
    }


class CompressionPluginRegistry:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self.lock = threading.RLock()
        self.plugins: dict[str, CompressionPlugin] = {}
        self.loaders = _builtin_loaders()

    @classmethod
    def instance(cls) -> "CompressionPluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, plugin: CompressionPlugin) -> None:
        with self.lock:
            if name in self.plugins:
                raise CompressorError(
                    _errno.EEXIST, "plugin %s already registered" % name)
            self.plugins[name] = plugin

    def load(self, name: str) -> CompressionPlugin:
        with self.lock:
            if name in self.plugins:
                return self.plugins[name]
            loader = self.loaders.get(name)
            if loader is None:
                raise CompressorError(
                    _errno.ENOENT,
                    "load dlopen(libceph_%s.so): not found" % name)
            plugin = loader()
            if plugin.version != __compression_version__:
                raise CompressorError(
                    _errno.EXDEV,
                    "plugin %s version %s != expected %s"
                    % (name, plugin.version, __compression_version__))
            self.plugins[name] = plugin
            return plugin

    def preload(self, names) -> None:
        if isinstance(names, str):
            names = [n.strip() for n in names.split(",") if n.strip()]
        for name in names:
            self.load(name)

    def factory(self, name: str) -> Compressor:
        with self.lock:
            plugin = self.load(name)
        return plugin.factory()

    def available(self, name: str) -> bool:
        """Non-raising availability probe: True when the plugin's host
        library is present and the plugin loads. Lets callers (pool
        option validation, tests) degrade instead of erroring when the
        environment lacks a library (e.g. zstandard)."""
        try:
            self.load(name)
            return True
        except CompressorError:
            return False


def available(name: str) -> bool:
    """Module-level availability probe (registry singleton)."""
    if not name or name == "none":
        return True
    return CompressionPluginRegistry.instance().available(name)


def create(name: str) -> Compressor | None:
    """Compressor::create semantics (Compressor.cc): '' and 'none' mean no
    compression; unknown names raise ENOENT."""
    if not name or name == "none":
        return None
    return CompressionPluginRegistry.instance().factory(name)
