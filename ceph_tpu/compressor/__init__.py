"""Compression plugin subsystem.

Second instance of the reference's dlopen-plugin idiom
(/root/reference/src/compressor/: `Compressor` interface in
Compressor.{h,cc}, `CompressionPlugin.h`, per-algorithm plugin dirs
zlib/ snappy/ zstd/ lz4/). Mirrors the same registry contract as the
erasure-code side (load-on-demand under a lock, EEXIST on duplicate
registration, version gating) and the `Compressor::create` alias
resolution + BlueStore compression-mode policy
(none/passive/aggressive/force, Compressor.h `CompressionMode`).

Algorithms: zlib (stdlib) always works; zstd/snappy/lz4 are probed at
import (`plugins.HAVE_*`) and register but fail to load with ENOENT when
their host libraries are absent — the same observable behavior as a
missing libceph_snappy.so in the reference. `available(name)` is the
non-raising probe callers use to degrade cleanly. `jax_device` is the
device-side bit-plane compressor riding the fused write transform
(osd/fused_transform.py).
"""

from .base import Compressor, CompressorError, MODE_AGGRESSIVE  # noqa: F401
from .base import MODE_FORCE, MODE_NONE, MODE_PASSIVE  # noqa: F401
from .registry import CompressionPluginRegistry, create, available  # noqa: F401
from .base import should_compress, compress_if_worthwhile  # noqa: F401
