"""ctypes bindings for the native runtime (native/build/libectpu.so).

The Python<->C++ seam of this framework: the native library carries the
dlopen plugin registry + CPU codecs (reference ABI:
/root/reference/src/erasure-code/ErasureCodePlugin.{h,cc}) and the TPU
batching bridge (native/src/tpu_bridge.cc); this module loads it, drives
codecs through the flat C API (native/include/ectpu/c_api.h), and can
install a JAX-backed dispatcher into the bridge so native threads'
encode calls coalesce into device batches.

No pybind11 in this image — ctypes is the binding layer, mirroring how
the reference binds Python via Cython rather than pybind11
(src/pybind/rados/rados.pyx).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(_REPO, "native")
BUILD_DIR = os.path.join(NATIVE_DIR, "build")
LIB_PATH = os.path.join(BUILD_DIR, "libectpu.so")

_lib = None


class NativeUnavailable(RuntimeError):
    pass


def build(targets=("all",)) -> None:
    """Invoke the native Makefile (idempotent; cheap when up to date)."""
    subprocess.run(["make", "-C", NATIVE_DIR, *targets], check=True,
                   capture_output=True)


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(LIB_PATH):
        try:
            build()
        except (OSError, subprocess.CalledProcessError) as e:
            raise NativeUnavailable("cannot build native runtime: %s" % e)
    L = ctypes.CDLL(LIB_PATH, mode=ctypes.RTLD_GLOBAL)
    L.ec_codec_create.restype = ctypes.c_void_p
    L.ec_codec_create.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_size_t]
    L.ec_codec_destroy.argtypes = [ctypes.c_void_p]
    L.ec_codec_k.argtypes = [ctypes.c_void_p]
    L.ec_codec_m.argtypes = [ctypes.c_void_p]
    L.ec_codec_chunk_size.restype = ctypes.c_uint
    L.ec_codec_chunk_size.argtypes = [ctypes.c_void_p, ctypes.c_uint]
    L.ec_codec_profile.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    L.ec_codec_chunk_mapping.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    L.ec_codec_minimum_to_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    L.ec_codec_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
    L.ec_codec_encode_chunks.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
    L.ec_codec_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_char_p]
    for name in ("ec_tpu_batches_dispatched", "ec_tpu_requests_dispatched"):
        getattr(L, name).restype = ctypes.c_uint64
    _lib = L
    return L


class NativeCodec:
    """A codec instance living in the native runtime."""

    def __init__(self, plugin: str, profile: dict,
                 directory: str = BUILD_DIR):
        L = lib()
        kv = " ".join("%s=%s" % (k, v) for k, v in profile.items())
        err = ctypes.create_string_buffer(512)
        self._h = L.ec_codec_create(plugin.encode(), directory.encode(),
                                    kv.encode(), err, 512)
        if not self._h:
            raise OSError(err.value.decode() or "codec create failed")
        self._L = L
        self.k = L.ec_codec_k(self._h)
        self.m = L.ec_codec_m(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._L.ec_codec_destroy(self._h)
            self._h = None

    def get_profile(self) -> dict:
        buf = ctypes.create_string_buffer(4096)
        self._L.ec_codec_profile(self._h, buf, 4096)
        out = {}
        for line in buf.value.decode().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                out[k] = v
        return out

    def get_chunk_size(self, object_size: int) -> int:
        return self._L.ec_codec_chunk_size(self._h, object_size)

    def chunk_mapping(self) -> list:
        n = self.k + self.m
        arr = (ctypes.c_int * n)()
        self._L.ec_codec_chunk_mapping(self._h, arr)
        return list(arr)

    def minimum_to_decode(self, want, avail) -> list:
        w = (ctypes.c_int * len(want))(*want)
        a = (ctypes.c_int * len(avail))(*avail)
        out = (ctypes.c_int * (self.k + self.m))()
        nmin = ctypes.c_int()
        r = self._L.ec_codec_minimum_to_decode(
            self._h, w, len(want), a, len(avail), out,
            ctypes.byref(nmin))
        if r:
            raise OSError(-r, os.strerror(-r))
        return list(out[: nmin.value])

    def encode(self, data: bytes) -> dict:
        bs = self.get_chunk_size(len(data))
        n = self.k + self.m
        out = ctypes.create_string_buffer(n * bs)
        r = self._L.ec_codec_encode(self._h, data, len(data), out)
        if r:
            raise OSError(-r, os.strerror(-r))
        raw = out.raw
        return {i: raw[i * bs:(i + 1) * bs] for i in range(n)}

    def decode(self, available: dict, want=None) -> dict:
        ids = sorted(available)
        bs = len(available[ids[0]])
        if any(len(available[i]) != bs for i in ids):
            # the C side reads navail*blocksize contiguous bytes; ragged
            # chunks would read past the joined buffer
            raise ValueError("all available chunks must be equal length")
        if want is None:
            want = list(range(self.k + self.m))
        a = (ctypes.c_int * len(ids))(*ids)
        w = (ctypes.c_int * len(want))(*want)
        chunks = b"".join(available[i] for i in ids)
        out = ctypes.create_string_buffer(len(want) * bs)
        r = self._L.ec_codec_decode(self._h, a, len(ids), chunks, bs, w,
                                    len(want), out)
        if r:
            raise OSError(-r, os.strerror(-r))
        raw = out.raw
        return {wid: raw[j * bs:(j + 1) * bs] for j, wid in enumerate(want)}


# -- TPU bridge dispatcher ------------------------------------------------

_DISPATCH_CFUNC = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                   ctypes.c_uint32, ctypes.c_void_p)
# Keepalive for every CFUNCTYPE thunk ever installed: the collector
# thread copies the fn pointer before invoking it unlocked, so a thunk
# being replaced can still be mid-call — freeing it would crash.
_installed_dispatchers: list = []


class _ECRequest(ctypes.Structure):
    _fields_ = [
        ("k", ctypes.c_uint32), ("m", ctypes.c_uint32),
        ("w", ctypes.c_uint32),
        ("technique", ctypes.c_char_p),
        ("blocksize", ctypes.c_uint64),
        ("data", ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))),
        ("parity", ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))),
    ]


def install_jax_dispatcher(max_batch: int = 64,
                           max_delay_us: int = 200) -> None:
    """Register a JAX-backed encode dispatcher into the native bridge.

    Native threads calling ec_tpu_encode() block while the bridge
    coalesces concurrent requests; this callback runs one batched device
    encode per homogeneous batch and scatters parity back through the
    request pointers.
    """
    import numpy as np

    from . import registry

    L = lib()
    codecs = {}

    def dispatch(reqs_ptr, count, _user):
        try:
            reqs = ctypes.cast(
                reqs_ptr, ctypes.POINTER(_ECRequest * count)).contents
            r0 = reqs[0]
            key = (r0.k, r0.m, r0.w, r0.technique)
            codec = codecs.get(key)
            if codec is None:
                codec = codecs[key] = registry.factory("jax_tpu", {
                    "technique": (r0.technique or b"reed_sol_van").decode(),
                    "k": str(r0.k), "m": str(r0.m), "w": str(r0.w)})
            bs = int(r0.blocksize)
            batch = np.empty((count, r0.k, bs), dtype=np.uint8)
            for i in range(count):
                for j in range(r0.k):
                    src = ctypes.cast(
                        reqs[i].data[j],
                        ctypes.POINTER(ctypes.c_uint8 * bs)).contents
                    batch[i, j] = np.frombuffer(src, dtype=np.uint8)
            parity = np.asarray(codec.encode_batch(batch))
            for i in range(count):
                for j in range(r0.m):
                    dst = ctypes.cast(
                        reqs[i].parity[j],
                        ctypes.POINTER(ctypes.c_uint8 * bs)).contents
                    ctypes.memmove(dst, parity[i, j].tobytes(), bs)
            return 0
        except Exception:
            return -5  # EIO: every request falls back to CPU

    thunk = _DISPATCH_CFUNC(dispatch)
    _installed_dispatchers.append(thunk)
    L.ec_tpu_register_dispatcher(thunk, None, max_batch, max_delay_us)


def uninstall_dispatcher() -> None:
    if _lib is not None:
        _lib.ec_tpu_unregister_dispatcher()


def bridge_encode(k: int, m: int, w: int, technique: str,
                  data_chunks: list) -> list:
    """Blocking encode through the native batching bridge (the path a
    native OSD thread takes). Returns m parity chunks; raises if no
    dispatcher is installed (-EAGAIN) or the dispatch failed."""
    L = lib()
    L.ec_tpu_encode.argtypes = [ctypes.POINTER(_ECRequest)]
    bs = len(data_chunks[0])
    dbufs = [ctypes.create_string_buffer(c, bs) for c in data_chunks]
    pbufs = [ctypes.create_string_buffer(bs) for _ in range(m)]
    dptr = (ctypes.POINTER(ctypes.c_uint8) * k)(
        *[ctypes.cast(b, ctypes.POINTER(ctypes.c_uint8)) for b in dbufs])
    pptr = (ctypes.POINTER(ctypes.c_uint8) * m)(
        *[ctypes.cast(b, ctypes.POINTER(ctypes.c_uint8)) for b in pbufs])
    tech = technique.encode()
    req = _ECRequest(k=k, m=m, w=w, technique=tech, blocksize=bs,
                     data=dptr, parity=pptr)
    r = L.ec_tpu_encode(ctypes.byref(req))
    if r:
        raise OSError(-r, os.strerror(-r))
    return [b.raw for b in pbufs]
