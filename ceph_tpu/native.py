"""ctypes bindings for the native runtime (native/build/libectpu.so).

The Python<->C++ seam of this framework: the native library carries the
dlopen plugin registry + CPU codecs (reference ABI:
/root/reference/src/erasure-code/ErasureCodePlugin.{h,cc}) and the TPU
batching bridge (native/src/tpu_bridge.cc); this module loads it, drives
codecs through the flat C API (native/include/ectpu/c_api.h), and can
install a JAX-backed dispatcher into the bridge so native threads'
encode calls coalesce into device batches.

No pybind11 in this image — ctypes is the binding layer, mirroring how
the reference binds Python via Cython rather than pybind11
(src/pybind/rados/rados.pyx).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(_REPO, "native")
BUILD_DIR = os.path.join(NATIVE_DIR, "build")
LIB_PATH = os.path.join(BUILD_DIR, "libectpu.so")

_lib = None


class NativeUnavailable(RuntimeError):
    pass


def build(targets=("all",)) -> None:
    """Invoke the native Makefile (idempotent; cheap when up to date)."""
    subprocess.run(["make", "-C", NATIVE_DIR, *targets], check=True,
                   capture_output=True)


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(LIB_PATH):
        try:
            build()
        except (OSError, subprocess.CalledProcessError) as e:
            raise NativeUnavailable("cannot build native runtime: %s" % e)
    L = _load_and_configure()
    _lib = L
    return L


def _load_and_configure() -> ctypes.CDLL:
    L = ctypes.CDLL(LIB_PATH, mode=ctypes.RTLD_GLOBAL)
    try:
        _configure_symbols(L)
    except AttributeError as e:
        # a stale .so from before a symbol was added: rebuild, then
        # load the fresh library under a UNIQUE path — dlopen of the
        # original path would just hand back the already-mapped stale
        # image, so an in-place reload can never pick up new symbols
        try:
            build()
        except (OSError, subprocess.CalledProcessError) as be:
            raise NativeUnavailable(
                "stale native runtime and rebuild failed: %s" % be)
        import shutil
        import tempfile
        tmp = tempfile.NamedTemporaryFile(
            prefix="libectpu-", suffix=".so", delete=False)
        tmp.close()
        shutil.copy(LIB_PATH, tmp.name)
        # RTLD_LOCAL (the default): the stale image is still globally
        # mapped, and loading the copy globally would let the fresh
        # library's internal cross-TU calls bind to STALE definitions
        L = ctypes.CDLL(tmp.name)
        try:
            _configure_symbols(L)
        except AttributeError as e2:
            raise NativeUnavailable(
                "native runtime lacks symbol after rebuild: %s" % e2)
        finally:
            try:
                os.unlink(tmp.name)  # the mapping survives the unlink
            except OSError:
                pass
    return L


def _configure_symbols(L: ctypes.CDLL) -> None:
    L.ec_codec_create.restype = ctypes.c_void_p
    L.ec_codec_create.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_size_t]
    L.ec_codec_destroy.argtypes = [ctypes.c_void_p]
    L.ec_codec_k.argtypes = [ctypes.c_void_p]
    L.ec_codec_m.argtypes = [ctypes.c_void_p]
    L.ec_codec_chunk_size.restype = ctypes.c_uint
    L.ec_codec_chunk_size.argtypes = [ctypes.c_void_p, ctypes.c_uint]
    L.ec_codec_profile.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    L.ec_codec_chunk_mapping.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    L.ec_codec_minimum_to_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    L.ec_codec_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
    L.ec_codec_encode_chunks.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
    L.ec_codec_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_char_p]
    L.ec_codec_decode_chunks.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p]
    for name in ("ec_tpu_batches_dispatched", "ec_tpu_requests_dispatched"):
        getattr(L, name).restype = ctypes.c_uint64
    L.ec_gf_isa.restype = ctypes.c_char_p
    L.ec_gf_isa.argtypes = []
    L.ec_gf_set_isa.argtypes = [ctypes.c_char_p]
    L.ec_gf_region_madd.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
        ctypes.c_size_t, ctypes.c_int]
    LL = ctypes.POINTER(ctypes.c_longlong)
    L.ec_crush_do_rule.restype = ctypes.c_int
    L.ec_crush_do_rule.argtypes = [
        LL, LL, LL, LL, ctypes.c_int,             # bucket arrays
        LL, LL,                                   # items, weights
        LL, ctypes.c_int,                         # steps
        ctypes.c_longlong, ctypes.c_int,          # x, result_max
        ctypes.POINTER(ctypes.c_uint), ctypes.c_int,   # weight
        ctypes.POINTER(ctypes.c_int),             # tunables[6]
        ctypes.POINTER(ctypes.c_int)]             # result
    L.ec_crush_ln.restype = ctypes.c_longlong
    L.ec_crush_ln.argtypes = [ctypes.c_uint]
    L.ec_crush_hash32_2.restype = ctypes.c_uint
    L.ec_crush_hash32_2.argtypes = [ctypes.c_uint] * 2
    L.ec_crush_hash32_3.restype = ctypes.c_uint
    L.ec_crush_hash32_3.argtypes = [ctypes.c_uint] * 3
    LL2 = ctypes.POINTER(ctypes.c_longlong)
    L.ec_crush_map_create.restype = ctypes.c_void_p
    L.ec_crush_map_create.argtypes = [LL2, LL2, LL2, LL2, ctypes.c_int,
                                      LL2, LL2]
    L.ec_crush_map_destroy.argtypes = [ctypes.c_void_p]
    L.ec_crush_do_rule_map.restype = ctypes.c_int
    L.ec_crush_do_rule_map.argtypes = [
        ctypes.c_void_p, LL2, ctypes.c_int,
        ctypes.c_longlong, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    L.ec_crush_do_rule_batch.restype = ctypes.c_int
    L.ec_crush_do_rule_batch.argtypes = [
        ctypes.c_void_p, LL2, ctypes.c_int,
        LL2, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    L.ec_crush_map_set_choose_args.restype = ctypes.c_int
    L.ec_crush_map_set_choose_args.argtypes = [
        ctypes.c_void_p, LL2, ctypes.c_int, LL2, LL2, LL2, LL2, LL2]
    L.ec_crush_map_clear_choose_args.argtypes = [ctypes.c_void_p]


# ---------------------------------------------------------------------------
# GF kernel SIMD dispatch (runtime cpuid selection in native/src/gf.cc)


def gf_isa() -> str:
    """The ISA the GF region kernels are currently dispatched to:
    'avx2' | 'ssse3' | 'scalar'."""
    return lib().ec_gf_isa().decode()


def gf_set_isa(name: str) -> bool:
    """Force a lower-or-equal kernel ISA (parity tests / triage);
    False if unknown or unsupported on this host. Process-global."""
    return lib().ec_gf_set_isa(name.encode()) == 0


def gf_region_madd(dst, src, g: int, w: int = 8) -> None:
    """dst[i] ^= g * src[i] through the dispatched native kernel.
    dst/src are equal-length contiguous uint8 numpy arrays."""
    import numpy as np
    if not (isinstance(dst, np.ndarray) and dst.flags["C_CONTIGUOUS"]):
        raise ValueError("dst must be a contiguous ndarray (mutated "
                         "in place)")
    src = np.ascontiguousarray(src)
    if dst.nbytes != src.nbytes:
        raise ValueError("dst/src length mismatch")
    r = lib().ec_gf_region_madd(
        dst.ctypes.data, src.ctypes.data, g, dst.nbytes, w)
    if r != 0:
        raise ValueError("gf_region_madd failed: %d" % r)


# ---------------------------------------------------------------------------
# native CRUSH (ectpu::crush_do_rule_flat over a serialized CrushMap)

_STEP_OPS = {
    "take": 1, "choose_firstn": 2, "choose_indep": 3, "emit": 4,
    "chooseleaf_firstn": 6, "chooseleaf_indep": 7,
    "set_choose_tries": 8, "set_chooseleaf_tries": 9,
    "set_choose_local_tries": 10, "set_choose_local_fallback_tries": 11,
    "set_chooseleaf_vary_r": 12, "set_chooseleaf_stable": 13,
}
_ALGS = {"uniform": 1, "list": 2, "straw2": 5}


def _map_fingerprint(cmap) -> int:
    """Content crc over everything placement-visible: bucket ids, algs,
    types, item and weight VECTORS (order-sensitive: swaps, moves and
    alg changes all alter it), rule steps, tunables excluded (they ride
    per call). Cheap: crc32 over the numpy buffers."""
    import zlib
    crc = 0
    for bid in sorted(cmap.buckets):
        b = cmap.buckets[bid]
        hdr = ("%d|%s|%d" % (b.id, b.alg, b.type)).encode()
        crc = zlib.crc32(hdr, crc)
        crc = zlib.crc32(b.items.tobytes(), crc)
        crc = zlib.crc32(b.weights.tobytes(), crc)
    for rule in cmap.rules:
        crc = zlib.crc32(repr(rule.steps).encode(), crc)
    return crc


class _NativeMapHandle:
    """Owns one C-side map (ec_crush_map_create/destroy)."""

    def __init__(self, L, flat):
        self._L = L
        LLp = ctypes.POINTER(ctypes.c_longlong)
        self.ptr = L.ec_crush_map_create(
            flat["bids"].ctypes.data_as(LLp),
            flat["algs"].ctypes.data_as(LLp),
            flat["types"].ctypes.data_as(LLp),
            flat["offs"].ctypes.data_as(LLp),
            len(flat["bids"]),
            flat["items"].ctypes.data_as(LLp),
            flat["weights"].ctypes.data_as(LLp))
        if not self.ptr:
            raise NativeUnavailable("native crush rejected the map")

    def __del__(self):
        ptr, self.ptr = getattr(self, "ptr", None), None
        if ptr:
            try:
                self._L.ec_crush_map_destroy(ptr)
            except Exception:
                pass


# Cache OFF the map object: a CDLL-holding handle stored as a CrushMap
# attribute would make the map un-deepcopyable/un-picklable, and maps
# are cloned and pickled on the daemon paths (OSDMap clone, MOSDMap
# distribution). Keyed by id() (CrushMap is an unhashable dataclass)
# with a weakref finalizer evicting the entry when the map dies, so a
# recycled id can never observe a stale entry.
import weakref  # noqa: E402

_flat_cache: dict = {}


def _flatten_map(cmap, L):
    """Serialize a CrushMap once: flat arrays + a persistent C-side map
    handle, cached in a weak side table and invalidated by a content
    crc over buckets/items/weights/rules."""
    import numpy as np
    key = id(cmap)
    fingerprint = _map_fingerprint(cmap)
    cached = _flat_cache.get(key)
    if cached is not None and cached[0] == fingerprint:
        return cached[1]
    bids, algs, types, offs = [], [], [], [0]
    items, weights = [], []
    for bid in sorted(cmap.buckets):
        b = cmap.buckets[bid]
        if b.alg not in _ALGS:
            raise NativeUnavailable(
                "native crush does not support bucket alg %r" % b.alg)
        bids.append(b.id)
        algs.append(_ALGS[b.alg])
        types.append(b.type)
        items.extend(int(i) for i in b.items)
        weights.extend(int(w) for w in b.weights)
        offs.append(len(items))
    rule_steps = []
    for rule in cmap.rules:
        steps = []
        for step in rule.steps:
            op = _STEP_OPS.get(step[0])
            if op is None:
                raise NativeUnavailable(
                    "native crush does not support step %r" % (step[0],))
            a1 = int(step[1]) if len(step) > 1 else 0
            a2 = int(step[2]) if len(step) > 2 else 0
            steps.extend([op, a1, a2])
        rule_steps.append(np.asarray(steps, dtype=np.int64))

    def arr(vals):
        return np.asarray(vals, dtype=np.int64)

    flat = {"bids": arr(bids), "algs": arr(algs), "types": arr(types),
            "offs": arr(offs), "items": arr(items),
            "weights": arr(weights), "rule_steps": rule_steps}
    flat["handle"] = _NativeMapHandle(L, flat)
    if key not in _flat_cache:
        weakref.finalize(cmap, _flat_cache.pop, key, None)
    _flat_cache[key] = (fingerprint, flat)
    return flat


def _apply_choose_args(L, handle, cmap, choose_args) -> None:
    """Install (or clear) a choose_args set on the C-side map handle.
    Skipped when the handle already carries the same set (content crc),
    so repeated bulk calls don't re-upload."""
    import zlib

    import numpy as np
    if isinstance(choose_args, int):
        choose_args = cmap.choose_args_get_with_fallback(choose_args)
    if not choose_args:
        if getattr(handle, "_cargs_crc", None) is not None:
            L.ec_crush_map_clear_choose_args(handle.ptr)
            handle._cargs_crc = None
        return
    crc = zlib.crc32(repr(sorted(
        (bid, (arg or {}).get("ids"), (arg or {}).get("weight_set"))
        for bid, arg in choose_args.items())).encode())
    if getattr(handle, "_cargs_crc", None) == crc:
        return
    bids, ids_flat, ids_offs = [], [], [0]
    ws_flat, ws_offs, ws_pos = [], [0], []
    for bid in sorted(choose_args):
        arg = choose_args[bid] or {}
        if bid not in cmap.buckets:
            continue
        bids.append(bid)
        ids = arg.get("ids")
        if ids:
            ids_flat.extend(int(i) for i in ids)
        ids_offs.append(len(ids_flat))
        ws = arg.get("weight_set")
        if ws:
            for row in ws:
                ws_flat.extend(int(w) for w in row)
            ws_pos.append(len(ws))
        else:
            ws_pos.append(0)
        ws_offs.append(len(ws_flat))
    LLp = ctypes.POINTER(ctypes.c_longlong)

    def arr(v):
        return np.asarray(v if v else [0], dtype=np.int64)

    rc = L.ec_crush_map_set_choose_args(
        handle.ptr,
        arr(bids).ctypes.data_as(LLp), len(bids),
        arr(ids_flat).ctypes.data_as(LLp),
        arr(ids_offs).ctypes.data_as(LLp),
        arr(ws_flat).ctypes.data_as(LLp),
        arr(ws_offs).ctypes.data_as(LLp),
        arr(ws_pos).ctypes.data_as(LLp))
    if rc != 0:
        raise NativeUnavailable("native crush rejected choose_args")
    handle._cargs_crc = crc


def crush_do_rule_native(cmap, ruleno: int, x: int, result_max: int,
                         weight=None, choose_args=None) -> list[int]:
    """Run a CrushMap rule through the native mapper; same contract as
    ceph_tpu.crush.mapper_ref.crush_do_rule (bit-identical results).
    Raises NativeUnavailable for bucket algs/steps the native side
    doesn't implement."""
    import numpy as np
    L = lib()
    if ruleno < 0 or ruleno >= len(cmap.rules):
        return []
    flat = _flatten_map(cmap, L)
    _apply_choose_args(L, flat["handle"], cmap, choose_args)
    a_steps = flat["rule_steps"][ruleno]
    if weight is None:
        weight = [0x10000] * cmap.max_devices
    t = cmap.tunables
    tun = np.asarray([t.choose_total_tries, t.choose_local_tries,
                      t.choose_local_fallback_tries,
                      t.chooseleaf_descend_once, t.chooseleaf_vary_r,
                      t.chooseleaf_stable], dtype=np.int32)
    LLp = ctypes.POINTER(ctypes.c_longlong)
    a_rw = np.asarray(weight, dtype=np.uint32)
    res = np.zeros(max(result_max, 1), dtype=np.int32)
    n = L.ec_crush_do_rule_map(
        flat["handle"].ptr,
        a_steps.ctypes.data_as(LLp), len(a_steps) // 3,
        x, result_max,
        a_rw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint)), len(a_rw),
        tun.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        res.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    if n < 0:
        raise NativeUnavailable("native crush rejected the map (%d)" % n)
    return [int(v) for v in res[:n]]


def crush_do_rule_batch_native(cmap, ruleno: int, xs, result_max: int,
                               weight=None, choose_args=None):
    """Bulk native mapping: all of `xs` in ONE C call (the
    ParallelPGMapper use case on the host side). Returns a list of
    per-x result lists, each bit-identical to crush_do_rule."""
    import numpy as np
    L = lib()
    if ruleno < 0 or ruleno >= len(cmap.rules):
        return [[] for _ in xs]
    flat = _flatten_map(cmap, L)
    _apply_choose_args(L, flat["handle"], cmap, choose_args)
    a_steps = flat["rule_steps"][ruleno]
    if weight is None:
        weight = [0x10000] * cmap.max_devices
    t = cmap.tunables
    tun = np.asarray([t.choose_total_tries, t.choose_local_tries,
                      t.choose_local_fallback_tries,
                      t.chooseleaf_descend_once, t.chooseleaf_vary_r,
                      t.chooseleaf_stable], dtype=np.int32)
    LLp = ctypes.POINTER(ctypes.c_longlong)
    a_xs = np.asarray(list(xs), dtype=np.int64)
    a_rw = np.asarray(weight, dtype=np.uint32)
    results = np.zeros((len(a_xs), max(result_max, 1)), dtype=np.int32)
    lengths = np.zeros(len(a_xs), dtype=np.int32)
    rc = L.ec_crush_do_rule_batch(
        flat["handle"].ptr,
        a_steps.ctypes.data_as(LLp), len(a_steps) // 3,
        a_xs.ctypes.data_as(LLp), len(a_xs), result_max,
        a_rw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint)), len(a_rw),
        tun.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        results.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    if rc < 0:
        raise NativeUnavailable("native crush batch failed (%d)" % rc)
    return [[int(v) for v in results[i][:lengths[i]]]
            for i in range(len(a_xs))]


class NativeCodec:
    """A codec instance living in the native runtime."""

    def __init__(self, plugin: str, profile: dict,
                 directory: str = BUILD_DIR):
        L = lib()
        kv = " ".join("%s=%s" % (k, v) for k, v in profile.items())
        err = ctypes.create_string_buffer(512)
        self._h = L.ec_codec_create(plugin.encode(), directory.encode(),
                                    kv.encode(), err, 512)
        if not self._h:
            raise OSError(err.value.decode() or "codec create failed")
        self._L = L
        self.k = L.ec_codec_k(self._h)
        self.m = L.ec_codec_m(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._L.ec_codec_destroy(self._h)
            self._h = None

    def get_profile(self) -> dict:
        buf = ctypes.create_string_buffer(4096)
        self._L.ec_codec_profile(self._h, buf, 4096)
        out = {}
        for line in buf.value.decode().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                out[k] = v
        return out

    def get_chunk_size(self, object_size: int) -> int:
        return self._L.ec_codec_chunk_size(self._h, object_size)

    def chunk_mapping(self) -> list:
        n = self.k + self.m
        arr = (ctypes.c_int * n)()
        self._L.ec_codec_chunk_mapping(self._h, arr)
        return list(arr)

    def minimum_to_decode(self, want, avail) -> list:
        w = (ctypes.c_int * len(want))(*want)
        a = (ctypes.c_int * len(avail))(*avail)
        out = (ctypes.c_int * (self.k + self.m))()
        nmin = ctypes.c_int()
        r = self._L.ec_codec_minimum_to_decode(
            self._h, w, len(want), a, len(avail), out,
            ctypes.byref(nmin))
        if r:
            raise OSError(-r, os.strerror(-r))
        return list(out[: nmin.value])

    def encode(self, data: bytes) -> dict:
        bs = self.get_chunk_size(len(data))
        n = self.k + self.m
        out = ctypes.create_string_buffer(n * bs)
        r = self._L.ec_codec_encode(self._h, data, len(data), out)
        if r:
            raise OSError(-r, os.strerror(-r))
        raw = out.raw
        return {i: raw[i * bs:(i + 1) * bs] for i in range(n)}

    def encode_chunks(self, data, parity) -> None:
        """Zero-copy chunk-level encode: `data` is a C-contiguous
        uint8 array of shape [k, blocksize] (numpy), `parity` a
        writable [m, blocksize]. The benchmark-honest path — no
        split/pad copies, matching the reference's aligned-bufferlist
        plugin loop."""
        import numpy as np
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert parity.flags["C_CONTIGUOUS"]
        r = self._L.ec_codec_encode_chunks(
            self._h, data.ctypes.data_as(ctypes.c_char_p),
            parity.ctypes.data_as(ctypes.c_char_p), data.shape[1])
        if r:
            raise OSError(-r, os.strerror(-r))

    def decode_chunks(self, avail_rows, chunks, out) -> None:
        """Zero-copy reconstruction of all k+m rows: `chunks` is
        [len(avail_rows), blocksize] (ascending logical rows), `out` a
        writable [k+m, blocksize]."""
        import numpy as np
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
        assert out.flags["C_CONTIGUOUS"]
        rows = (ctypes.c_int * len(avail_rows))(*avail_rows)
        r = self._L.ec_codec_decode_chunks(
            self._h, rows, len(avail_rows),
            chunks.ctypes.data_as(ctypes.c_void_p), chunks.shape[1],
            out.ctypes.data_as(ctypes.c_void_p))
        if r:
            raise OSError(-r, os.strerror(-r))

    def decode(self, available: dict, want=None) -> dict:
        ids = sorted(available)
        bs = len(available[ids[0]])
        if any(len(available[i]) != bs for i in ids):
            # the C side reads navail*blocksize contiguous bytes; ragged
            # chunks would read past the joined buffer
            raise ValueError("all available chunks must be equal length")
        if want is None:
            want = list(range(self.k + self.m))
        a = (ctypes.c_int * len(ids))(*ids)
        w = (ctypes.c_int * len(want))(*want)
        chunks = b"".join(available[i] for i in ids)
        out = ctypes.create_string_buffer(len(want) * bs)
        r = self._L.ec_codec_decode(self._h, a, len(ids), chunks, bs, w,
                                    len(want), out)
        if r:
            raise OSError(-r, os.strerror(-r))
        raw = out.raw
        return {wid: raw[j * bs:(j + 1) * bs] for j, wid in enumerate(want)}


# -- TPU bridge dispatcher ------------------------------------------------

_DISPATCH_CFUNC = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                   ctypes.c_uint32, ctypes.c_void_p)
# Keepalive for every CFUNCTYPE thunk ever installed: the collector
# thread copies the fn pointer before invoking it unlocked, so a thunk
# being replaced can still be mid-call — freeing it would crash.
_installed_dispatchers: list = []


class _ECRequest(ctypes.Structure):
    _fields_ = [
        ("k", ctypes.c_uint32), ("m", ctypes.c_uint32),
        ("w", ctypes.c_uint32),
        ("technique", ctypes.c_char_p),
        ("blocksize", ctypes.c_uint64),
        ("data", ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))),
        ("parity", ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))),
    ]


def install_jax_dispatcher(max_batch: int = 64,
                           max_delay_us: int = 200) -> None:
    """Register a JAX-backed encode dispatcher into the native bridge.

    Native threads calling ec_tpu_encode() block while the bridge
    coalesces concurrent requests; this callback runs one batched device
    encode per homogeneous batch and scatters parity back through the
    request pointers.
    """
    import numpy as np

    from . import registry

    L = lib()
    codecs = {}

    def dispatch(reqs_ptr, count, _user):
        try:
            reqs = ctypes.cast(
                reqs_ptr, ctypes.POINTER(_ECRequest * count)).contents
            r0 = reqs[0]
            key = (r0.k, r0.m, r0.w, r0.technique)
            codec = codecs.get(key)
            if codec is None:
                codec = codecs[key] = registry.factory("jax_tpu", {
                    "technique": (r0.technique or b"reed_sol_van").decode(),
                    "k": str(r0.k), "m": str(r0.m), "w": str(r0.w)})
            bs = int(r0.blocksize)
            batch = np.empty((count, r0.k, bs), dtype=np.uint8)
            for i in range(count):
                for j in range(r0.k):
                    src = ctypes.cast(
                        reqs[i].data[j],
                        ctypes.POINTER(ctypes.c_uint8 * bs)).contents
                    batch[i, j] = np.frombuffer(src, dtype=np.uint8)
            parity = np.asarray(codec.encode_batch(batch))
            for i in range(count):
                for j in range(r0.m):
                    dst = ctypes.cast(
                        reqs[i].parity[j],
                        ctypes.POINTER(ctypes.c_uint8 * bs)).contents
                    ctypes.memmove(dst, parity[i, j].tobytes(), bs)
            return 0
        except Exception:
            return -5  # EIO: every request falls back to CPU

    thunk = _DISPATCH_CFUNC(dispatch)
    _installed_dispatchers.append(thunk)
    L.ec_tpu_register_dispatcher(thunk, None, max_batch, max_delay_us)


def uninstall_dispatcher() -> None:
    if _lib is not None:
        _lib.ec_tpu_unregister_dispatcher()


def bridge_encode(k: int, m: int, w: int, technique: str,
                  data_chunks: list) -> list:
    """Blocking encode through the native batching bridge (the path a
    native OSD thread takes). Returns m parity chunks; raises if no
    dispatcher is installed (-EAGAIN) or the dispatch failed."""
    L = lib()
    L.ec_tpu_encode.argtypes = [ctypes.POINTER(_ECRequest)]
    bs = len(data_chunks[0])
    dbufs = [ctypes.create_string_buffer(c, bs) for c in data_chunks]
    pbufs = [ctypes.create_string_buffer(bs) for _ in range(m)]
    dptr = (ctypes.POINTER(ctypes.c_uint8) * k)(
        *[ctypes.cast(b, ctypes.POINTER(ctypes.c_uint8)) for b in dbufs])
    pptr = (ctypes.POINTER(ctypes.c_uint8) * m)(
        *[ctypes.cast(b, ctypes.POINTER(ctypes.c_uint8)) for b in pbufs])
    tech = technique.encode()
    req = _ECRequest(k=k, m=m, w=w, technique=tech, blocksize=bs,
                     data=dptr, parity=pptr)
    r = L.ec_tpu_encode(ctypes.byref(req))
    if r:
        raise OSError(-r, os.strerror(-r))
    return [b.raw for b in pbufs]
