"""Erasure-code plugin registry.

Python rendition of ErasureCodePluginRegistry
(/root/reference/src/erasure-code/ErasureCodePlugin.{h,cc}): a process
singleton that loads plugins on demand under a lock, rejects duplicate
registration (EEXIST), version-gates loaded plugins, and constructs codec
instances from profiles (factory, ErasureCodePlugin.cc:92-120) with a
profile echo check (:114-118).

Built-in plugins:
  jerasure   CPU (numpy) implementations of the 7 jerasure techniques
  isa        CPU implementations of reed_sol_van / cauchy (ISA-L parity)
  jax_tpu    the TPU-batched backend (the north-star plugin)
  example    XOR k=2,m=1 interface fixture

The native dlopen ABI (libec_*.so with __erasure_code_init /
__erasure_code_version) lives in native/; this registry is the Python
process's equivalent seam, and also powers the registry failure-mode tests
(fixtures modeled on src/test/erasure-code/ErasureCodePlugin*.cc).
"""

from __future__ import annotations

import errno
import threading

from .models.base import ErasureCode, ErasureCodeError

__erasure_code_version__ = "1.0.0"


class ErasureCodePlugin:
    """A named factory for codec instances."""

    version = __erasure_code_version__

    def factory(self, profile: dict, errors: list | None = None) -> ErasureCode:
        raise NotImplementedError


class _TechniquePlugin(ErasureCodePlugin):
    """Dispatches on profile["technique"] like the jerasure plugin factory
    (ErasureCodePluginJerasure.cc:34-73)."""

    def __init__(self, techniques: dict, backend: str,
                 default_technique: str | None = None):
        self.techniques = techniques
        self.backend = backend
        self.default_technique = default_technique

    def factory(self, profile, errors=None):
        t = profile.get("technique") or self.default_technique
        cls = self.techniques.get(t)
        if cls is None:
            raise ErasureCodeError(
                errno.ENOENT,
                "technique=%s is not a valid coding technique. Choose one "
                "of the following: %s" % (t, ", ".join(self.techniques)))
        profile.setdefault("technique", t)
        codec = cls(backend=self.backend)
        codec.init(profile, errors)
        return codec


class _ExamplePlugin(ErasureCodePlugin):
    def factory(self, profile, errors=None):
        from .models.xor_example import XorExample
        codec = XorExample()
        codec.init(profile, errors)
        return codec


class _LrcPlugin(ErasureCodePlugin):
    def __init__(self, backend: str):
        self.backend = backend

    def factory(self, profile, errors=None):
        from .models.lrc import Lrc
        codec = Lrc(backend=self.backend)
        codec.init(profile, errors)
        return codec


def _jerasure_techniques():
    from .models import cauchy, liberation, rs
    return {
        "reed_sol_van": rs.ReedSolomonVandermonde,
        "reed_sol_r6_op": rs.ReedSolomonRAID6,
        "cauchy_orig": cauchy.CauchyOrig,
        "cauchy_good": cauchy.CauchyGood,
        "liberation": liberation.Liberation,
        "blaum_roth": liberation.BlaumRoth,
        "liber8tion": liberation.Liber8tion,
    }


def _isa_techniques():
    from .models import cauchy, rs
    return {
        "reed_sol_van": rs.ReedSolomonVandermonde,
        "cauchy": cauchy.CauchyGood,
    }


def _msr_techniques():
    from .models import msr
    return {
        "msr": msr.MsrProductMatrix,
    }


def _shec_techniques():
    from .models import shec
    return {
        "multiple": shec.ShecMultiple,
        "single": shec.ShecSingle,
    }


_BUILTIN_LOADERS = {
    "jerasure": lambda: _TechniquePlugin(_jerasure_techniques(), "numpy"),
    "isa": lambda: _TechniquePlugin(_isa_techniques(), "numpy",
                                    default_technique="reed_sol_van"),
    "jax_tpu": lambda: _TechniquePlugin(_jerasure_techniques(), "jax",
                                        default_technique="reed_sol_van"),
    "shec": lambda: _TechniquePlugin(_shec_techniques(), "numpy",
                                     default_technique="multiple"),
    "shec_tpu": lambda: _TechniquePlugin(_shec_techniques(), "jax",
                                         default_technique="multiple"),
    "msr": lambda: _TechniquePlugin(_msr_techniques(), "numpy",
                                    default_technique="msr"),
    "msr_tpu": lambda: _TechniquePlugin(_msr_techniques(), "jax",
                                        default_technique="msr"),
    "lrc": lambda: _LrcPlugin("numpy"),
    "lrc_tpu": lambda: _LrcPlugin("jax"),
    "example": lambda: _ExamplePlugin(),
}


class ErasureCodePluginRegistry:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self.lock = threading.RLock()
        self.plugins: dict[str, ErasureCodePlugin] = {}
        self.loaders = dict(_BUILTIN_LOADERS)
        self.disable_dlclose = False

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        """Register a plugin; EEXIST on duplicates (ErasureCodePlugin.cc)."""
        with self.lock:
            if name in self.plugins:
                raise ErasureCodeError(
                    errno.EEXIST, "plugin %s already registered" % name)
            self.plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self.lock:
            return self.plugins.get(name)

    def load(self, name: str) -> ErasureCodePlugin:
        """Load a plugin on demand; version-gate it like the dlopen path
        (__erasure_code_version check, ErasureCodePlugin.cc:144-149)."""
        with self.lock:
            if name in self.plugins:
                return self.plugins[name]
            loader = self.loaders.get(name)
            if loader is None:
                raise ErasureCodeError(
                    errno.ENOENT, "load dlopen(libec_%s.so): not found" % name)
            plugin = loader()
            if not isinstance(plugin, ErasureCodePlugin):
                raise ErasureCodeError(
                    errno.ENOENT, "plugin %s did not register itself" % name)
            if plugin.version != __erasure_code_version__:
                raise ErasureCodeError(
                    errno.EXDEV,
                    "plugin %s version %s != expected %s"
                    % (name, plugin.version, __erasure_code_version__))
            self.plugins[name] = plugin
            return plugin

    def preload(self, names) -> None:
        """Preload a comma list or iterable of plugins
        (ErasureCodePlugin.cc:186-202; called from daemon start, the analog
        of global_init_preload_erasure_code)."""
        if isinstance(names, str):
            names = [n.strip() for n in names.split(",") if n.strip()]
        for name in names:
            self.load(name)

    def factory(self, name: str, profile: dict,
                errors: list | None = None) -> ErasureCode:
        """Instantiate a codec (ErasureCodePlugin.cc:92-120)."""
        with self.lock:
            plugin = self.load(name)
        codec = plugin.factory(profile, errors)
        echo = codec.get_profile()
        if echo is not profile and echo != profile:
            raise ErasureCodeError(
                errno.EINVAL,
                "profile %r was not echoed back by plugin %s: %r"
                % (profile, name, echo))
        return codec


def factory(name: str, profile: dict, errors: list | None = None) -> ErasureCode:
    """Module-level convenience: build a codec from a plugin name + profile."""
    return ErasureCodePluginRegistry.instance().factory(name, profile, errors)
