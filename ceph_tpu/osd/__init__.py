"""OSD data-plane: stripe math, write planning, caching, backends.

The framework's rendition of src/osd/'s erasure-coded IO path
(SURVEY.md §2.2), re-shaped TPU-first: where the reference encodes one
stripe per call inside ECUtil::encode's loop (src/osd/ECUtil.cc:116),
this layer reshapes whole objects (and, in the batching queue, many
objects) into one device call.

  ec_util         stripe_info_t arithmetic, batched encode/decode seam,
                  HashInfo integrity hashes
  ec_transaction  WritePlan: logical writes -> stripe-aligned read/write
                  sets (RMW planning)
  extent_cache    pinned extents for pipelined RMW overwrites
  pg_transaction  logical object operations (PGTransaction)
  ec_backend      the two-phase write/read/recovery pipeline
  replicated_backend  the replication strategy peer
"""
