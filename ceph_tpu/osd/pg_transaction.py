"""Logical per-object operations.

Role of the reference's PGTransaction (src/osd/PGTransaction.h): the
PG-level description of what a client op does to objects — creates,
deletes, buffer writes/zeros, truncates, clones/renames, attr and omap
updates — consumed by a backend's planner which turns it into physical
per-shard store transactions. safe_create_traverse orders entries so
clone/rename sources are processed safely.
"""

from __future__ import annotations

__all__ = ["PGTransaction", "ObjectOperation"]


class ObjectOperation:
    def __init__(self):
        self.init_type = "none"        # none | create | clone | rename
        self.source = None             # clone/rename source oid
        self.delete_first = False
        self.truncate = None           # (first, final) like the reference
        self.buffer_updates: list[tuple] = []  # ("write",off,bytes)|("zero",off,len)
        self.attr_updates: dict = {}   # name -> bytes | None (= remove)
        self.omap_updates: dict = {}
        self.omap_rmkeys: list = []

    # -- queries (WritePlan template contract) -------------------------

    def deletes_first(self) -> bool:
        return self.delete_first

    def has_source(self) -> bool:
        return self.source is not None

    def is_fresh_object(self) -> bool:
        return self.init_type == "create" and not self.buffer_updates \
            and self.truncate is None

    def is_none(self) -> bool:
        return self.init_type == "none" and not self.delete_first \
            and not self.buffer_updates and self.truncate is None \
            and not self.attr_updates and not self.omap_updates \
            and not self.omap_rmkeys

    def is_delete(self) -> bool:
        """A pure removal: the object ends the transaction gone."""
        return self.delete_first and self.init_type == "none" \
            and not self.buffer_updates and self.truncate is None \
            and not self.attr_updates and not self.omap_updates


class PGTransaction:
    def __init__(self):
        self.op_map: dict = {}         # oid -> ObjectOperation

    def _get(self, oid) -> ObjectOperation:
        op = self.op_map.get(oid)
        if op is None:
            op = self.op_map[oid] = ObjectOperation()
        return op

    # -- builders (the PrimaryLogPG-facing API) ------------------------

    def create(self, oid) -> None:
        self._get(oid).init_type = "create"

    def remove(self, oid) -> None:
        self.reset_data(oid)
        op = self._get(oid)
        op.delete_first = True
        op.init_type = "none"

    def reset_data(self, oid) -> None:
        """Drop queued data mutations (buffer updates + truncate) while
        keeping attr/omap updates — the data half of what remove() does.
        Used by WRITEFULL, which replaces the object's entire data
        stream but must preserve xattrs (snapset) and omap."""
        op = self._get(oid)
        op.buffer_updates = []
        op.truncate = None

    def drop_attr_update(self, oid, name: str) -> None:
        """Discard a QUEUED setattr — for ops that supersede a marker
        an earlier op in the same compound queued (e.g. WRITEFULL after
        a whiteout-remove). A queued rmattr (value None) is kept: it
        clears persisted state, which still must happen."""
        op = self.op_map.get(oid)
        if op is not None and op.attr_updates.get(name) is not None:
            op.attr_updates.pop(name)

    def write(self, oid, offset: int, data: bytes) -> None:
        self._get(oid).buffer_updates.append(("write", offset, bytes(data)))

    def zero(self, oid, offset: int, length: int) -> None:
        self._get(oid).buffer_updates.append(("zero", offset, length))

    def truncate(self, oid, size: int) -> None:
        op = self._get(oid)
        if op.truncate is None:
            op.truncate = (size, size)
        else:
            op.truncate = (op.truncate[0], size)

    def clone(self, src, dst) -> None:
        op = self._get(dst)
        op.init_type = "clone"
        op.source = src

    def rename(self, src, dst) -> None:
        op = self._get(dst)
        op.init_type = "rename"
        op.source = src
        # the source ceases to exist
        self._get(src).delete_first = True

    def setattr(self, oid, name: str, value) -> None:
        self._get(oid).attr_updates[name] = value

    def rmattr(self, oid, name: str) -> None:
        self._get(oid).attr_updates[name] = None

    def omap_setkeys(self, oid, kv: dict) -> None:
        # the builders are called in op-vector order; make the merged
        # record order-independent by letting the LAST logical op per
        # key win (a set cancels a queued rm of the same key — e.g.
        # OMAPCLEAR followed by OMAPSETKEYS in one compound op)
        op = self._get(oid)
        op.omap_updates.update(kv)
        if op.omap_rmkeys:
            op.omap_rmkeys = [k for k in op.omap_rmkeys if k not in kv]

    def omap_rmkeys_op(self, oid, keys) -> None:
        op = self._get(oid)
        op.omap_rmkeys.extend(keys)
        for k in keys:
            op.omap_updates.pop(k, None)

    # -- traversal -----------------------------------------------------

    def safe_create_traverse(self):
        """Yield (oid, op) with rename/clone sources before their
        destinations (PGTransaction::safe_create_traverse)."""
        emitted = set()
        order = []

        def emit(oid):
            if oid in emitted or oid not in self.op_map:
                return
            op = self.op_map[oid]
            if op.source is not None:
                emit(op.source)
            emitted.add(oid)
            order.append(oid)

        for oid in self.op_map:
            emit(oid)
        return [(oid, self.op_map[oid]) for oid in order]

    def empty(self) -> bool:
        return not self.op_map
