"""Dynamic per-principal perf queries (OSD side).

Role of the reference's OSD perf-query machinery
(src/osd/osd_perf_counters.{h,cc} + the mgr's OSDPerfMetricQuery
flow behind `rbd perf image iotop`): the mgr subscribes dynamic
queries on every OSD; each query names the columns ops are keyed by
(client session/id, pool, pg, object prefix) and the OSD accumulates
ops / bytes / read-write split / latency sum+count+histogram per key
on the op completion path.  Results ride the existing MMgrReport
cadence; the mgr merges them cluster-wide (mgr/perf_query.py).

The key table is BOUNDED: at most `osd_perf_query_max_keys` live keys
per query, least-recently-updated evicted first, and keys idle past
`osd_perf_query_key_age` are dropped at dump time — a million
distinct clients cost a million evictions, never a million table
rows.  Eviction counts are part of the dump so the mgr can tell
"quiet cluster" from "table churning".

Client keys are (client_id, session-nonce): a client that reconnects
with a fresh session nonce but a recycled client_id starts a FRESH
key — merging across the nonce would attribute a dead process's ops
to its successor.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

__all__ = ["PerfQueryEngine", "PQ_LAT_BUCKETS_US", "KEY_COLUMNS"]

#: latency histogram bucket upper bounds, microseconds, power-of-two:
#: bucket i counts samples <= 2^(i+1) us; one overflow bucket last.
#: 24 edges -> ~16.8 s ceiling, plenty past any complaint time.
PQ_LAT_BUCKETS_US = tuple(1 << i for i in range(1, 25))

#: the columns a query may key by, in canonical order
KEY_COLUMNS = ("client", "pool", "pg", "object_prefix")


def _client_label(msg) -> str:
    """client.<id>:<session-prefix> — the session nonce keeps two
    incarnations of a recycled client_id apart (attribution
    integrity), the prefix keeps labels short."""
    session = getattr(msg, "session", "") or ""
    return "client.%d:%s" % (getattr(msg, "client_id", 0), session[:8])


class _KeyStats:
    __slots__ = ("ops", "rd_ops", "wr_ops", "rd_bytes", "wr_bytes",
                 "lat_sum", "lat_count", "lat_hist", "last_t", "first_t")

    def __init__(self, now: float):
        self.ops = 0
        self.rd_ops = 0
        self.wr_ops = 0
        self.rd_bytes = 0
        self.wr_bytes = 0
        self.lat_sum = 0.0
        self.lat_count = 0
        self.lat_hist = [0] * (len(PQ_LAT_BUCKETS_US) + 1)
        self.first_t = now
        self.last_t = now

    def add(self, is_read: bool, in_bytes: int, out_bytes: int,
            latency: float, now: float) -> None:
        self.ops += 1
        if is_read:
            self.rd_ops += 1
            self.rd_bytes += out_bytes
        else:
            self.wr_ops += 1
            self.wr_bytes += in_bytes
        self.lat_sum += latency
        self.lat_count += 1
        us = int(latency * 1e6)
        for i, edge in enumerate(PQ_LAT_BUCKETS_US):
            if us <= edge:
                self.lat_hist[i] += 1
                break
        else:
            self.lat_hist[-1] += 1
        self.last_t = now

    def dump(self) -> dict:
        return {"ops": self.ops, "rd_ops": self.rd_ops,
                "wr_ops": self.wr_ops, "rd_bytes": self.rd_bytes,
                "wr_bytes": self.wr_bytes,
                "lat_sum": round(self.lat_sum, 9),
                "lat_count": self.lat_count,
                "lat_hist": list(self.lat_hist)}


class _Query:
    __slots__ = ("query_id", "key_by", "pool", "object_prefix",
                 "max_keys", "table", "evictions")

    def __init__(self, query_id: int, spec: dict, default_max: int):
        self.query_id = query_id
        key_by = spec.get("key_by") or ["client", "pool"]
        # canonical column order regardless of request order
        self.key_by = tuple(c for c in KEY_COLUMNS if c in key_by)
        if not self.key_by:
            self.key_by = ("client", "pool")
        self.pool = spec.get("pool") or None
        self.object_prefix = spec.get("object_prefix") or None
        self.max_keys = int(spec.get("max_keys") or default_max)
        # LRU by last update: OrderedDict with move_to_end on touch
        self.table: OrderedDict[tuple, _KeyStats] = OrderedDict()
        self.evictions = 0

    def spec(self) -> dict:
        return {"key_by": list(self.key_by), "pool": self.pool,
                "object_prefix": self.object_prefix,
                "max_keys": self.max_keys}

    def key_for(self, msg, pool_name: str, pgid) -> tuple | None:
        """The key tuple this op lands on; None = filtered out."""
        if self.pool is not None and pool_name != self.pool:
            return None
        if self.object_prefix is not None and \
                not str(msg.oid).startswith(self.object_prefix):
            return None
        parts = []
        for col in self.key_by:
            if col == "client":
                parts.append(_client_label(msg))
            elif col == "pool":
                parts.append(pool_name)
            elif col == "pg":
                parts.append(str(pgid))
            elif col == "object_prefix":
                parts.append(str(self.object_prefix or ""))
        return tuple(parts)

    def account(self, key: tuple, is_read: bool, in_bytes: int,
                out_bytes: int, latency: float, now: float) -> int:
        """Returns how many keys were evicted making room (the
        least-recently-updated go first past the bound)."""
        st = self.table.get(key)
        if st is None:
            st = self.table[key] = _KeyStats(now)
        else:
            self.table.move_to_end(key)
        st.add(is_read, in_bytes, out_bytes, latency, now)
        evicted = 0
        while len(self.table) > self.max_keys:
            self.table.popitem(last=False)
            self.evictions += 1
            evicted += 1
        return evicted

    def prune(self, now: float, key_age: float) -> None:
        """Drop keys idle past key_age (ageout is NOT an eviction —
        the client left; nothing was displaced)."""
        dead = [k for k, st in self.table.items()
                if now - st.last_t > key_age]
        for k in dead:
            del self.table[k]

    def dump(self) -> dict:
        return {"key_by": list(self.key_by),
                "buckets_us": list(PQ_LAT_BUCKETS_US),
                "evictions": self.evictions,
                "keys": [{"k": list(key), **st.dump()}
                         for key, st in self.table.items()]}


class PerfQueryEngine:
    """The OSD's live subscription table + op-path accounting.

    `wrap_reply` is the single hook point: pg.do_op wraps the reply
    callable once per op (guarded by msg._pq_wrapped against do_op
    re-entry via missing-object parking / waiting_for_active), so
    accounting runs at op COMPLETION with the latency the client saw.
    When no queries are subscribed, `active` is False and the op path
    pays one attribute check — nothing else.
    """

    def __init__(self, conf=None, perf=None):
        self._lock = threading.Lock()
        self._queries: dict[int, _Query] = {}
        self.perf = perf
        self.default_max_keys = 256
        self.key_age = 30.0
        if conf is not None:
            try:
                self.default_max_keys = int(
                    conf.get_val("osd_perf_query_max_keys"))
            except Exception:
                pass
            try:
                self.key_age = float(
                    conf.get_val("osd_perf_query_key_age"))
            except Exception:
                pass

    @property
    def active(self) -> bool:
        return bool(self._queries)

    # -- subscription control (MOSDPerfQuery add/remove/list) ----------

    def add_query(self, query_id: int, spec: dict) -> None:
        """Idempotent: the mgr re-broadcasts its subscription table on
        every osdmap change (so a late-booting OSD catches up), and a
        re-add with the SAME spec must not reset an accumulating
        table."""
        qid = int(query_id)
        q = _Query(qid, spec or {}, self.default_max_keys)
        with self._lock:
            cur = self._queries.get(qid)
            if cur is not None and cur.spec() == q.spec():
                return
            self._queries[qid] = q
        self._update_gauges()

    def remove_query(self, query_id: int) -> bool:
        with self._lock:
            found = self._queries.pop(int(query_id), None) is not None
        self._update_gauges()
        return found

    def list_queries(self) -> dict:
        # str keys: the table rides MOSDPerfQueryReply and asok JSON,
        # where int dict keys would not round-trip
        with self._lock:
            return {str(qid): q.spec()
                    for qid, q in self._queries.items()}

    # -- op-path accounting --------------------------------------------

    def wrap_reply(self, msg, reply_fn, pool_name: str, pgid):
        """Completion-path hook: returns a reply callable that
        accounts the op into every matching query, then forwards."""
        from ..msg.message import OSD_READ_OPS
        start = getattr(msg, "_pq_start", None)
        if start is None:
            start = time.monotonic()
        ops = list(getattr(msg, "ops", ()) or ())
        is_read = bool(ops) and all(op[0] in OSD_READ_OPS for op in ops)
        in_bytes = sum(len(arg) for op_t in ops for arg in op_t
                       if isinstance(arg, (bytes, bytearray)))

        def wrapped(result, data):
            now = time.monotonic()
            out_bytes = 0
            if isinstance(data, (bytes, bytearray)):
                out_bytes = len(data)
            elif isinstance(data, list):
                out_bytes = sum(len(d) for d in data
                                if isinstance(d, (bytes, bytearray)))
            self.account(msg, pool_name, pgid, is_read, in_bytes,
                         out_bytes, now - start, now)
            reply_fn(result, data)

        return wrapped

    def account(self, msg, pool_name: str, pgid, is_read: bool,
                in_bytes: int, out_bytes: int, latency: float,
                now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        sampled, evicted = False, 0
        with self._lock:
            for q in self._queries.values():
                key = q.key_for(msg, pool_name, pgid)
                if key is None:
                    continue
                evicted += q.account(key, is_read, in_bytes,
                                     out_bytes, latency, now)
                sampled = True
        if self.perf is not None:
            if sampled:
                self.perf.inc("l_osd_pq_samples")
            if evicted:
                self.perf.inc("l_osd_pq_evictions", evicted)
        self._update_gauges()

    # -- report-path dump ----------------------------------------------

    def dump(self, now: float | None = None) -> dict:
        """The MMgrReport perf_query payload: {query_id: table dump}.
        Idle keys are pruned here, on the report cadence, so a
        vanished client's key stops shipping within key_age."""
        now = time.monotonic() if now is None else now
        with self._lock:
            for q in self._queries.values():
                q.prune(now, self.key_age)
            out = {str(qid): q.dump()
                   for qid, q in self._queries.items()}
        self._update_gauges()
        return out

    def _update_gauges(self) -> None:
        if self.perf is None:
            return
        with self._lock:
            nq = len(self._queries)
            nk = sum(len(q.table) for q in self._queries.values())
        try:
            self.perf.set("l_osd_pq_queries", nq)
            self.perf.set("l_osd_pq_keys", nk)
        except Exception:
            pass
