"""Object classes: in-OSD methods executed next to the data.

Rendition of the reference's cls/objclass subsystem
(/root/reference/src/objclass/ + src/cls/): plugins register named
classes whose methods run inside the primary OSD against one object,
invoked by clients through the `exec` op. Methods declare RD/WR flags;
a WR method's mutations are staged on a method context and committed
as one transaction.

Per the reference's design, classes are unavailable on erasure-coded
pools: cls methods need synchronous local reads and ECBackend's
objects_read_sync returns -EOPNOTSUPP
(doc/dev/osd_internals/erasure_coding/ecbackend.rst:79-83, enforced in
PG.do_op here).

Built-ins mirror reference classes: `hello` (src/cls/hello/),
`lock` (src/cls/lock/ advisory locks), `refcount`
(src/cls/refcount/).
"""

from __future__ import annotations

import threading
import time

from .. import encoding

__all__ = ["ClassHandler", "MethodContext", "CLS_METHOD_RD",
           "CLS_METHOD_WR"]

CLS_METHOD_RD = 1
CLS_METHOD_WR = 2


class MethodContext:
    """cls_method_context_t: the object view a method runs against.

    Reads come straight from the local store; writes stage into a
    PGTransaction the PG commits after the method returns success.
    """

    def __init__(self, pg, oid):
        from .pg_transaction import PGTransaction
        self.pg = pg
        self.oid = oid
        self.txn = PGTransaction()
        self.wrote = False
        self.removed = False   # final state is "object gone"

    # -- reads ---------------------------------------------------------

    def _cid(self):
        return self.pg.cid_of_shard(self.pg.my_shard())

    def read(self, offset: int = 0, length: int = 0) -> bytes | None:
        try:
            return self.pg.store.read(self._cid(), self.oid, offset,
                                      length)
        except KeyError:
            return None

    def stat(self):
        size = self.pg._object_size(self.oid)
        return None if size is None else {"size": size}

    def getxattr(self, name: str):
        try:
            return self.pg.store.getattr(self._cid(), self.oid, name)
        except KeyError:
            return None

    def omap_get(self) -> dict:
        try:
            return self.pg.store.omap_get(self._cid(), self.oid)
        except KeyError:
            return {}

    # -- staged writes --------------------------------------------------

    def create(self) -> None:
        self.wrote = True
        self.removed = False
        self.txn.create(self.oid)

    def write(self, offset: int, data: bytes) -> None:
        self.wrote = True
        self.removed = False
        self.txn.write(self.oid, offset, data)

    def setxattr(self, name: str, value: bytes) -> None:
        self.wrote = True
        self.removed = False
        self.txn.setattr(self.oid, name, value)

    def rmxattr(self, name: str) -> None:
        self.wrote = True
        self.removed = False
        self.txn.rmattr(self.oid, name)

    def omap_set(self, kv: dict) -> None:
        self.wrote = True
        self.removed = False
        self.txn.omap_setkeys(self.oid, kv)

    def remove(self) -> None:
        self.wrote = True
        self.removed = True
        self.txn.remove(self.oid)


class _Method:
    __slots__ = ("name", "flags", "fn")

    def __init__(self, name, flags, fn):
        self.name = name
        self.flags = flags
        self.fn = fn


class _Class:
    def __init__(self, name: str):
        self.name = name
        self.methods: dict[str, _Method] = {}

    def register_method(self, name: str, flags: int, fn) -> None:
        if name in self.methods:
            raise ValueError("method %s.%s already registered"
                             % (self.name, name))
        self.methods[name] = _Method(name, flags, fn)


class ClassHandler:
    """Process-wide class registry (reference ClassHandler singleton)."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self.classes: dict[str, _Class] = {}

    @classmethod
    def instance(cls) -> "ClassHandler":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    # fully build (builtins included) BEFORE publishing,
                    # so a concurrent first caller never sees an empty
                    # registry
                    inst = cls()
                    _register_builtins(inst)
                    cls._instance = inst
        return cls._instance

    def register_class(self, name: str) -> _Class:
        c = self.classes.get(name)
        if c is None:
            c = self.classes[name] = _Class(name)
        return c

    def get_method(self, cls_name: str, method: str) -> _Method | None:
        c = self.classes.get(cls_name)
        return c.methods.get(method) if c else None


# ---------------------------------------------------------------------------
# built-in classes


def _register_builtins(handler: ClassHandler) -> None:
    # -- hello (src/cls/hello/cls_hello.cc) -----------------------------
    hello = handler.register_class("hello")

    def say_hello(hctx, indata: bytes):
        name = indata.decode() if indata else "world"
        return 0, ("Hello, %s!" % name).encode()

    def record_hello(hctx, indata: bytes):
        if hctx.getxattr("hello.greeted") is not None:
            return -17, b""  # EEXIST: only greet once
        hctx.create()
        hctx.setxattr("hello.greeted", indata or b"world")
        return 0, b""

    hello.register_method("say_hello", CLS_METHOD_RD, say_hello)
    hello.register_method("record_hello",
                          CLS_METHOD_RD | CLS_METHOD_WR, record_hello)

    # -- lock (src/cls/lock/: advisory object locks) --------------------
    lock_cls = handler.register_class("lock")
    LOCK_XATTR = "lock.%s"

    def _load_lock(hctx, name):
        blob = hctx.getxattr(LOCK_XATTR % name)
        return encoding.decode_any(blob) if blob else {"type": None,
                                                "lockers": {}}

    def _prune_expired(st, now):
        # cls_lock lock duration semantics (cls_lock_types.h): a locker
        # with a nonzero duration self-expires, so a crashed holder
        # cannot wedge the object forever
        dead = [c for c, info in st["lockers"].items()
                if info.get("expires") and now > info["expires"]]
        for c in dead:
            del st["lockers"][c]
        if not st["lockers"]:
            st["type"] = None

    def lock_lock(hctx, indata: bytes):
        # {name, cookie, type: exclusive|shared, duration: secs (0=forever)}
        req = encoding.decode_any(indata)
        now = time.time()
        st = _load_lock(hctx, req["name"])
        _prune_expired(st, now)
        if st["lockers"]:
            if st["type"] == "exclusive" or req["type"] == "exclusive":
                if req["cookie"] not in st["lockers"]:
                    return -16, b""  # EBUSY
        duration = float(req.get("duration") or 0.0)
        st["type"] = req["type"]
        st["lockers"][req["cookie"]] = {
            "acquired": now,
            "expires": now + duration if duration else None}
        hctx.setxattr(LOCK_XATTR % req["name"], encoding.encode_any(st))
        return 0, b""

    def lock_break(hctx, indata: bytes):
        # {name, cookie}: forcibly evict another client's locker
        # (cls_lock break_lock, the admin/recovery path)
        req = encoding.decode_any(indata)
        st = _load_lock(hctx, req["name"])
        if req["cookie"] not in st["lockers"]:
            return -2, b""           # ENOENT
        del st["lockers"][req["cookie"]]
        if not st["lockers"]:
            st["type"] = None
        hctx.setxattr(LOCK_XATTR % req["name"], encoding.encode_any(st))
        return 0, b""

    def lock_unlock(hctx, indata: bytes):
        req = encoding.decode_any(indata)   # {name, cookie}
        st = _load_lock(hctx, req["name"])
        if req["cookie"] not in st["lockers"]:
            return -2, b""           # ENOENT
        del st["lockers"][req["cookie"]]
        if not st["lockers"]:
            st["type"] = None
        hctx.setxattr(LOCK_XATTR % req["name"], encoding.encode_any(st))
        return 0, b""

    def lock_get_info(hctx, indata: bytes):
        req = encoding.decode_any(indata)   # {name}
        return 0, encoding.encode_any(_load_lock(hctx, req["name"]))

    lock_cls.register_method("lock", CLS_METHOD_RD | CLS_METHOD_WR,
                             lock_lock)
    lock_cls.register_method("unlock", CLS_METHOD_RD | CLS_METHOD_WR,
                             lock_unlock)
    lock_cls.register_method("break_lock", CLS_METHOD_RD | CLS_METHOD_WR,
                             lock_break)
    lock_cls.register_method("get_info", CLS_METHOD_RD, lock_get_info)

    # -- refcount (src/cls/refcount/) -----------------------------------
    refc = handler.register_class("refcount")
    REF_XATTR = "refcount.refs"

    def _load_refs(hctx):
        blob = hctx.getxattr(REF_XATTR)
        return encoding.decode_any(blob) if blob else set()

    def ref_get(hctx, indata: bytes):
        tag = indata.decode()
        refs = _load_refs(hctx)
        refs.add(tag)
        hctx.setxattr(REF_XATTR, encoding.encode_any(refs))
        return 0, b""

    def ref_put(hctx, indata: bytes):
        tag = indata.decode()
        refs = _load_refs(hctx)
        refs.discard(tag)
        if refs:
            hctx.setxattr(REF_XATTR, encoding.encode_any(refs))
        else:
            # last reference dropped: the object goes away
            hctx.remove()
        return 0, b""

    def ref_read(hctx, indata: bytes):
        return 0, encoding.encode_any(sorted(_load_refs(hctx)))

    refc.register_method("get", CLS_METHOD_RD | CLS_METHOD_WR, ref_get)
    refc.register_method("put", CLS_METHOD_RD | CLS_METHOD_WR, ref_put)
    refc.register_method("read", CLS_METHOD_RD, ref_read)
