"""Cluster map: pools, device states, placement pipeline, bulk mapping.

Role of the reference's OSDMap (src/osd/OSDMap.{h,cc}) and pg_pool_t
(src/osd/osd_types.{h,cc}):

  raw_pg_to_pps     stable_mod + pool-salted rjenkins hash -> the CRUSH
                    input seed (osd_types.cc:1392-1407)
  _pg_to_raw_osds   CRUSH do_rule (OSDMap.cc:1894-1911)
  _apply_upmap      explicit pg_upmap / pg_upmap_items overrides (:1924)
  _raw_to_up_osds   drop down/dne devices — shift for replicated pools,
                    leave CRUSH_ITEM_NONE holes for EC (:1959)
  primary affinity  proportional primary rejection via hash (:1982)
  _get_temp_osds    pg_temp / primary_temp overlay (:2035)
  pg_to_up_acting_osds   the composition every client + OSD runs (:2103)

Incremental mutation mirrors OSDMap::Incremental: the monitor publishes
deltas; everyone applies them to reach the same epoch.

OSDMapMapping + the batched update (update_mapping) is the
ParallelPGMapper analog (src/osd/OSDMapMapping.h:17,169): instead of
sharding PGs over CPU threads, all PG seeds go through ONE batched CRUSH
device call (ceph_tpu.crush.batched), then the cheap overlay steps run
vectorized on host.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field

import numpy as np

from ..crush import hashing
from ..crush.map import (CRUSH_ITEM_NONE, CrushMap, POOL_TYPE_ERASURE,
                         POOL_TYPE_REPLICATED)
from ..crush.mapper_ref import crush_do_rule

__all__ = ["PGID", "PGPool", "OSDMap", "Incremental", "OSDMapMapping",
           "POOL_TYPE_REPLICATED", "POOL_TYPE_ERASURE", "CRUSH_ITEM_NONE"]

DEFAULT_PRIMARY_AFFINITY = 0x10000
MAX_PRIMARY_AFFINITY = 0x10000


def calc_bits_of(n: int) -> int:
    bits = 0
    while n:
        n >>= 1
        bits += 1
    return bits


def stable_mod(x: int, b: int, bmask: int) -> int:
    """ceph_stable_mod (src/include/ceph_hash.h idiom): remap x into
    [0, b) such that growing b splits each bucket in two."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


@dataclass(frozen=True)
class PGID:
    pool: int
    ps: int

    def __str__(self):
        return "%d.%x" % (self.pool, self.ps)


@dataclass
class PGPool:
    """pg_pool_t subset."""

    pool_id: int
    name: str
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 8
    pgp_num: int = 0
    crush_rule: int = 0
    erasure_code_profile: str = ""
    hashpspool: bool = True
    stripe_width: int = 0
    # snapshots (pg_pool_t snap_seq / snaps / removed_snaps)
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)      # name -> snap id
    removed_snaps: list = field(default_factory=list)
    # cache tiering (pg_pool_t tier fields, src/osd/osd_types.h:1230-1320:
    # tier_of / tiers / read_tier / write_tier / cache_mode, hit_set and
    # agent-target knobs)
    tier_of: int = -1                  # base pool this pool caches for
    tiers: list = field(default_factory=list)   # cache pools over us
    read_tier: int = -1                # overlay: reads redirect here
    write_tier: int = -1               # overlay: writes redirect here
    cache_mode: str = "none"     # none|writeback|readproxy|readonly|forward
    hit_set_count: int = 4
    hit_set_period: int = 0            # seconds; 0 disables hit sets
    hit_set_fpp: float = 0.05          # bloom false-positive target
    target_max_objects: int = 0
    target_max_bytes: int = 0
    cache_target_dirty_ratio: float = 0.4
    cache_target_full_ratio: float = 0.8
    cache_min_flush_age: int = 0       # seconds
    cache_min_evict_age: int = 0       # seconds
    # dmclock QoS profile (rides the osdmap into every OSD's op-queue
    # shards as a dedicated "client:<pool>" class; 0/0/0 = no profile)
    qos_reservation: float = 0.0       # ops/s reserved cluster-wide
    qos_weight: float = 0.0            # relative share; 0 = inherit
    qos_limit: float = 0.0             # ops/s cap; 0 = unlimited

    def has_qos(self) -> bool:
        return (self.qos_reservation > 0 or self.qos_weight > 0
                or self.qos_limit > 0)

    def snap_context(self) -> tuple:
        """Pool-snap SnapContext for writes: (seq, ids descending)."""
        return (self.snap_seq,
                tuple(sorted(self.snaps.values(), reverse=True)))

    def __post_init__(self):
        if self.pgp_num == 0:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return (1 << calc_bits_of(self.pg_num - 1)) - 1

    @property
    def pgp_num_mask(self) -> int:
        return (1 << calc_bits_of(self.pgp_num - 1)) - 1

    def can_shift_osds(self) -> bool:
        # replicated pools shift gaps away; EC pools keep positional
        # holes (osd_types.h can_shift_osds)
        return self.type == POOL_TYPE_REPLICATED

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def is_tier(self) -> bool:
        """Is this pool a cache tier over another pool?
        (pg_pool_t::is_tier)"""
        return self.tier_of >= 0

    def has_tiers(self) -> bool:
        return bool(self.tiers)

    def raw_pg_to_pg(self, pgid: PGID) -> PGID:
        return PGID(pgid.pool,
                    stable_mod(pgid.ps, self.pg_num, self.pg_num_mask))

    def raw_pg_to_pps(self, pgid: PGID) -> int:
        if self.hashpspool:
            return int(hashing.hash32_2(
                stable_mod(pgid.ps, self.pgp_num, self.pgp_num_mask),
                pgid.pool))
        return stable_mod(pgid.ps, self.pgp_num,
                          self.pgp_num_mask) + pgid.pool


class Incremental:
    """OSDMap::Incremental: the delta the monitor publishes per epoch."""

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.new_pools: dict[int, PGPool] = {}
        self.old_pools: list[int] = []
        self.new_up: dict[int, object] = {}      # osd -> addr
        self.new_down: list[int] = []
        self.new_weight: dict[int, int] = {}     # osd -> 16.16
        self.new_primary_affinity: dict[int, int] = {}
        self.new_pg_temp: dict[PGID, list] = {}  # [] clears
        self.new_primary_temp: dict[PGID, int] = {}
        self.new_pg_upmap: dict[PGID, list] = {}
        self.old_pg_upmap: list[PGID] = []
        self.new_pg_upmap_items: dict[PGID, list] = {}
        self.old_pg_upmap_items: list[PGID] = []
        self.new_max_osd: int | None = None
        self.new_crush: CrushMap | None = None
        self.new_ec_profiles: dict[str, dict] = {}

    def overlay_only(self) -> bool:
        """True when this inc only touches per-PG overlays (pg_temp /
        primary_temp / upmap) or down-marks — the churn classes whose
        affected-PG set is exactly enumerable, so a precomputed
        mapping can advance without a full CRUSH re-sweep.  Weight,
        boot, pool and crush changes move raw placements and need the
        sweep."""
        return not (self.new_pools or self.old_pools or self.new_up
                    or self.new_weight or self.new_primary_affinity
                    or self.new_max_osd is not None
                    or self.new_crush is not None)

    def overlay_pgs(self) -> set:
        """The raw PGIDs named by this inc's overlay entries."""
        pgs: set = set()
        for d in (self.new_pg_temp, self.new_primary_temp,
                  self.new_pg_upmap, self.new_pg_upmap_items):
            pgs.update(d.keys())
        pgs.update(self.old_pg_upmap)
        pgs.update(self.old_pg_upmap_items)
        return pgs


class OSDMap:
    def __init__(self):
        self.epoch = 0
        self.max_osd = 0
        self.crush = CrushMap()
        self.pools: dict[int, PGPool] = {}
        self.osd_exists: list[bool] = []
        self.osd_up: list[bool] = []
        self.osd_weight: list[int] = []          # 16.16; 0 = out
        self.osd_addrs: dict[int, object] = {}
        self.osd_primary_affinity: list[int] | None = None
        self.pg_temp: dict[PGID, list] = {}
        self.primary_temp: dict[PGID, int] = {}
        self.pg_upmap: dict[PGID, list] = {}
        self.pg_upmap_items: dict[PGID, list] = {}
        # erasure-code profiles ride in the map (OSDMap::erasure_code_profiles)
        self.ec_profiles: dict[str, dict] = {}

    # -- device state --------------------------------------------------

    def set_max_osd(self, n: int) -> None:
        while len(self.osd_exists) < n:
            self.osd_exists.append(False)
            self.osd_up.append(False)
            self.osd_weight.append(0)
        self.max_osd = n

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and self.osd_exists[osd]

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and self.osd_up[osd]

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_out(self, osd: int) -> bool:
        return not self.exists(osd) or self.osd_weight[osd] == 0

    def is_in(self, osd: int) -> bool:
        return not self.is_out(osd)

    def get_addr(self, osd: int):
        return self.osd_addrs.get(osd)

    def get_up_osds(self) -> list[int]:
        return [o for o in range(self.max_osd) if self.is_up(o)]

    # -- incremental apply --------------------------------------------

    def apply_incremental(self, inc: Incremental) -> None:
        assert inc.epoch == self.epoch + 1, \
            "incremental %d vs epoch %d" % (inc.epoch, self.epoch)
        self.epoch = inc.epoch
        if inc.new_max_osd is not None:
            self.set_max_osd(inc.new_max_osd)
        if inc.new_crush is not None:
            self.crush = inc.new_crush
        for pool_id, pool in inc.new_pools.items():
            self.pools[pool_id] = pool
        for pool_id in inc.old_pools:
            self.pools.pop(pool_id, None)
        for osd, addr in inc.new_up.items():
            if osd >= self.max_osd:
                self.set_max_osd(osd + 1)
            self.osd_exists[osd] = True
            self.osd_up[osd] = True
            self.osd_addrs[osd] = addr
            if self.osd_weight[osd] == 0:
                self.osd_weight[osd] = 0x10000
        for osd in inc.new_down:
            if 0 <= osd < self.max_osd:
                self.osd_up[osd] = False
        for osd, w in inc.new_weight.items():
            if osd >= self.max_osd:
                self.set_max_osd(osd + 1)
            self.osd_exists[osd] = True
            self.osd_weight[osd] = w
        for osd, a in inc.new_primary_affinity.items():
            if self.osd_primary_affinity is None:
                self.osd_primary_affinity = \
                    [DEFAULT_PRIMARY_AFFINITY] * max(self.max_osd, osd + 1)
            while len(self.osd_primary_affinity) <= osd:
                self.osd_primary_affinity.append(DEFAULT_PRIMARY_AFFINITY)
            self.osd_primary_affinity[osd] = a
        for pgid, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pgid] = list(osds)
            else:
                self.pg_temp.pop(pgid, None)
        for pgid, osd in inc.new_primary_temp.items():
            if osd == -1:
                self.primary_temp.pop(pgid, None)
            else:
                self.primary_temp[pgid] = osd
        for pgid, osds in inc.new_pg_upmap.items():
            self.pg_upmap[pgid] = list(osds)
        for pgid in inc.old_pg_upmap:
            self.pg_upmap.pop(pgid, None)
        for pgid, items in inc.new_pg_upmap_items.items():
            self.pg_upmap_items[pgid] = list(items)
        for pgid in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pgid, None)
        self.ec_profiles.update(inc.new_ec_profiles)

    def clone(self) -> "OSDMap":
        return copy.deepcopy(self)

    # -- placement pipeline (OSDMap.cc:1894-2160) ----------------------

    def _pg_to_raw_osds(self, pool: PGPool, pgid: PGID):
        pps = pool.raw_pg_to_pps(pgid)
        ruleno = pool.crush_rule
        osds: list[int] = []
        if 0 <= ruleno < len(self.crush.rules):
            # pool id selects the choose_args set, falling back to the
            # default set (OSDMap.cc passes the pool id as the
            # choose_args index; the balancer writes per-pool or
            # default weight-sets)
            osds = crush_do_rule(self.crush, ruleno, pps, pool.size,
                                 self._weight_vector(),
                                 choose_args=pgid.pool)
        self._remove_nonexistent_osds(pool, osds)
        return osds, pps

    def _weight_vector(self):
        n = max(self.max_osd, self.crush.max_devices)
        w = np.zeros(n, dtype=np.int64)
        for osd in range(min(self.max_osd, n)):
            if self.osd_exists[osd]:
                w[osd] = self.osd_weight[osd]
        return w

    def _remove_nonexistent_osds(self, pool: PGPool, osds: list) -> None:
        # OSDMap::_remove_nonexistent_osds (OSDMap.cc:1870-1892): shift
        # out dne devices for replicated pools, hole them for EC
        if pool.can_shift_osds():
            osds[:] = [o for o in osds
                       if o != CRUSH_ITEM_NONE and self.exists(o)]
        else:
            osds[:] = [o if (o == CRUSH_ITEM_NONE or self.exists(o))
                       else CRUSH_ITEM_NONE for o in osds]

    def _apply_upmap(self, pool: PGPool, raw_pg: PGID, raw: list) -> list:
        pg = pool.raw_pg_to_pg(raw_pg)
        upmap = self.pg_upmap.get(pg)
        if upmap:
            if not any(o != CRUSH_ITEM_NONE and o < self.max_osd
                       and self.osd_weight[o] == 0 for o in upmap):
                raw = list(upmap)
        items = self.pg_upmap_items.get(pg)
        if items:
            raw = list(raw)
            for i, osd in enumerate(raw):
                for src, dst in items:
                    if src != osd:
                        continue
                    if not (dst != CRUSH_ITEM_NONE and dst < self.max_osd
                            and self.osd_weight[dst] == 0):
                        raw[i] = dst
                    break
        return raw

    def _raw_to_up_osds(self, pool: PGPool, raw: list) -> list:
        if pool.can_shift_osds():
            return [o for o in raw
                    if o != CRUSH_ITEM_NONE and self.exists(o)
                    and not self.is_down(o)]
        return [o if (o != CRUSH_ITEM_NONE and self.exists(o)
                      and not self.is_down(o)) else CRUSH_ITEM_NONE
                for o in raw]

    @staticmethod
    def _pick_primary(osds: list) -> int:
        for osd in osds:
            if osd != CRUSH_ITEM_NONE:
                return osd
        return -1

    def _apply_primary_affinity(self, seed: int, pool: PGPool,
                                osds: list, primary: int):
        pa = self.osd_primary_affinity
        if pa is None:
            return osds, primary
        if not any(o != CRUSH_ITEM_NONE and o < len(pa)
                   and pa[o] != DEFAULT_PRIMARY_AFFINITY for o in osds):
            return osds, primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = pa[o] if o < len(pa) else DEFAULT_PRIMARY_AFFINITY
            if a < MAX_PRIMARY_AFFINITY and \
                    (int(hashing.hash32_2(seed, o)) >> 16) >= a:
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return osds, primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            osds = [osds[pos]] + osds[:pos] + osds[pos + 1:]
        return osds, primary

    def _get_temp_osds(self, pool: PGPool, pgid: PGID):
        pg = pool.raw_pg_to_pg(pgid)
        temp_pg: list[int] = []
        for osd in self.pg_temp.get(pg, []):
            if not self.exists(osd) or self.is_down(osd):
                if not pool.can_shift_osds():
                    temp_pg.append(CRUSH_ITEM_NONE)
            else:
                temp_pg.append(osd)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp_pg:
            temp_primary = self._pick_primary(temp_pg)
        return temp_pg, temp_primary

    def pg_to_raw_osds(self, pgid: PGID):
        pool = self.pools.get(pgid.pool)
        if pool is None:
            return [], -1
        raw, _ = self._pg_to_raw_osds(pool, pgid)
        return raw, self._pick_primary(raw)

    def pg_to_up_acting_osds(self, pgid: PGID):
        """Returns (up, up_primary, acting, acting_primary)."""
        pool = self.pools.get(pgid.pool)
        if pool is None or pgid.ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pgid)
        raw, pps = self._pg_to_raw_osds(pool, pgid)
        raw = self._apply_upmap(pool, pgid, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(pps, pool, up,
                                                      up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def object_to_pg(self, pool_id: int, name: str) -> PGID:
        """Hash an object name into its raw PG (the librados locator
        path: ceph_str_hash_rjenkins(name) -> ps)."""
        return PGID(pool_id, str_hash_rjenkins(name))


_M32 = 0xFFFFFFFF


def _mix32(a: int, b: int, c: int):
    """Jenkins mix on plain python ints (ceph_hash.cc mix macro)."""
    a = (a - b - c) & _M32; a ^= c >> 13            # noqa: E702
    b = (b - c - a) & _M32; b = (b ^ (a << 8)) & _M32   # noqa: E702
    c = (c - a - b) & _M32; c ^= b >> 13            # noqa: E702
    a = (a - b - c) & _M32; a ^= c >> 12            # noqa: E702
    b = (b - c - a) & _M32; b = (b ^ (a << 16)) & _M32  # noqa: E702
    c = (c - a - b) & _M32; c ^= b >> 5             # noqa: E702
    a = (a - b - c) & _M32; a ^= c >> 3             # noqa: E702
    b = (b - c - a) & _M32; b = (b ^ (a << 10)) & _M32  # noqa: E702
    c = (c - a - b) & _M32; c ^= b >> 15            # noqa: E702
    return a, b, c


def str_hash_rjenkins(name) -> int:
    """ceph_str_hash_rjenkins (src/common/ceph_hash.cc:21-77), exact:
    12-byte little-endian blocks mixed, tail bytes shifted into place
    with c's low byte reserved for the length."""
    k = name.encode() if isinstance(name, str) else bytes(name)
    a = b = 0x9E3779B9
    c = 0
    i, length = 0, len(k)
    while length - i >= 12:
        a = (a + int.from_bytes(k[i:i + 4], "little")) & _M32
        b = (b + int.from_bytes(k[i + 4:i + 8], "little")) & _M32
        c = (c + int.from_bytes(k[i + 8:i + 12], "little")) & _M32
        a, b, c = _mix32(a, b, c)
        i += 12
    tail = k[i:]
    n = len(tail)
    c = (c + length) & _M32
    shifts_c = {10: 24, 9: 16, 8: 8}   # k[10]<<24, k[9]<<16, k[8]<<8
    for idx in (10, 9, 8):
        if n > idx:
            c = (c + (tail[idx] << shifts_c[idx])) & _M32
    for idx, shift in ((7, 24), (6, 16), (5, 8), (4, 0)):
        if n > idx:
            b = (b + (tail[idx] << shift)) & _M32
    for idx, shift in ((3, 24), (2, 16), (1, 8), (0, 0)):
        if n > idx:
            a = (a + (tail[idx] << shift)) & _M32
    _, _, c = _mix32(a, b, c)
    return c


class OSDMapMapping:
    """Precomputed full-cluster mapping (OSDMapMapping.h:169) with the
    batched device recompute standing in for ParallelPGMapper."""

    def __init__(self):
        self.epoch = -1
        self.by_pg: dict[PGID, tuple] = {}
        self.by_osd: dict[int, list] = {}

    def update(self, osdmap: OSDMap, batched: bool = True,
               mesh=None, native: bool = False) -> None:
        """Recompute every pool's PG mappings. With batched=True the
        CRUSH step for each pool's whole PG range runs as one device
        call (ceph_tpu.crush.batched.batched_do_rule); with mesh set
        (True for the default local-device mesh, or an explicit 1-axis
        jax Mesh) the PG batch is additionally sharded across chips
        (ceph_tpu.crush.batched.mesh_do_rule).  native=True routes the
        bulk sweep through the compiled C mapper instead
        (crush_do_rule_batch_native — the host-side ParallelPGMapper
        analogue, bit-identical to the device kernels): on a CPU-only
        host the device paths pay XLA emulation cost per seed, while a
        datacenter-scale balancer round needs 10^5 placements per
        sweep.  Falls back to the device path if the native lib is not
        built."""
        self.by_pg.clear()
        self.by_osd = {o: [] for o in range(osdmap.max_osd)}
        mesh_obj = None
        if mesh is not None and mesh is not False:
            from ..crush.batched import make_batch_mesh
            mesh_obj = make_batch_mesh() if mesh is True else mesh
        for pool_id, pool in osdmap.pools.items():
            pgids = [PGID(pool_id, ps) for ps in range(pool.pg_num)]
            raws = None
            if batched and 0 <= pool.crush_rule < len(osdmap.crush.rules):
                from ..crush.batched import batched_do_rule, mesh_do_rule
                seeds = np.array([pool.raw_pg_to_pps(p) for p in pgids],
                                 dtype=np.int64)
                mat = None
                if native:
                    try:
                        from ..native import crush_do_rule_batch_native
                        mat = crush_do_rule_batch_native(
                            osdmap.crush, pool.crush_rule, seeds,
                            pool.size, osdmap._weight_vector(),
                            choose_args=pool_id)
                    except Exception:
                        mat = None    # lib not built: device fallback
                if mat is None and mesh_obj is not None:
                    mat = mesh_do_rule(osdmap.crush, pool.crush_rule,
                                       seeds, pool.size,
                                       osdmap._weight_vector(),
                                       mesh=mesh_obj,
                                       choose_args=pool_id)
                elif mat is None:
                    mat = batched_do_rule(osdmap.crush, pool.crush_rule,
                                          seeds, pool.size,
                                          osdmap._weight_vector(),
                                          choose_args=pool_id)
                raws = [[int(v) for v in row[:pool.size]] for row in mat]
            for i, pgid in enumerate(pgids):
                if raws is not None:
                    raw = list(raws[i])
                    osdmap._remove_nonexistent_osds(pool, raw)
                    raw = osdmap._apply_upmap(pool, pgid, raw)
                    up = osdmap._raw_to_up_osds(pool, raw)
                    up_primary = osdmap._pick_primary(up)
                    up, up_primary = osdmap._apply_primary_affinity(
                        pool.raw_pg_to_pps(pgid), pool, up, up_primary)
                    acting, acting_primary = osdmap._get_temp_osds(
                        pool, pgid)
                    if not acting:
                        acting = list(up)
                        if acting_primary == -1:
                            acting_primary = up_primary
                else:
                    up, up_primary, acting, acting_primary = \
                        osdmap.pg_to_up_acting_osds(pgid)
                self.by_pg[pgid] = (up, up_primary, acting,
                                    acting_primary)
                for osd in acting:
                    if osd != CRUSH_ITEM_NONE and osd in self.by_osd:
                        self.by_osd[osd].append(pgid)
        self.epoch = osdmap.epoch

    def apply_incremental(self, osdmap: OSDMap, inc: Incremental,
                          batched: bool = True, mesh=None) -> dict:
        """Advance the precomputed mapping by one epoch touching only
        the PGs the inc can move (ISSUE 19: sub-linear apply).  The
        caller applies `inc` to `osdmap` FIRST; this then either

          - recomputes exactly the affected PG set on the host path
            (overlay-only incs: pg_temp / primary_temp / upmap edits
            and down-marks — the steady-state churn classes at 10^5+
            PGs), or
          - falls back to the full batched/mesh sweep when raw
            placements moved (weight, boot, pool, crush changes).

        Returns {"mode": "incremental"|"full", "recomputed": n}."""
        if osdmap.epoch != inc.epoch or self.epoch != inc.epoch - 1 \
                or not inc.overlay_only():
            self.update(osdmap, batched=batched, mesh=mesh)
            return {"mode": "full", "recomputed": len(self.by_pg)}
        affected: set[PGID] = set()
        for pgid in inc.overlay_pgs():
            pool = osdmap.pools.get(pgid.pool)
            if pool is not None:
                affected.add(pool.raw_pg_to_pg(pgid))
        for osd in inc.new_down:
            # a downed osd only moves PGs it served: its acting set,
            # plus pg_temp'd PGs where it sat in `up` but not acting
            affected.update(self.by_osd.get(osd, []))
            for pg in osdmap.pg_temp:
                row = self.by_pg.get(pg)
                if row is not None and osd in row[0]:
                    affected.add(pg)
        for pgid in affected:
            old = self.by_pg.get(pgid)
            if old is not None:
                for osd in old[2]:
                    lst = self.by_osd.get(osd)
                    if lst is not None and pgid in lst:
                        lst.remove(pgid)
            up, upp, acting, actp = osdmap.pg_to_up_acting_osds(pgid)
            if not up and not acting and old is None:
                continue
            self.by_pg[pgid] = (up, upp, acting, actp)
            for osd in acting:
                if osd != CRUSH_ITEM_NONE:
                    self.by_osd.setdefault(osd, []).append(pgid)
        self.epoch = inc.epoch
        return {"mode": "incremental", "recomputed": len(affected)}

    def get(self, pgid: PGID):
        return self.by_pg.get(pgid)

    def get_osd_acting_pgs(self, osd: int) -> list:
        return self.by_osd.get(osd, [])
