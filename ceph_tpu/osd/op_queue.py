"""QoS op queues: weighted-priority and dmClock scheduling.

Renditions of the reference's OSD op-queue disciplines, selected by the
`osd_op_queue` option (src/common/options.cc):

  WeightedPriorityQueue   src/common/WeightedPriorityQueue.h — a strict
                          band for high-priority ops plus deficit-
                          weighted round-robin across priority buckets,
                          so a flood of low-priority work (recovery,
                          scrub) cannot starve client ops but still
                          makes progress.
  MClockOpClassQueue      src/osd/mClockOpClassQueue.{h,cc} over the
                          vendored dmclock library (src/dmclock/):
                          per-op-class (client / recovery / scrub /
                          snaptrim) reservation + weight + limit tags;
                          reservations are served first, spare capacity
                          is shared by weight, and limits cap a class
                          even when the device is idle.

`QosShardedOpWQ` is the ShardedOpWQ shape (hash key -> shard, one
worker per shard preserving per-PG ordering) with one of these queues
inside each shard.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import OrderedDict, deque

__all__ = ["OpQueue", "WeightedPriorityQueue", "MClockOpClassQueue",
           "QosShardedOpWQ", "make_op_queue"]


class OpQueue:
    """Discipline contract (src/common/OpQueue.h)."""

    def enqueue(self, klass: str, priority: int, cost: int, item) -> None:
        raise NotImplementedError

    def enqueue_strict(self, klass: str, priority: int, item) -> None:
        raise NotImplementedError

    def dequeue(self, now: float | None = None):
        """Next item, or None when every class is limit-throttled."""
        raise NotImplementedError

    def next_ready_in(self, now: float | None = None) -> float | None:
        """Seconds until a throttled head becomes eligible (None = no
        throttled work)."""
        return None

    def empty(self) -> bool:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class WeightedPriorityQueue(OpQueue):
    """Strict band + deficit-weighted round-robin buckets.

    Ops enqueued strict dequeue first, highest priority first, FIFO
    within. Normal ops land in per-priority buckets; each round-robin
    visit grants a bucket `priority` worth of deficit and it drains
    cost units against it — bandwidth proportional to priority, order
    preserved within a bucket.
    """

    def __init__(self, min_cost: int = 4096):
        self.min_cost = min_cost
        self._strict: dict[int, deque] = {}  # priority -> FIFO
        self._strict_prios: list[int] = []   # sorted ascending
        self._buckets: "OrderedDict[int, deque]" = OrderedDict()
        self._deficit: dict[int, float] = {}
        self._size = 0

    def enqueue(self, klass, priority, cost, item):
        b = self._buckets.get(priority)
        if b is None:
            b = self._buckets[priority] = deque()
            self._deficit.setdefault(priority, 0.0)
        b.append((max(cost, 0), item))
        self._size += 1

    def enqueue_strict(self, klass, priority, item):
        # strict band: highest priority first, FIFO within; per-priority
        # deques keep every pop O(1) even under a peering storm
        band = self._strict.get(priority)
        if band is None:
            band = self._strict[priority] = deque()
            bisect.insort(self._strict_prios, priority)
        band.append(item)
        self._size += 1

    def _cost_units(self, cost: int) -> float:
        return max(cost, self.min_cost) / self.min_cost

    def dequeue(self, now=None):
        if self._strict_prios:
            prio = self._strict_prios[-1]
            band = self._strict[prio]
            item = band.popleft()
            if not band:
                del self._strict[prio]
                self._strict_prios.pop()
            self._size -= 1
            return item
        # Deficit round robin: a bucket at the front keeps serving while
        # its deficit covers the head's cost, then earns `priority` more
        # and rotates — so over a full rotation each priority p drains
        # ~p/cost items and bandwidth is proportional to priority.
        # Deficit grows only while unaffordable, so it stays bounded and
        # the loop terminates.
        while self._buckets:
            priority, bucket = next(iter(self._buckets.items()))
            if self._deficit[priority] >= self._cost_units(bucket[0][0]):
                cost, item = bucket.popleft()
                self._deficit[priority] -= self._cost_units(cost)
                self._size -= 1
                if not bucket:
                    del self._buckets[priority]
                    del self._deficit[priority]
                return item
            # quantum floor of 1: a zero/negative priority must still
            # make progress or the shard worker spins forever on it
            self._deficit[priority] += max(priority, 1)
            self._buckets.move_to_end(priority)
        return None

    def empty(self) -> bool:
        return self._size == 0

    def __len__(self) -> int:
        return self._size


class _MClass:
    __slots__ = ("reservation", "weight", "limit", "q",
                 "r_tag", "p_tag", "l_tag")

    def __init__(self, reservation: float, weight: float, limit: float):
        self.reservation = reservation
        self.weight = weight
        self.limit = limit
        self.q: deque = deque()     # (r, p, l, item) per-op tags
        # None = never active: the first op of a (re)activated class
        # tags at `now` (dmclock's new-client rule) and only rate debt
        # pushes tags into the future
        self.r_tag: float | None = None
        self.p_tag: float | None = None
        self.l_tag: float | None = None


class MClockOpClassQueue(OpQueue):
    """dmClock over op classes.

    client_info: {class: (reservation_ops_per_s, weight, limit_ops_per_s)}
    (0 reservation = none; 0 limit = unlimited). Dequeue serves overdue
    reservations first (min r-tag <= now), then shares by weight among
    classes under their limit; returns None when everything queued is
    limit-throttled (next_ready_in says how long).
    """

    DEFAULT_INFO = {
        "client": (0.0, 500.0, 0.0),
        "osd_subop": (0.0, 500.0, 0.0),
        "recovery": (0.0, 1.0, 0.0),
        "scrub": (0.0, 1.0, 0.0),
        "snaptrim": (0.0, 1.0, 0.0),
    }

    def __init__(self, client_info: dict | None = None,
                 min_cost: int = 4096):
        self.info = dict(self.DEFAULT_INFO)
        if client_info:
            self.info.update(client_info)
        self.min_cost = min_cost
        self._classes: dict[str, _MClass] = {}
        self._strict: deque = deque()
        self._size = 0

    def _class(self, klass: str) -> _MClass:
        c = self._classes.get(klass)
        if c is None:
            res, wgt, lim = self.info.get(klass, (0.0, 1.0, 0.0))
            c = self._classes[klass] = _MClass(res, wgt, lim)
        return c

    @staticmethod
    def _next_tag(prev: float | None, rate: float, scale: float,
                  now: float) -> float:
        """max(now, prev + scale/rate); a fresh/long-idle class tags at
        now so its first op is immediately eligible."""
        if prev is None:
            return now
        return max(now, prev + scale / rate)

    def enqueue(self, klass, priority, cost, item):
        now = time.monotonic()
        c = self._class(klass)
        # normalize byte cost into units so weights stay the dominant
        # signal (raw bytes would advance a 1MB client op's tag by
        # minutes and invert the configured client:recovery ratio)
        scale = max(cost, self.min_cost) / self.min_cost
        if not c.q:
            # re-activation after a drain: clamp accumulated debt down
            # to `now` so a burst's leftover tags don't exile the class
            # for minutes — the next tag still advances by scale/rate
            # from now, so a trickler is paced at its configured share
            # rather than evading it (dequeue-side tag resets would
            # allow exactly that evasion)
            for attr in ("r_tag", "p_tag", "l_tag"):
                prev = getattr(c, attr)
                if prev is not None and prev > now:
                    setattr(c, attr, now)
        if c.reservation > 0:
            r = self._next_tag(c.r_tag, c.reservation, scale, now)
            c.r_tag = r
        else:
            r = float("inf")
        p = self._next_tag(c.p_tag, c.weight, scale, now)
        c.p_tag = p
        if c.limit > 0:
            lim = self._next_tag(c.l_tag, c.limit, scale, now)
            c.l_tag = lim
        else:
            lim = 0.0
        c.q.append((r, p, lim, item))
        self._size += 1

    def enqueue_strict(self, klass, priority, item):
        self._strict.append(item)
        self._size += 1

    def dequeue(self, now=None):
        if self._strict:
            self._size -= 1
            return self._strict.popleft()
        now = time.monotonic() if now is None else now
        # reservation phase
        best = None
        for klass, c in self._classes.items():
            if c.q and c.q[0][0] <= now:
                if best is None or c.q[0][0] < best[0]:
                    best = (c.q[0][0], c)
        if best is None:
            # proportional phase (limit-gated)
            for klass, c in self._classes.items():
                if c.q and c.q[0][2] <= now:
                    if best is None or c.q[0][1] < best[0]:
                        best = (c.q[0][1], c)
        if best is not None:
            _, _, _, item = best[1].q.popleft()
            self._size -= 1
            return item
        return None

    def next_ready_in(self, now=None):
        now = time.monotonic() if now is None else now
        # a head op becomes serviceable at the earlier of its
        # reservation tag and its limit tag (dequeue serves the
        # r-phase first), so the wait must take min over both
        waits = [min(c.q[0][0], c.q[0][2]) - now
                 for c in self._classes.values() if c.q]
        return max(0.0, min(waits)) if waits else None

    def empty(self) -> bool:
        return self._size == 0

    def __len__(self) -> int:
        return self._size


def make_op_queue(conf=None) -> OpQueue | None:
    """Build the discipline named by osd_op_queue; None means plain FIFO."""
    name = conf.get_val("osd_op_queue") if conf is not None else "wpq"
    if name == "wpq":
        return WeightedPriorityQueue()
    if name == "mclock_opclass":
        info = {}
        for klass in ("client", "recovery"):
            info[klass] = (
                conf.get_val("osd_op_queue_mclock_%s_res" % klass),
                conf.get_val("osd_op_queue_mclock_%s_wgt" % klass),
                conf.get_val("osd_op_queue_mclock_%s_lim" % klass))
        return MClockOpClassQueue(info)
    if name == "fifo":
        return None
    raise ValueError("unknown osd_op_queue %r" % name)


class QosShardedOpWQ:
    """ShardedOpWQ with a QoS discipline inside each shard.

    Same contract as ShardedThreadPool (hash key -> shard, one worker
    per shard => per-PG ordering within a priority class), but each
    shard drains an OpQueue so client ops outrank recovery/scrub work.
    """

    def __init__(self, name: str, num_shards: int, queue_factory,
                 hbmap=None, grace: float = 30.0):
        self.name = name
        self.num_shards = num_shards
        self._shards = [_QosShard("%s-s%d" % (name, i), queue_factory(),
                                  hbmap, grace)
                        for i in range(num_shards)]

    def start(self) -> None:
        for s in self._shards:
            s.start()

    def queue(self, key, fn, *args, klass: str = "client",
              priority: int = 63, cost: int = 0) -> None:
        self._shards[hash(key) % self.num_shards].enqueue(
            klass, priority, cost, (fn, args))

    def drain(self) -> None:
        for s in self._shards:
            s.drain()

    def stop(self) -> None:
        for s in self._shards:
            s.stop()


class _QosShard:
    def __init__(self, name: str, opq: OpQueue, hbmap, grace: float):
        self.name = name
        self.opq = opq
        self._hbmap = hbmap
        self._grace = grace
        # idle wakeups must outpace the heartbeat grace or an idle
        # shard reads as wedged
        self._wait_cap = min(1.0, grace / 2) if hbmap else 1.0
        self._cond = threading.Condition()
        self._stopping = False
        self._inflight = 0
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._worker,
                                        name=self.name, daemon=True)
        self._thread.start()

    def enqueue(self, klass, priority, cost, item) -> None:
        with self._cond:
            self.opq.enqueue(klass, priority, cost, item)
            self._cond.notify()

    def _worker(self) -> None:
        handle = self._hbmap.add(self.name, self._grace) \
            if self._hbmap else None
        while True:
            with self._cond:
                while True:
                    if handle:  # idle loops must stay visibly alive
                        handle.renew()
                    if self._stopping:
                        # drain before exit (ShardedThreadPool parity:
                        # its shutdown sentinel sits BEHIND pending
                        # work); limits are bypassed — a stopping OSD
                        # must not strand throttled replies
                        item = self.opq.dequeue(now=float("inf"))
                        if item is None:
                            if handle:
                                handle.remove()
                            return
                        self._inflight += 1
                        break
                    item = self.opq.dequeue()
                    if item is not None:
                        self._inflight += 1
                        break
                    wait = self.opq.next_ready_in()
                    self._cond.wait(min(wait, self._wait_cap)
                                    if wait is not None
                                    else self._wait_cap)
            if handle:
                handle.renew()
            fn, args = item
            try:
                fn(*args)
            except Exception:
                import traceback
                traceback.print_exc()
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def drain(self) -> None:
        with self._cond:
            while not self.opq.empty() or self._inflight:
                self._cond.wait(0.01)

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            # unbounded: stop() guarantees the drain completed — a
            # timed join would let shutdown race the very replies the
            # drain protects
            self._thread.join()
