"""QoS op queues: weighted-priority and dmClock scheduling.

Renditions of the reference's OSD op-queue disciplines, selected by the
`osd_op_queue` option (src/common/options.cc):

  WeightedPriorityQueue   src/common/WeightedPriorityQueue.h — a strict
                          band for high-priority ops plus deficit-
                          weighted round-robin across priority buckets,
                          so a flood of low-priority work (recovery,
                          scrub) cannot starve client ops but still
                          makes progress.
  MClockOpClassQueue      src/osd/mClockOpClassQueue.{h,cc} over the
                          vendored dmclock library (src/dmclock/):
                          per-op-class (client / recovery / scrub /
                          snaptrim) reservation + weight + limit tags;
                          reservations are served first, spare capacity
                          is shared by weight, and limits cap a class
                          even when the device is idle.

`QosShardedOpWQ` is the ShardedOpWQ shape (hash key -> shard, one
worker per shard preserving per-PG ordering) with one of these queues
inside each shard.

dmClock extensions (the *distributed* half, Gulati et al. OSDI'10):

  per-pool classes    a pool with a QoS profile (pg_pool_t
                      qos_reservation/qos_weight/qos_limit riding the
                      osdmap) splits its client ops into a dedicated
                      "client:<pool>" class per shard, so one pool's
                      reservation cannot be consumed by another's
                      flood; reservation/limit rates are divided by
                      the shard count (each shard runs its own tags).
  delta/rho feedback  clients stamp each op with the service they
                      received cluster-wide since their previous op to
                      THIS osd (delta = all completions, rho =
                      reservation-phase completions, both in min_cost
                      units).  Tags advance by (rho+cost)/r and
                      (delta+cost)/w instead of cost/r and cost/w, so
                      every OSD prices the work its peers already did
                      and a client's reservation holds globally rather
                      than per-server.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import OrderedDict, deque

__all__ = ["OpQueue", "WeightedPriorityQueue", "MClockOpClassQueue",
           "QosShardedOpWQ", "make_op_queue"]


class OpQueue:
    """Discipline contract (src/common/OpQueue.h)."""

    def enqueue(self, klass: str, priority: int, cost: int, item,
                delta: float = 0.0, rho: float = 0.0) -> None:
        raise NotImplementedError

    def enqueue_strict(self, klass: str, priority: int, item) -> None:
        raise NotImplementedError

    def dequeue(self, now: float | None = None):
        """Next item, or None when every class is limit-throttled."""
        raise NotImplementedError

    def next_ready_in(self, now: float | None = None) -> float | None:
        """Seconds until a throttled head becomes eligible (None = no
        throttled work)."""
        return None

    def set_class_info(self, klass: str, reservation: float,
                       weight: float, limit: float) -> bool:
        """Install/replace a class QoS profile; False if the discipline
        has no per-class rates (wpq)."""
        return False

    def note_throttled(self, seconds: float,
                       now: float | None = None) -> None:
        """Attribute worker idle-wait to the classes it throttled."""

    def class_stats(self) -> dict:
        """{class: {depth, served, throttle_wait_s}} for observability."""
        return {}

    def empty(self) -> bool:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class WeightedPriorityQueue(OpQueue):
    """Strict band + deficit-weighted round-robin buckets.

    Ops enqueued strict dequeue first, highest priority first, FIFO
    within. Normal ops land in per-priority buckets; each round-robin
    visit grants a bucket `priority` worth of deficit and it drains
    cost units against it — bandwidth proportional to priority, order
    preserved within a bucket.
    """

    def __init__(self, min_cost: int = 4096):
        self.min_cost = min_cost
        self._strict: dict[int, deque] = {}  # priority -> FIFO
        self._strict_prios: list[int] = []   # sorted ascending
        self._buckets: "OrderedDict[int, deque]" = OrderedDict()
        self._deficit: dict[int, float] = {}
        self._size = 0
        self._kdepth: dict[str, int] = {}
        self._kserved: dict[str, int] = {}

    def enqueue(self, klass, priority, cost, item, delta=0.0, rho=0.0):
        b = self._buckets.get(priority)
        if b is None:
            b = self._buckets[priority] = deque()
            self._deficit.setdefault(priority, 0.0)
        b.append((max(cost, 0), klass, item))
        self._kdepth[klass] = self._kdepth.get(klass, 0) + 1
        self._size += 1

    def enqueue_strict(self, klass, priority, item):
        # strict band: highest priority first, FIFO within; per-priority
        # deques keep every pop O(1) even under a peering storm
        band = self._strict.get(priority)
        if band is None:
            band = self._strict[priority] = deque()
            bisect.insort(self._strict_prios, priority)
        band.append((klass, item))
        self._kdepth[klass] = self._kdepth.get(klass, 0) + 1
        self._size += 1

    def _cost_units(self, cost: int) -> float:
        return max(cost, self.min_cost) / self.min_cost

    def _count_served(self, klass: str) -> None:
        d = self._kdepth.get(klass, 1) - 1
        if d <= 0:
            self._kdepth.pop(klass, None)
        else:
            self._kdepth[klass] = d
        self._kserved[klass] = self._kserved.get(klass, 0) + 1

    def dequeue(self, now=None):
        if self._strict_prios:
            prio = self._strict_prios[-1]
            band = self._strict[prio]
            klass, item = band.popleft()
            if not band:
                del self._strict[prio]
                self._strict_prios.pop()
            self._size -= 1
            self._count_served(klass)
            return item
        # Deficit round robin: a bucket at the front keeps serving while
        # its deficit covers the head's cost, then earns `priority` more
        # and rotates — so over a full rotation each priority p drains
        # ~p/cost items and bandwidth is proportional to priority.
        # Deficit grows only while unaffordable, so it stays bounded and
        # the loop terminates.
        while self._buckets:
            priority, bucket = next(iter(self._buckets.items()))
            if self._deficit[priority] >= self._cost_units(bucket[0][0]):
                cost, klass, item = bucket.popleft()
                self._deficit[priority] -= self._cost_units(cost)
                self._size -= 1
                if not bucket:
                    del self._buckets[priority]
                    del self._deficit[priority]
                self._count_served(klass)
                return item
            # quantum floor of 1: a zero/negative priority must still
            # make progress or the shard worker spins forever on it
            self._deficit[priority] += max(priority, 1)
            self._buckets.move_to_end(priority)
        return None

    def class_stats(self):
        out = {}
        for klass in set(self._kdepth) | set(self._kserved):
            out[klass] = {"depth": self._kdepth.get(klass, 0),
                          "served": self._kserved.get(klass, 0),
                          "throttle_wait_s": 0.0}
        return out

    def empty(self) -> bool:
        return self._size == 0

    def __len__(self) -> int:
        return self._size


class _MClass:
    __slots__ = ("reservation", "weight", "limit", "q",
                 "r_tag", "p_tag", "l_tag", "served", "throttled_s")

    def __init__(self, reservation: float, weight: float, limit: float):
        self.reservation = reservation
        self.weight = weight
        self.limit = limit
        self.q: deque = deque()     # (r, p, l, item) per-op tags
        # None = never active: the first op of a (re)activated class
        # tags at `now` (dmclock's new-client rule) and only rate debt
        # pushes tags into the future
        self.r_tag: float | None = None
        self.p_tag: float | None = None
        self.l_tag: float | None = None
        self.served = 0
        self.throttled_s = 0.0


class MClockOpClassQueue(OpQueue):
    """dmClock over op classes.

    client_info: {class: (reservation_ops_per_s, weight, limit_ops_per_s)}
    (0 reservation = none; 0 limit = unlimited). Dequeue serves overdue
    reservations first (min r-tag <= now), then shares by weight among
    classes under their limit; returns None when everything queued is
    limit-throttled (next_ready_in says how long).
    """

    DEFAULT_INFO = {
        "client": (0.0, 500.0, 0.0),
        "osd_subop": (0.0, 500.0, 0.0),
        "recovery": (0.0, 1.0, 0.0),
        "scrub": (0.0, 1.0, 0.0),
        "snaptrim": (0.0, 1.0, 0.0),
    }

    def __init__(self, client_info: dict | None = None,
                 min_cost: int = 4096, clock=None):
        self.info = dict(self.DEFAULT_INFO)
        if client_info:
            self.info.update(client_info)
        self.min_cost = min_cost
        # injectable for bit-exact tag-math tests on a fake clock
        self._clock = clock if clock is not None else time.monotonic
        self._classes: dict[str, _MClass] = {}
        self._strict: deque = deque()
        self._strict_served = 0
        self._size = 0
        # (klass, phase) of the most recent dequeue; phase is one of
        # "strict" | "reservation" | "proportional" — servers stamp it
        # on the reply so clients can accumulate dmclock rho
        self.last_dequeue: tuple[str, str] | None = None

    def _lookup_info(self, klass: str) -> tuple:
        """Exact class, else its base before ':' (a per-pool class
        "client:gold" with no explicit profile inherits "client")."""
        got = self.info.get(klass)
        if got is not None:
            return got
        if ":" in klass:
            got = self.info.get(klass.split(":", 1)[0])
            if got is not None:
                return got
        return (0.0, 1.0, 0.0)

    def _class(self, klass: str) -> _MClass:
        c = self._classes.get(klass)
        if c is None:
            res, wgt, lim = self._lookup_info(klass)
            c = self._classes[klass] = _MClass(res, wgt, lim)
        return c

    def set_class_info(self, klass, reservation, weight, limit) -> bool:
        self.info[klass] = (reservation, weight, limit)
        c = self._classes.get(klass)
        if c is not None:
            # live rate change applies from the next enqueue; queued
            # ops keep the tags they were priced at
            c.reservation = reservation
            c.weight = weight
            c.limit = limit
        return True

    @staticmethod
    def _next_tag(prev: float | None, rate: float, units: float,
                  now: float) -> float:
        """max(now, prev + units/rate); a fresh/long-idle class tags at
        now so its first op is immediately eligible."""
        if prev is None:
            return now
        return max(now, prev + units / rate)

    def enqueue(self, klass, priority, cost, item, delta=0.0, rho=0.0):
        now = self._clock()
        c = self._class(klass)
        # normalize byte cost into units so weights stay the dominant
        # signal (raw bytes would advance a 1MB client op's tag by
        # minutes and invert the configured client:recovery ratio)
        scale = max(cost, self.min_cost) / self.min_cost
        if not c.q:
            # re-activation after a drain: clamp accumulated debt down
            # to `now` so a burst's leftover tags don't exile the class
            # for minutes — the next tag still advances by scale/rate
            # from now, so a trickler is paced at its configured share
            # rather than evading it (dequeue-side tag resets would
            # allow exactly that evasion)
            for attr in ("r_tag", "p_tag", "l_tag"):
                prev = getattr(c, attr)
                if prev is not None and prev > now:
                    setattr(c, attr, now)
        # dmClock: delta/rho are min_cost units of service this
        # principal received cluster-wide since its previous op to this
        # server; pricing them into the advance makes each tag reflect
        # global service, so an OSD that served less pulls ahead
        if c.reservation > 0:
            r = self._next_tag(c.r_tag, c.reservation, rho + scale, now)
            c.r_tag = r
        else:
            r = float("inf")
        p = self._next_tag(c.p_tag, c.weight, delta + scale, now)
        c.p_tag = p
        if c.limit > 0:
            lim = self._next_tag(c.l_tag, c.limit, delta + scale, now)
            c.l_tag = lim
        else:
            lim = 0.0
        c.q.append((r, p, lim, item))
        self._size += 1

    def enqueue_strict(self, klass, priority, item):
        self._strict.append((klass, item))
        self._size += 1

    def dequeue(self, now=None):
        if self._strict:
            self._size -= 1
            self._strict_served += 1
            klass, item = self._strict.popleft()
            self.last_dequeue = (klass, "strict")
            return item
        now = self._clock() if now is None else now
        # reservation phase
        best = None
        phase = "reservation"
        for klass, c in self._classes.items():
            if c.q and c.q[0][0] <= now:
                if best is None or c.q[0][0] < best[0]:
                    best = (c.q[0][0], klass, c)
        if best is None:
            # proportional phase (limit-gated)
            phase = "proportional"
            for klass, c in self._classes.items():
                if c.q and c.q[0][2] <= now:
                    if best is None or c.q[0][1] < best[0]:
                        best = (c.q[0][1], klass, c)
        if best is not None:
            _, klass, c = best
            _, _, _, item = c.q.popleft()
            c.served += 1
            self._size -= 1
            self.last_dequeue = (klass, phase)
            return item
        return None

    def next_ready_in(self, now=None):
        now = self._clock() if now is None else now
        # a head op becomes serviceable at the earlier of its
        # reservation tag and its limit tag (dequeue serves the
        # r-phase first), so the wait must take min over both
        waits = [min(c.q[0][0], c.q[0][2]) - now
                 for c in self._classes.values() if c.q]
        return max(0.0, min(waits)) if waits else None

    def note_throttled(self, seconds, now=None):
        """Attribute `seconds` of worker idle-wait to every class whose
        head op is ineligible — its limit (or unmet reservation) is
        what kept the worker sleeping."""
        now = self._clock() if now is None else now
        for c in self._classes.values():
            if c.q and min(c.q[0][0], c.q[0][2]) > now:
                c.throttled_s += seconds

    def class_stats(self):
        out = {}
        for klass, c in self._classes.items():
            if c.q or c.served or c.throttled_s:
                out[klass] = {"depth": len(c.q), "served": c.served,
                              "throttle_wait_s": c.throttled_s}
        if self._strict or self._strict_served:
            out["strict"] = {"depth": len(self._strict),
                             "served": self._strict_served,
                             "throttle_wait_s": 0.0}
        return out

    def empty(self) -> bool:
        return self._size == 0

    def __len__(self) -> int:
        return self._size


def make_op_queue(conf=None) -> OpQueue | None:
    """Build the discipline named by osd_op_queue; None means plain FIFO."""
    name = conf.get_val("osd_op_queue") if conf is not None else "wpq"
    if name == "wpq":
        return WeightedPriorityQueue()
    if name == "mclock_opclass":
        info = {}
        for klass in ("client", "recovery", "scrub", "snaptrim"):
            info[klass] = (
                conf.get_val("osd_op_queue_mclock_%s_res" % klass),
                conf.get_val("osd_op_queue_mclock_%s_wgt" % klass),
                conf.get_val("osd_op_queue_mclock_%s_lim" % klass))
        return MClockOpClassQueue(info)
    if name == "fifo":
        return None
    raise ValueError("unknown osd_op_queue %r" % name)


class QosShardedOpWQ:
    """ShardedOpWQ with a QoS discipline inside each shard.

    Same contract as ShardedThreadPool (hash key -> shard, one worker
    per shard => per-PG ordering within a priority class), but each
    shard drains an OpQueue so client ops outrank recovery/scrub work.
    """

    def __init__(self, name: str, num_shards: int, queue_factory,
                 hbmap=None, grace: float = 30.0):
        self.name = name
        self.num_shards = num_shards
        self._shards = [_QosShard("%s-s%d" % (name, i), queue_factory(),
                                  hbmap, grace)
                        for i in range(num_shards)]

    def start(self) -> None:
        for s in self._shards:
            s.start()

    def queue(self, key, fn, *args, klass: str = "client",
              priority: int = 63, cost: int = 0, delta: float = 0.0,
              rho: float = 0.0, qos_obj=None) -> None:
        # qos_obj (usually the op message) gets `_qos_phase` stamped at
        # dequeue time so the reply can tell the client which dmclock
        # phase served it
        self._shards[hash(key) % self.num_shards].enqueue(
            klass, priority, cost, (fn, args, qos_obj), delta, rho)

    def set_pool_qos(self, pool: str, reservation: float, weight: float,
                     limit: float) -> bool:
        """Split the pool's client ops into a dedicated per-shard class.

        Reservation/limit arrive as whole-OSD op rates; each shard runs
        independent tags, so the rates are divided across shards
        (weight is relative and needs no scaling)."""
        n = max(1, self.num_shards)
        ok = False
        for s in self._shards:
            with s._cond:
                ok = s.opq.set_class_info("client:%s" % pool,
                                          reservation / n, weight,
                                          limit / n) or ok
                s._cond.notify_all()
        return ok

    def dump(self) -> dict:
        """Per-class stats merged across shards (asok dump_op_queue)."""
        out: dict = {}
        for s in self._shards:
            with s._cond:
                stats = s.opq.class_stats()
            for klass, st in stats.items():
                agg = out.setdefault(klass, {"depth": 0, "served": 0,
                                             "throttle_wait_s": 0.0})
                agg["depth"] += st["depth"]
                agg["served"] += st["served"]
                agg["throttle_wait_s"] += st["throttle_wait_s"]
        return out

    def drain(self) -> None:
        for s in self._shards:
            s.drain()

    def stop(self) -> None:
        for s in self._shards:
            s.stop()


class _QosShard:
    def __init__(self, name: str, opq: OpQueue, hbmap, grace: float):
        self.name = name
        self.opq = opq
        self._hbmap = hbmap
        self._grace = grace
        # idle wakeups must outpace the heartbeat grace or an idle
        # shard reads as wedged
        self._wait_cap = min(1.0, grace / 2) if hbmap else 1.0
        self._cond = threading.Condition()
        self._stopping = False
        self._inflight = 0
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._worker,
                                        name=self.name, daemon=True)
        self._thread.start()

    def enqueue(self, klass, priority, cost, item,
                delta: float = 0.0, rho: float = 0.0) -> None:
        with self._cond:
            self.opq.enqueue(klass, priority, cost, item, delta, rho)
            self._cond.notify()

    def _stamp_phase(self, item) -> None:
        # must run under the lock, right after the dequeue that set
        # last_dequeue — another worker pass would overwrite it
        qos_obj = item[2] if len(item) > 2 else None
        if qos_obj is not None:
            ld = getattr(self.opq, "last_dequeue", None)
            if ld is not None:
                qos_obj._qos_phase = ld[1]

    def _worker(self) -> None:
        handle = self._hbmap.add(self.name, self._grace) \
            if self._hbmap else None
        while True:
            with self._cond:
                while True:
                    if handle:  # idle loops must stay visibly alive
                        handle.renew()
                    if self._stopping:
                        # drain before exit (ShardedThreadPool parity:
                        # its shutdown sentinel sits BEHIND pending
                        # work); limits are bypassed — a stopping OSD
                        # must not strand throttled replies
                        item = self.opq.dequeue(now=float("inf"))
                        if item is None:
                            if handle:
                                handle.remove()
                            return
                        self._inflight += 1
                        self._stamp_phase(item)
                        break
                    item = self.opq.dequeue()
                    if item is not None:
                        self._inflight += 1
                        self._stamp_phase(item)
                        break
                    wait = self.opq.next_ready_in()
                    if wait is not None:
                        # head(s) exist but are throttled: sleep and
                        # charge the wait to the classes that caused it
                        t0 = time.monotonic()
                        self._cond.wait(min(wait, self._wait_cap))
                        self.opq.note_throttled(time.monotonic() - t0)
                    else:
                        self._cond.wait(self._wait_cap)
            if handle:
                handle.renew()
            fn, args = item[0], item[1]
            try:
                fn(*args)
            except Exception:
                import traceback
                traceback.print_exc()
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def drain(self) -> None:
        with self._cond:
            while not self.opq.empty() or self._inflight:
                self._cond.wait(0.01)

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            # unbounded: stop() guarantees the drain completed — a
            # timed join would let shutdown race the very replies the
            # drain protects
            self._thread.join()
