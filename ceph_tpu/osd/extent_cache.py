"""Pinned-extent cache for pipelined RMW overwrites.

Role of the reference's ExtentCache (src/osd/ExtentCache.{h,cc}): when
write A must read-modify-write a stripe and write B to the same stripe
is right behind it, B must see A's post-image without waiting for A to
commit to disk. Each in-flight write pins the extents it reads/writes;
reads check the cache first and only fetch the holes remotely; on
write-apply the new bytes land in the cache; a pin releases on commit
and fully-released extents are dropped.

API shape follows the reference: open_write_pin / reserve_extents_for_rmw
-> must_read holes; get_remaining_extents_for_rmw after the readback;
present_rmw_update with the written bytes; release_write_pin on commit.
"""

from __future__ import annotations

from ..common.interval_set import ExtentMap, IntervalSet

__all__ = ["ExtentCache", "WritePin"]


class WritePin:
    def __init__(self, tid):
        self.tid = tid
        self.pinned: dict = {}  # oid -> IntervalSet


class _ObjectState:
    def __init__(self):
        self.cache = ExtentMap()
        self.pin_counts: dict = {}  # (start,len) granular counting via sets

    def empty(self) -> bool:
        return not self.pin_counts


class ExtentCache:
    def __init__(self):
        self._objects: dict = {}

    def open_write_pin(self, tid) -> WritePin:
        return WritePin(tid)

    # -- reserve -------------------------------------------------------

    def reserve_extents_for_rmw(self, oid, pin: WritePin,
                                to_read: IntervalSet,
                                will_write: IntervalSet) -> IntervalSet:
        """Pin to_read+will_write; return the subset of to_read NOT in
        the cache (must be fetched from shards)."""
        state = self._objects.setdefault(oid, _ObjectState())
        pinned = pin.pinned.setdefault(oid, IntervalSet())
        pinned.union_of(to_read)
        pinned.union_of(will_write)
        for off, length in pinned:
            key = (off, length)
            state.pin_counts[key] = state.pin_counts.get(key, 0) + 1

        must_read = IntervalSet()
        cached = state.cache.intervals()
        for off, length in to_read:
            seg = IntervalSet([(off, length)])
            hit = seg.intersect(cached)
            for s, e_len in hit:
                seg.erase(s, e_len)
            must_read.union_of(seg)
        return must_read

    # -- fill ----------------------------------------------------------

    def present_read(self, oid, offset: int, data) -> None:
        """Insert readback bytes fetched for an RMW."""
        state = self._objects.setdefault(oid, _ObjectState())
        state.cache.insert(offset, data)

    def get_remaining_extents_for_rmw(self, oid,
                                      to_read: IntervalSet) -> ExtentMap:
        """Return the cached bytes covering to_read (post-readback)."""
        state = self._objects.get(oid)
        out = ExtentMap()
        if state is None:
            return out
        for off, length in to_read:
            got = state.cache.get(off, length)
            if got is not None:
                out.insert(off, got)
            else:
                for s, d in state.cache:
                    lo, hi = max(s, off), min(s + d.size, off + length)
                    if lo < hi:
                        out.insert(lo, d[lo - s:hi - s])
        return out

    def present_rmw_update(self, oid, written: ExtentMap) -> None:
        """Write-apply: the op's post-image becomes visible to later
        pipelined ops immediately (before commit)."""
        state = self._objects.setdefault(oid, _ObjectState())
        for off, data in written:
            state.cache.insert(off, data)

    # -- release -------------------------------------------------------

    def release_write_pin(self, pin: WritePin) -> None:
        for oid, pinned in pin.pinned.items():
            state = self._objects.get(oid)
            if state is None:
                continue
            for off, length in pinned:
                key = (off, length)
                count = state.pin_counts.get(key, 0) - 1
                if count <= 0:
                    state.pin_counts.pop(key, None)
                    # drop bytes no longer pinned by anyone
                    still = IntervalSet()
                    for (o2, l2) in state.pin_counts:
                        still.union_insert(o2, l2)
                    if not still.intersects(off, length):
                        state.cache.erase(off, length)
                else:
                    state.pin_counts[key] = count
            if state.empty():
                self._objects.pop(oid, None)
        pin.pinned = {}

    # -- introspection -------------------------------------------------

    def contains_object(self, oid) -> bool:
        return oid in self._objects

    def dump(self) -> dict:
        return {str(oid): [(s, d.size) for s, d in state.cache]
                for oid, state in self._objects.items()}
