"""Pinned-extent cache for pipelined RMW overwrites.

Role of the reference's ExtentCache (src/osd/ExtentCache.{h,cc}): when
write A must read-modify-write a stripe and write B to the same stripe
is right behind it, B must see A's post-image without waiting for A to
commit to disk.  Each in-flight write pins the extents it reads/writes;
reads check the cache first and only fetch the holes remotely; on
write-apply the new bytes land in the cache; a pin releases on commit
and extents nobody later pinned are dropped.

Ownership model (ExtentCache.h's core design, realised with an
interval owner-map instead of intrusive lists): every cached byte
range is owned by exactly ONE pin — the LATEST write that pinned it.
Reserving moves overlapping ranges to the (younger) reserving pin;
releasing a pin drops only the ranges it still owns.  This is what
makes out-of-order commit completion safe: if write B (tid 8) re-
pinned part of write A's (tid 5) extents, A's release leaves those
bytes cached for B, and whichever order A and B commit in, bytes are
freed exactly when their last pinned writer completes.

Invariant inherited from the reference (its header's "Writes on a
particular object must be ordered"): reserve_extents_for_rmw calls
for one object must happen in tid order — the EC backend's
waiting_state FIFO guarantees it, and the cache asserts it.

API shape follows the reference: open_write_pin /
reserve_extents_for_rmw -> must_read holes;
get_remaining_extents_for_rmw after the readback; present_rmw_update
with the written bytes; release_write_pin on commit.
"""

from __future__ import annotations

from ..common.interval_set import ExtentMap, IntervalSet

__all__ = ["ExtentCache", "WritePin"]


class WritePin:
    def __init__(self, tid):
        self.tid = tid
        self.objects: set = set()    # oids this pin ever touched


class _OwnerMap:
    """Interval -> owner tid, with assign-splits and per-tid release
    (the pin_state/extent ownership bookkeeping of ExtentCache.h as a
    flat sorted interval list: [start, end, tid])."""

    def __init__(self):
        self._ivals: list = []       # sorted, non-overlapping

    def assign(self, off: int, length: int, tid: int) -> None:
        """Make `tid` the owner of [off, off+length) — later writes
        steal ownership of overlapping ranges (extent::move)."""
        if length <= 0:
            return
        end = off + length
        out = []
        for s, e, t in self._ivals:
            if e <= off or s >= end:
                out.append([s, e, t])
                continue
            if s < off:
                out.append([s, off, t])
            if e > end:
                out.append([end, e, t])
        out.append([off, end, tid])
        out.sort()
        # merge adjacent same-owner ranges (fixed per-extent overhead)
        merged: list = []
        for s, e, t in out:
            if merged and merged[-1][2] == t and merged[-1][1] == s:
                merged[-1][1] = e
            else:
                merged.append([s, e, t])
        self._ivals = merged

    def release(self, tid: int) -> IntervalSet:
        """Drop every range still owned by tid; returns them."""
        freed = IntervalSet()
        keep = []
        for s, e, t in self._ivals:
            if t == tid:
                freed.union_insert(s, e - s)
            else:
                keep.append([s, e, t])
        self._ivals = keep
        return freed

    def max_tid(self) -> int:
        return max((t for _s, _e, t in self._ivals), default=-1)

    def empty(self) -> bool:
        return not self._ivals

    def owned_by(self, tid: int) -> IntervalSet:
        out = IntervalSet()
        for s, e, t in self._ivals:
            if t == tid:
                out.union_insert(s, e - s)
        return out

    def all_ranges(self) -> IntervalSet:
        out = IntervalSet()
        for s, e, _t in self._ivals:
            out.union_insert(s, e - s)
        return out


class _ObjectState:
    def __init__(self):
        self.cache = ExtentMap()     # bytes (post-images + readbacks)
        self.owners = _OwnerMap()    # byte range -> owning pin tid

    def empty(self) -> bool:
        return self.owners.empty()


class ExtentCache:
    def __init__(self):
        self._objects: dict = {}

    def open_write_pin(self, tid) -> WritePin:
        return WritePin(tid)

    # -- reserve -------------------------------------------------------

    def reserve_extents_for_rmw(self, oid, pin: WritePin,
                                to_read: IntervalSet,
                                will_write: IntervalSet) -> IntervalSet:
        """Pin to_read+will_write under this (youngest) pin; return
        the subset of to_read NOT in the cache (must be fetched from
        shards)."""
        state = self._objects.setdefault(oid, _ObjectState())
        # the pipeline invariant the reference's design leans on:
        # writes on one object reserve in order
        assert pin.tid >= state.owners.max_tid(), \
            "out-of-order reserve: tid %s after %s" % (
                pin.tid, state.owners.max_tid())
        pin.objects.add(oid)
        # ranges an EARLIER in-flight write already pinned are the
        # reference's "Write Pending" extents: their bytes will be in
        # the cache (readback or post-image) before this op's apply
        # runs, so they must NOT be fetched from the shards — a shard
        # read could return the pre-write image and clobber the
        # pipelined post-image (ExtentCache.h state 1)
        pending = state.owners.all_ranges()
        for off, length in to_read:
            state.owners.assign(off, length, pin.tid)
        for off, length in will_write:
            state.owners.assign(off, length, pin.tid)

        must_read = IntervalSet()
        cached = state.cache.intervals()
        for off, length in to_read:
            seg = IntervalSet([(off, length)])
            for cover in (cached, pending):
                hit = seg.intersect(cover)
                for s, e_len in hit:
                    seg.erase(s, e_len)
            must_read.union_of(seg)
        return must_read

    # -- fill ----------------------------------------------------------

    def present_read(self, oid, offset: int, data) -> None:
        """Insert readback bytes fetched for an RMW."""
        state = self._objects.setdefault(oid, _ObjectState())
        state.cache.insert(offset, data)

    def get_remaining_extents_for_rmw(self, oid,
                                      to_read: IntervalSet) -> ExtentMap:
        """Return the cached bytes covering to_read (post-readback)."""
        state = self._objects.get(oid)
        out = ExtentMap()
        if state is None:
            return out
        for off, length in to_read:
            got = state.cache.get(off, length)
            if got is not None:
                out.insert(off, got)
            else:
                for s, d in state.cache:
                    lo, hi = max(s, off), min(s + d.size, off + length)
                    if lo < hi:
                        out.insert(lo, d[lo - s:hi - s])
        return out

    def present_rmw_update(self, oid, written: ExtentMap) -> None:
        """Write-apply: the op's post-image becomes visible to later
        pipelined ops immediately (before commit)."""
        state = self._objects.setdefault(oid, _ObjectState())
        for off, data in written:
            state.cache.insert(off, data)

    # -- release -------------------------------------------------------

    def release_write_pin(self, pin: WritePin) -> None:
        """Commit: drop every byte range this pin still OWNS.  Ranges
        a younger write re-pinned were moved to that pin at its
        reserve and survive — out-of-order commit completion cannot
        evict bytes a later in-flight write will read."""
        for oid in pin.objects:
            state = self._objects.get(oid)
            if state is None:
                continue
            for off, length in state.owners.release(pin.tid):
                state.cache.erase(off, length)
            if state.empty():
                self._objects.pop(oid, None)
        pin.objects = set()

    # -- introspection -------------------------------------------------

    def contains_object(self, oid) -> bool:
        return oid in self._objects

    def pinned_by(self, oid, tid) -> IntervalSet:
        state = self._objects.get(oid)
        return state.owners.owned_by(tid) if state else IntervalSet()

    def dump(self) -> dict:
        return {str(oid): [(s, d.size) for s, d in state.cache]
                for oid, state in self._objects.items()}
