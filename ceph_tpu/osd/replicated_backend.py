"""Replicated PG backend.

Role of the reference's ReplicatedBackend (src/osd/ReplicatedBackend.cc):
the primary applies the logical transaction locally, fans MOSDRepOp with
the physical ops to every replica, and completes the client op when all
acting replicas commit. Reads are local to the primary. Recovery is
push-based: the primary sends the whole object state.
"""

from __future__ import annotations

import itertools
import threading

from ..common.lockdep import make_rlock
from ..msg.message import MOSDRepOp, MOSDRepOpReply
from ..store.object_store import Transaction

__all__ = ["ReplicatedBackend"]


class _Inflight:
    def __init__(self, tid, on_commit, waiting_on):
        self.tid = tid
        self.on_commit = on_commit
        self.waiting_on = set(waiting_on)


class ReplicatedBackend:
    def __init__(self, pg):
        self.pg = pg
        self._tids = itertools.count(1)
        self.lock = make_rlock("rep-backend:%s" % (pg.pgid,))
        self.inflight: dict[int, _Inflight] = {}

    # -- write ---------------------------------------------------------

    def submit_transaction(self, pg_txn, at_version: int,
                           on_commit) -> int:
        tid = next(self._tids)
        txn = self._physical_txn(pg_txn)
        peers = [o for o in self.pg.acting_osds() if o >= 0]
        log_entries = self.pg.mint_log_entries(pg_txn.op_map, at_version)
        op = _Inflight(tid, on_commit, peers)
        with self.lock:
            self.inflight[tid] = op
        for osd in peers:
            msg = MOSDRepOp(pgid=self.pg.pgid, from_osd=self.pg.whoami,
                            tid=tid, at_version=at_version,
                            log_entries=log_entries, txn_ops=txn.ops,
                            map_epoch=self.pg.map_epoch())
            if osd == self.pg.whoami:
                self.handle_rep_op(msg, local=True)
            else:
                self.pg.send_to_osd(osd, msg)
        return tid

    def _physical_txn(self, pg_txn) -> Transaction:
        """Logical -> physical is 1:1 for replication (no striping)."""
        cid = self.pg.cid_of_shard(-1)
        txn = Transaction()
        for oid, op in pg_txn.safe_create_traverse():
            if op.deletes_first():
                txn.remove(cid, oid)
            if op.init_type == "create":
                txn.touch(cid, oid)
            elif op.init_type == "clone":
                txn.clone(cid, op.source, oid)
            elif op.init_type == "rename":
                txn.collection_move_rename(cid, op.source, cid, oid)
            if op.truncate is not None:
                txn.truncate(cid, oid, op.truncate[0])
            for upd in op.buffer_updates:
                if upd[0] == "write":
                    txn.write(cid, oid, upd[1], upd[2])
                else:
                    txn.zero(cid, oid, upd[1], upd[2])
            if op.truncate is not None and \
                    op.truncate[1] != op.truncate[0]:
                txn.truncate(cid, oid, op.truncate[1])
            for name, value in op.attr_updates.items():
                if value is None:
                    txn.rmattr(cid, oid, name)
                else:
                    txn.setattr(cid, oid, name, value)
            if op.omap_updates:
                txn.omap_setkeys(cid, oid, op.omap_updates)
            if op.omap_rmkeys:
                txn.omap_rmkeys(cid, oid, op.omap_rmkeys)
        return txn

    # -- replica -------------------------------------------------------

    def handle_rep_op(self, msg, local: bool = False) -> None:
        txn = Transaction()
        txn.ops = list(msg.txn_ops)
        # log keys ride the same store transaction as the data
        self.pg.log_operation(msg.log_entries, msg.at_version, -1,
                              txn=txn)

        def on_commit():
            reply = MOSDRepOpReply(pgid=self.pg.pgid,
                                   from_osd=self.pg.whoami,
                                   tid=msg.tid, committed=True)
            if local:
                self.handle_rep_op_reply(reply)
            else:
                self.pg.send_to_osd(msg.from_osd, reply)

        txn.register_on_commit(on_commit)
        self.pg.store.queue_transaction(txn)

    def handle_rep_op_reply(self, msg) -> None:
        with self.lock:
            op = self.inflight.get(msg.tid)
            if op is None:
                return
            op.waiting_on.discard(msg.from_osd)
            if op.waiting_on:
                return
            self.inflight.pop(msg.tid, None)
        if op.on_commit:
            op.on_commit()

    # -- read ----------------------------------------------------------

    def objects_read(self, oid, off: int, length: int, on_done) -> None:
        try:
            data = self.pg.local_read_shard(-1, oid, off, length)
        except (OSError, KeyError):
            on_done(None)
            return
        on_done(data)

    # -- recovery ------------------------------------------------------

    def recover_object(self, oid, target_shard: int, on_done) -> None:
        """Full-copy push source: the primary's bytes ARE the object."""
        try:
            data = self.pg.local_read_shard(-1, oid, 0, 0)
        except (OSError, KeyError):
            on_done(None)
            return
        on_done(data)
