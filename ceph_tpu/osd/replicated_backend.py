"""Replicated PG backend.

Role of the reference's ReplicatedBackend (src/osd/ReplicatedBackend.cc):
the primary applies the logical transaction locally, fans MOSDRepOp with
the physical ops to every replica, and completes the client op when all
acting replicas commit. Reads are local to the primary. Recovery is
push-based: the primary sends the whole object state.
"""

from __future__ import annotations

import itertools
import threading

from ..common.bounded import BoundedDict
from ..common.lockdep import make_rlock
from ..common.tracer import NULL_SPAN, trace_ctx
from ..msg.message import MOSDRepOp, MOSDRepOpReply
from ..store.object_store import Transaction

__all__ = ["ReplicatedBackend"]


class _Inflight:
    def __init__(self, tid, on_commit, waiting_on):
        self.tid = tid
        self.on_commit = on_commit
        self.waiting_on = set(waiting_on)
        self.msg = None               # the MOSDRepOp, for retransmit
        self.sub_spans: dict = {}     # osd -> per-peer rep-op span


class ReplicatedBackend:
    # sub-ops are at-least-once: fan-out retries ride the timer until
    # every peer acks (a dropped MOSDRepOp must not wedge the write),
    # and replicas dedup by (from_osd, tid) so retransmits replay the
    # ack without re-applying the transaction
    RETRY_INTERVAL = 1.0

    def __init__(self, pg):
        self.pg = pg
        self._tids = itertools.count(1)
        self.lock = make_rlock("rep-backend:%s" % (pg.pgid,))
        self.inflight: dict[int, _Inflight] = {}
        # per-instance nonce: tids restart when a daemon restarts, so
        # the replica dedup keys on (instance, tid) — a reborn primary
        # must never hit a dead incarnation's cache entries
        import uuid
        self.instance = uuid.uuid4().hex
        self._seen: BoundedDict = BoundedDict()  # key -> committed?

    # -- write ---------------------------------------------------------

    def submit_transaction(self, pg_txn, at_version: int,
                           on_commit, reqid: tuple = ("", 0),
                           trace=NULL_SPAN) -> int:
        tid = next(self._tids)
        if trace is None:
            trace = NULL_SPAN
        txn = self._physical_txn(pg_txn)
        peers = [o for o in self.pg.acting_osds() if o >= 0]
        log_entries = self.pg.mint_log_entries(pg_txn.op_map, at_version,
                                               reqid)
        op = _Inflight(tid, on_commit, peers)
        t_id, p_id = trace_ctx(trace)
        op.msg = MOSDRepOp(pgid=self.pg.pgid, from_osd=self.pg.whoami,
                           tid=tid, at_version=at_version,
                           log_entries=log_entries, txn_ops=txn.ops,
                           map_epoch=self.pg.map_epoch(),
                           instance=self.instance, trace_id=t_id,
                           parent_span=p_id)
        with self.lock:
            self.inflight[tid] = op
            for osd in peers:
                span = trace.child("rep_op(osd=%d)" % osd)
                op.sub_spans[osd] = span
        for osd in peers:
            if osd == self.pg.whoami:
                self.handle_rep_op(op.msg, local=True)
            else:
                self.pg.send_to_osd(osd, op.msg)
        self.pg.daemon.timer.add_event_after(
            self.RETRY_INTERVAL, self._retry_inflight, tid)
        return tid

    def _retry_inflight(self, tid: int) -> None:
        acting = set(self.pg.acting_osds())
        done = None
        with self.lock:
            op = self.inflight.get(tid)
            if op is None:
                return                 # completed
            # a peer that left the acting set can never ack: stop
            # waiting on it (the new interval's peering roll-forward
            # owns its convergence) — otherwise a dead replica wedges
            # the write forever while duplicates are being dropped
            op.waiting_on &= acting | {self.pg.whoami}
            if not op.waiting_on:
                self.inflight.pop(tid, None)
                done = op
            waiting = set(op.waiting_on)
            msg = op.msg
        if done is not None:
            for span in done.sub_spans.values():
                span.finish()
            done.sub_spans = {}
            if done.on_commit:
                done.on_commit()
            return
        for osd in waiting:
            if osd != self.pg.whoami:
                self.pg.send_to_osd(osd, msg)
        self.pg.daemon.timer.add_event_after(
            self.RETRY_INTERVAL, self._retry_inflight, tid)

    def _physical_txn(self, pg_txn) -> Transaction:
        """Logical -> physical is 1:1 for replication (no striping)."""
        cid = self.pg.cid_of_shard(-1)
        txn = Transaction()
        for oid, op in pg_txn.safe_create_traverse():
            if op.deletes_first():
                txn.remove(cid, oid)
            if op.init_type == "create":
                txn.touch(cid, oid)
            elif op.init_type == "clone":
                txn.clone(cid, op.source, oid)
            elif op.init_type == "rename":
                txn.collection_move_rename(cid, op.source, cid, oid)
            if op.truncate is not None:
                txn.truncate(cid, oid, op.truncate[0])
            for upd in op.buffer_updates:
                if upd[0] == "write":
                    txn.write(cid, oid, upd[1], upd[2])
                else:
                    txn.zero(cid, oid, upd[1], upd[2])
            if op.truncate is not None and \
                    op.truncate[1] != op.truncate[0]:
                txn.truncate(cid, oid, op.truncate[1])
            for name, value in op.attr_updates.items():
                if value is None:
                    txn.rmattr(cid, oid, name)
                else:
                    txn.setattr(cid, oid, name, value)
            if op.omap_updates:
                txn.omap_setkeys(cid, oid, op.omap_updates)
            if op.omap_rmkeys:
                txn.omap_rmkeys(cid, oid, op.omap_rmkeys)
        return txn

    # -- replica -------------------------------------------------------

    def handle_rep_op(self, msg, local: bool = False) -> None:
        def on_commit():
            reply = MOSDRepOpReply(pgid=self.pg.pgid,
                                   from_osd=self.pg.whoami,
                                   tid=msg.tid, committed=True)
            if local:
                self.handle_rep_op_reply(reply)
            else:
                self.pg.send_to_osd(msg.from_osd, reply)

        # retransmit? replay the ack — but only once the ORIGINAL
        # application actually committed (acking uncommitted data
        # would let the primary complete a write a crashing replica
        # never made durable); an uncommitted in-flight original just
        # drops the duplicate (its own commit will ack)
        key = (getattr(msg, "instance", "") or msg.from_osd, msg.tid)
        with self.lock:
            state = self._seen.get(key)
            if state is None:
                self._seen[key] = False     # received, not committed
        if state is not None:
            if state:
                on_commit()
            return

        # replica-side span, stitched from the envelope context
        span = self.pg.daemon.tracer.continue_trace(
            "rep_apply", getattr(msg, "trace_id", 0),
            getattr(msg, "parent_span", 0))
        span.keyval("tid", msg.tid)

        def commit_and_ack():
            with self.lock:
                self._seen[key] = True
            span.finish()
            on_commit()

        txn = Transaction()
        txn.ops = list(msg.txn_ops)
        txn.trace = span             # store-level spans nest under it
        # log keys ride the same store transaction as the data
        self.pg.log_operation(msg.log_entries, msg.at_version, -1,
                              txn=txn)
        txn.register_on_commit(commit_and_ack)
        self.pg.store.queue_transaction(txn)

    def handle_rep_op_reply(self, msg) -> None:
        with self.lock:
            op = self.inflight.get(msg.tid)
            if op is None:
                return
            op.waiting_on.discard(msg.from_osd)
            span = op.sub_spans.pop(msg.from_osd, None)
            if op.waiting_on:
                if span is not None:
                    span.finish()
                return
            self.inflight.pop(msg.tid, None)
            leftovers = list(op.sub_spans.values())
            op.sub_spans = {}
        if span is not None:
            span.finish()
        for s in leftovers:
            s.finish()
        if op.on_commit:
            op.on_commit()

    # -- read ----------------------------------------------------------

    def objects_read(self, oid, off: int, length: int, on_done,
                     trace=NULL_SPAN) -> None:
        if trace is None:
            trace = NULL_SPAN
        try:
            with trace.child("local_read"):
                data = self.pg.local_read_shard(-1, oid, off, length)
        except (OSError, KeyError):
            on_done(None)
            return
        on_done(data)

    # -- recovery ------------------------------------------------------

    def recover_object(self, oid, target_shard: int, on_done) -> None:
        """Full-copy push source: the primary's bytes ARE the object."""
        try:
            data = self.pg.local_read_shard(-1, oid, 0, 0)
        except (OSError, KeyError):
            on_done(None)
            return
        on_done(data)
