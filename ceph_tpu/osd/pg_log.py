"""Per-PG operation log with authoritative-log merge.

Role of the reference's PGLog (src/osd/PGLog.{h,cc}, 2,974 LoC) and the
peering log machinery (doc/dev/osd_internals/log_based_pg.rst,
doc/dev/osd_internals/erasure_coding/ecbackend.rst:149-174): every
write appends a log entry stamped with an eversion — (map epoch,
version) — and peering converges replicas by comparing LOGS, not by
scanning object inventories:

  - the peer with the highest last_update owns the authoritative log;
  - entries the authoritative log has beyond ours become `missing`
    (oid -> the version we need) and drive targeted recovery;
  - OUR entries beyond the last common point are DIVERGENT — written
    in a dead interval, never acked against the surviving quorum's
    chain — and are undone: a divergent create is removed, a divergent
    modify/delete reverts to the authoritative object (via recovery,
    the "cannot rollback -> add to missing" lane of PGLog::_merge_
    object_divergent_entries; EC roll-forward semantics fall out of
    the same rule because acked entries are by construction on every
    surviving shard's log).

The epoch half of the eversion is what makes fork detection sound: two
primaries of different intervals minting version N produce entries
(e1, N) != (e2, N), so the divergent one cannot masquerade as the
acked one (the failure class plain version counters cannot see).
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["LogEntry", "PGLog", "entry_from_tuple"]


@dataclass
class LogEntry:
    """One journaled PG operation (pg_log_entry_t). reqid carries the
    client's (session, tid) so a new primary can dedup retransmits
    across failover (pg_log_entry_t::reqid exactly-once role)."""
    epoch: int = 0
    version: int = 0
    oid: str = ""
    kind: str = "modify"          # modify | delete
    prior_version: int = 0
    reqid: tuple = ("", 0)

    @property
    def ev(self) -> tuple:
        return (self.epoch, self.version)


def entry_from_tuple(t) -> LogEntry:
    """Canonical wire/durable row: (epoch, version, oid, kind, prior
    [, session, tid]). Legacy 3-tuples (version, oid, kind) still
    parse (epoch 0)."""
    if isinstance(t, LogEntry):
        return t
    if len(t) >= 7:
        return LogEntry(epoch=t[0], version=t[1], oid=t[2], kind=t[3],
                        prior_version=t[4], reqid=(t[5], t[6]))
    if len(t) >= 5:
        return LogEntry(epoch=t[0], version=t[1], oid=t[2], kind=t[3],
                        prior_version=t[4])
    return LogEntry(epoch=0, version=t[0], oid=t[1], kind=t[2])


class PGLog:
    """Ordered entry list + oid index + missing map."""

    CAP = 5000

    def __init__(self):
        self.entries: list[LogEntry] = []
        self.head: tuple = (0, 0)     # eversion of newest entry
        self.tail: tuple = (0, 0)     # everything before this is trimmed
        # oid -> version we need (0 = must not exist / delete local)
        self.missing: dict = {}

    def __len__(self):
        return len(self.entries)

    def append(self, entry: LogEntry) -> list:
        """Returns the entries trimmed off the tail (so the durable
        omap can drop their keys — the on-disk log must not grow
        unboundedly while the in-memory one caps at CAP)."""
        self.entries.append(entry)
        if entry.ev > self.head:
            self.head = entry.ev
        return self._trim()

    def _trim(self) -> list:
        dropped: list = []
        if len(self.entries) > self.CAP:
            drop = len(self.entries) - self.CAP
            dropped = self.entries[:drop]
            self.entries = self.entries[drop:]
            self.tail = self.entries[0].ev
        return dropped

    def has_ev(self, ev: tuple) -> bool:
        return any(e.ev == tuple(ev) for e in self.entries)

    def entries_since(self, ev: tuple) -> list[LogEntry]:
        """Entries strictly after eversion ev, in order."""
        ev = tuple(ev)
        return [e for e in self.entries if e.ev > ev]

    def overlaps(self, ev: tuple) -> bool:
        """Can this log serve a delta from `ev`? True when ev is within
        [tail, head] (an empty start, (0,0), overlaps iff the log's
        tail is still the very beginning)."""
        ev = tuple(ev)
        if ev == self.head:
            return True
        if ev >= self.tail and (ev == (0, 0) or self.has_ev(ev)):
            return True
        return False

    def latest_for_oid(self, oid) -> LogEntry | None:
        for e in reversed(self.entries):
            if e.oid == oid:
                return e
        return None

    # -- authoritative merge -------------------------------------------

    def merge(self, auth_entries: list[LogEntry], auth_head: tuple
              ) -> tuple:
        """Merge an authoritative log segment into this log
        (PGLog::merge_log). Returns (updates, divergent_oids):
        updates maps oid -> need version (int > 0: recover that
        version; 0: the object must not exist locally); divergent_oids
        names objects whose LOCAL copy was written in a dead interval —
        its version xattr is a lie from a fork, so the store copy must
        be dropped before recovery, never version-compared against the
        authoritative copy.

        The last COMMON eversion splits both logs: auth entries after
        it are to-apply (missing); our entries after it are divergent
        and get undone toward the authoritative object state."""
        auth_head = tuple(auth_head)
        auth_evs = {e.ev for e in auth_entries}
        # last common point. Preferred: the newest of our entries that
        # the authoritative segment also contains. When the segment
        # shares nothing with us, it is either a contiguous extension
        # (starts past our head) or a rewind to auth_head known to be
        # in our chain — both bound the common prefix by
        # min(head, auth_head). A segment reaching below our head that
        # still shares nothing means we forked before its start: only
        # our tail is provably common.
        common = None
        for e in self.entries:
            if e.ev in auth_evs:
                common = e.ev if common is None else max(common, e.ev)
        if common is None:
            common = min(self.head, auth_head)
            if auth_entries and \
                    min(e.ev for e in auth_entries) <= common:
                common = min(self.tail, common)
        updates: dict = {}
        divergent_oids: set = set()

        # 1. divergent local entries (ours, newer than common, not in
        #    the authoritative chain)
        divergent = [e for e in self.entries
                     if e.ev > common and e.ev not in auth_evs]
        divergent_oids = {e.oid for e in divergent}
        auth_latest: dict = {}
        for e in auth_entries:
            auth_latest[e.oid] = e
        reverted: set = set()
        for e in divergent:
            ae = auth_latest.get(e.oid)
            if ae is not None and ae.ev <= auth_head:
                # authoritative chain has its own (older or newer)
                # truth for the object
                updates[e.oid] = 0 if ae.kind == "delete" else \
                    ae.version
            elif e.oid not in reverted:
                # the object's only history beyond common is divergent:
                # revert to its state AT common — the EARLIEST divergent
                # entry's prior_version (later divergent entries' priors
                # are themselves divergent versions nobody can serve)
                updates[e.oid] = e.prior_version
                reverted.add(e.oid)
        # drop divergent entries from our log (rewind)
        self.entries = [e for e in self.entries
                        if e.ev <= common or e.ev in auth_evs]

        # 2. apply the authoritative delta
        for e in sorted(auth_entries, key=lambda x: x.ev):
            if e.ev <= common:
                continue
            updates[e.oid] = 0 if e.kind == "delete" else e.version
            self.entries.append(e)
        self.entries.sort(key=lambda x: x.ev)
        self.head = max(auth_head, common)
        self._trim()
        return updates, divergent_oids

    # -- (de)serialization ---------------------------------------------

    def dump(self) -> list:
        return [(e.epoch, e.version, e.oid, e.kind, e.prior_version,
                 e.reqid[0], e.reqid[1]) for e in self.entries]

    def load(self, rows: list) -> None:
        self.entries = [entry_from_tuple(r) for r in rows]
        self.entries.sort(key=lambda e: e.ev)
        if self.entries:
            self.head = self.entries[-1].ev
            self.tail = self.entries[0].ev
        self._trim()
