"""Upmap balancer: flattens the PG distribution with pg_upmap_items.

Role of the reference's OSDMap::calc_pg_upmaps
(/root/reference/src/osd/OSDMap.cc:3763), OSDMap::try_pg_upmap (:3718),
CrushWrapper::try_remap_rule / _choose_type_stack
(/root/reference/src/crush/CrushWrapper.cc) and the mgr balancer
module's upmap mode (/root/reference/src/pybind/mgr/balancer): compute
per-OSD PG deviation from the CRUSH-weight target, then greedily
evacuate the fullest OSDs by (a) dropping existing pg_upmap_items that
land on them and (b) adding new items that remap one PG shard from an
overfull to an underfull device — never violating the placement rule's
failure-domain separation.

TPU-first: the expensive part of every balancer round is the
all-PG placement sweep, which the reference computes with
ParallelPGMapper CPU threads.  Here each pool's whole PG range maps in
ONE batched device CRUSH program (ceph_tpu.crush.batched via
OSDMapMapping.update), so the sweep that runs once per accepted change
rides the accelerator; the greedy bookkeeping between sweeps is cheap
host code.

Failure-domain validity: the reference re-walks the rule per candidate
(_choose_type_stack) to pick a replacement inside a compatible bucket.
This implementation instead proposes a replacement from the underfull
list and then checks the resulting mapping is one the rule could have
produced: every device lies under the rule's take root, and the number
of distinct failure-domain buckets (the deepest typed choose step) does
not decrease.  That invariant is what the reference's per-level walk
ultimately guarantees; checking it directly is simpler and equally
safe.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..crush.map import CRUSH_ITEM_NONE, CrushMap
from .osd_map import PGID, Incremental, OSDMap, OSDMapMapping

__all__ = ["calc_pg_upmaps", "eval_distribution", "BalancerResult",
           "Distribution", "measure_sweep"]


# ---------------------------------------------------------------------------
# crush topology helpers


def rule_take_roots(crush: CrushMap, ruleno: int) -> list[int]:
    """Bucket/device ids named by the rule's take steps."""
    if not (0 <= ruleno < len(crush.rules)):
        return []
    return [step[1] for step in crush.rules[ruleno].steps
            if step[0] == "take"]


def rule_failure_domain(crush: CrushMap, ruleno: int) -> int:
    """The separation domain: the deepest non-device type named by a
    choose/chooseleaf step (0 = no bucket-type separation, devices
    only)."""
    domain = 0
    if not (0 <= ruleno < len(crush.rules)):
        return domain
    for step in crush.rules[ruleno].steps:
        if step[0].startswith("choose") and len(step) >= 3 and \
                step[2] > 0:
            domain = step[2]
    return domain


def parent_index(crush: CrushMap) -> dict[int, int]:
    """item id -> containing bucket id (CRUSH trees have one parent)."""
    idx: dict[int, int] = {}
    for bid, bucket in crush.buckets.items():
        for item in bucket.items:
            idx[int(item)] = bid
    return idx


def parent_of_type(crush: CrushMap, item: int, type_id: int,
                   pindex: dict[int, int]) -> int | None:
    """Walk up from item to its ancestor bucket of type_id
    (CrushWrapper::get_parent_of_type)."""
    cur = item
    while True:
        parent = pindex.get(cur)
        if parent is None:
            return None
        if crush.buckets[parent].type == type_id:
            return parent
        cur = parent


def subtree_devices(crush: CrushMap, root: int) -> set[int]:
    """All device ids beneath root (root may itself be a device)."""
    if root >= 0:
        return {root}
    out: set[int] = set()
    stack = [root]
    while stack:
        bid = stack.pop()
        bucket = crush.buckets.get(bid)
        if bucket is None:
            continue
        for item in bucket.items:
            item = int(item)
            if item >= 0:
                out.add(item)
            else:
                stack.append(item)
    return out


def rule_weight_osd_map(crush: CrushMap, ruleno: int) -> dict[int, float]:
    """Per-device CRUSH weight reachable through the rule's take steps
    (CrushWrapper::get_rule_weight_osd_map): the balancer's notion of
    each OSD's fair share."""
    out: dict[int, float] = {}
    for root in rule_take_roots(crush, ruleno):
        if root >= 0:
            out[root] = out.get(root, 0.0) + 1.0
            continue
        stack = [root]
        while stack:
            bid = stack.pop()
            bucket = crush.buckets.get(bid)
            if bucket is None:
                continue
            for item, w in zip(bucket.items, bucket.weights):
                item = int(item)
                if item >= 0:
                    out[item] = out.get(item, 0.0) + int(w) / 0x10000
                else:
                    stack.append(item)
    return out


# ---------------------------------------------------------------------------
# distribution evaluation (balancer eval / the verify re-sweep)


@dataclass
class Distribution:
    pg_counts: dict[int, int]           # osd -> #up PG shards
    targets: dict[int, float]           # osd -> fair share (pgs)
    total_deviation: float
    stddev: float

    def deviation(self, osd: int) -> float:
        return self.pg_counts.get(osd, 0) - self.targets.get(osd, 0.0)


def _sweep(osdmap: OSDMap, pools: set[int] | None,
           use_device: bool,
           use_mesh: bool = False,
           use_native: bool = False) -> dict[PGID, list[int]]:
    """All-PG up mappings — one batched device CRUSH program per pool
    (the ParallelPGMapper-analog step of every balancer round).  With
    use_mesh the PG batch is sharded across every local chip
    (crush.batched.mesh_do_rule) instead of running on one device;
    use_native runs the compiled host mapper (bit-identical, and the
    honest CPU comparator when no real accelerator is attached)."""
    mapping = OSDMapMapping()
    mapping.update(osdmap, batched=use_device or use_mesh or use_native,
                   mesh=True if use_mesh else None, native=use_native)
    out: dict[PGID, list[int]] = {}
    for pgid, (up, _up_p, _acting, _acting_p) in mapping.by_pg.items():
        if pools is not None and pgid.pool not in pools:
            continue
        out[pgid] = up
    return out


def measure_sweep(osdmap: OSDMap, use_device: bool,
                  pools: set[int] | None = None,
                  use_mesh: bool = False,
                  use_native: bool = False) -> float:
    """Wall-time of one all-PG placement sweep on the named backend
    (mesh = PG batch sharded across local chips, device = batched
    CRUSH program on one chip, native = the host mapper).  The mgr
    balancer's measured-speed backend selection (ROADMAP #4) feeds on
    these instead of assuming the device always wins — on a single
    chip behind a slow transport the host sweep often does, and on a
    small map the mesh's collective overhead can lose to one chip."""
    import time as _time
    t0 = _time.perf_counter()
    _sweep(osdmap, pools, use_device, use_mesh=use_mesh,
           use_native=use_native)
    return _time.perf_counter() - t0


def _targets(osdmap: OSDMap,
             pools: set[int] | None) -> tuple[dict[int, float], float]:
    """Per-OSD fair share: (weights, pgs_per_weight).  Shared by the
    scorer and the optimizer so `balancer eval` always agrees with the
    deviations calc_pg_upmaps acted on."""
    total_pgs = 0
    weights: dict[int, float] = {}
    weight_total = 0.0
    for pool_id, pool in osdmap.pools.items():
        if pools is not None and pool_id not in pools:
            continue
        total_pgs += pool.size * pool.pg_num
        for osd, w in rule_weight_osd_map(osdmap.crush,
                                          pool.crush_rule).items():
            # only devices that are in (weight > 0) can hold data
            if osd < osdmap.max_osd and osdmap.is_in(osd):
                weights[osd] = weights.get(osd, 0.0) + w
                weight_total += w
    per_weight = total_pgs / weight_total if weight_total > 0 else 0.0
    return weights, per_weight


def eval_distribution(osdmap: OSDMap, pools: set[int] | None = None,
                      use_device: bool = True,
                      use_mesh: bool = False,
                      use_native: bool = False) -> Distribution:
    """Score the current map: per-OSD up-PG counts vs CRUSH-weight
    targets (the `balancer eval` / OSDUtilizationDumper role)."""
    by_pg = _sweep(osdmap, pools, use_device, use_mesh=use_mesh,
                   use_native=use_native)
    counts: dict[int, int] = {}
    for up in by_pg.values():
        for osd in up:
            if osd != CRUSH_ITEM_NONE:
                counts[osd] = counts.get(osd, 0) + 1
    weights, per_weight = _targets(osdmap, pools)
    targets: dict[int, float] = {}
    for osd, w in weights.items():
        targets[osd] = w * per_weight
        counts.setdefault(osd, 0)
    devs = [counts.get(o, 0) - t for o, t in targets.items()]
    total_dev = float(sum(abs(d) for d in devs))
    stddev = float(np.std(devs)) if devs else 0.0
    return Distribution(counts, targets, total_dev, stddev)


# ---------------------------------------------------------------------------
# the optimizer


@dataclass
class BalancerResult:
    num_changed: int = 0
    start_deviation: float = 0.0
    end_deviation: float = 0.0
    sweeps: int = 0
    # the proposal, Incremental-shaped
    new_pg_upmap_items: dict[PGID, list] = field(default_factory=dict)
    old_pg_upmap_items: list[PGID] = field(default_factory=list)

    def apply_to(self, inc: Incremental) -> None:
        inc.new_pg_upmap_items.update(self.new_pg_upmap_items)
        for pgid in self.old_pg_upmap_items:
            # a pgid dropped in one sweep and re-added in a later one
            # must land as a SET, not a removal (apply_incremental
            # processes removals last)
            if pgid not in self.new_pg_upmap_items and \
                    pgid not in inc.old_pg_upmap_items:
                inc.old_pg_upmap_items.append(pgid)


def _try_pg_upmap(osdmap: OSDMap, pgid: PGID, overfull: set[int],
                  underfull: list[int]) -> list[tuple[int, int]] | None:
    """Propose (src, dst) item pairs moving pgid's overfull shards to
    underfull devices while preserving the rule's placement validity
    (OSDMap::try_pg_upmap + CrushWrapper::try_remap_rule role)."""
    pool = osdmap.pools.get(pgid.pool)
    if pool is None:
        return None
    crush = osdmap.crush
    ruleno = pool.crush_rule
    orig, _pps = osdmap._pg_to_raw_osds(pool, pgid)
    if not any(o in overfull for o in orig if o != CRUSH_ITEM_NONE):
        return None
    allowed: set[int] = set()
    for root in rule_take_roots(crush, ruleno):
        allowed |= subtree_devices(crush, root)
    fd_type = rule_failure_domain(crush, ruleno)
    pindex = parent_index(crush)

    def domains(osds) -> list:
        return [parent_of_type(crush, o, fd_type, pindex)
                for o in osds if o != CRUSH_ITEM_NONE]

    orig_domains = domains(orig)
    out = list(orig)
    used = {o for o in out if o != CRUSH_ITEM_NONE}
    for i, osd in enumerate(out):
        if osd == CRUSH_ITEM_NONE or osd not in overfull:
            continue
        for cand in underfull:
            if cand in used or cand not in allowed:
                continue
            trial = list(out)
            trial[i] = cand
            if fd_type > 0:
                # separation must not degrade: at least as many
                # distinct failure-domain buckets as CRUSH produced
                if len(set(domains(trial))) < len(set(orig_domains)):
                    continue
            out = trial
            used.add(cand)
            break
    if out == orig:
        return None
    return [(orig[i], out[i]) for i in range(len(orig))
            if orig[i] != out[i]]


def calc_pg_upmaps(osdmap: OSDMap,
                   max_deviation: float = 1.0,
                   max_deviation_ratio: float = 0.0,
                   max_changes: int = 10,
                   pools: set[int] | None = None,
                   use_device: bool = True,
                   use_mesh: bool = False,
                   use_native: bool = False,
                   changes_per_sweep: int = 1) -> BalancerResult:
    """Greedy upmap optimization, one accepted change per device
    sweep, mirroring OSDMap::calc_pg_upmaps' restart loop.  Stops
    when the fullest OSD sits within max_deviation PGs of its target
    (and, when max_deviation_ratio > 0, additionally within that
    ratio of the target).  Returns the proposal; the caller routes it
    through the monitor ("osd pg-upmap-items" /
    "osd rm-pg-upmap-items") or an Incremental.

    changes_per_sweep > 1 amortizes the device sweep at scale (ISSUE
    19 huge-map convergence): each accepted remap updates the sweep's
    pg/deviation bookkeeping locally and the batch keeps hunting, so
    a 1000-OSD map converges in O(deviation / batch) sweeps instead
    of one CRUSH sweep per change."""
    tmp = osdmap.clone()
    res = BalancerResult()
    remaining = max_changes
    while remaining > 0:
        by_pg = _sweep(tmp, pools, use_device, use_mesh=use_mesh,
                       use_native=use_native)
        res.sweeps += 1
        pgs_by_osd: dict[int, list[PGID]] = {}
        for pgid, up in sorted(by_pg.items(),
                               key=lambda kv: (kv[0].pool, kv[0].ps)):
            for osd in up:
                if osd != CRUSH_ITEM_NONE:
                    pgs_by_osd.setdefault(osd, []).append(pgid)
        weights, per_weight = _targets(tmp, pools)
        if per_weight <= 0:
            break
        deviations: dict[int, float] = {}
        overfull: set[int] = set()
        total_deviation = 0.0
        for osd, w in weights.items():
            pgs_by_osd.setdefault(osd, [])
            dev = len(pgs_by_osd[osd]) - w * per_weight
            deviations[osd] = dev
            if dev >= 1.0:
                overfull.add(osd)
            total_deviation += abs(dev)
        # devices carrying PGs but outside every rule's weight map
        # (e.g. weight zeroed mid-flight) are maximally overfull
        for osd, pgs in pgs_by_osd.items():
            if osd not in deviations:
                deviations[osd] = float(len(pgs))
                if pgs:
                    overfull.add(osd)
                total_deviation += len(pgs)
        if res.sweeps == 1:
            res.start_deviation = total_deviation
        res.end_deviation = total_deviation
        underfull = [osd for osd, dev in
                     sorted(deviations.items(),
                            key=lambda kv: (kv[1], kv[0]))
                     if dev < -0.999]
        if not overfull or not underfull:
            break
        underfull_set = set(underfull)
        batch = max(1, int(changes_per_sweep))
        accepted = 0
        while accepted < batch and remaining > 0:
            changed = None             # (pgid, pairs|None) on accept
            for osd in sorted(deviations, key=lambda o: -deviations[o]):
                dev = deviations[osd]
                target = weights.get(osd, 0.0) * per_weight
                if max_deviation_ratio > 0 and target > 0 and \
                        dev / target < max_deviation_ratio:
                    break              # fullest is within tolerance
                if dev < max(1.0, max_deviation):
                    break
                # 1) un-remap: drop existing items landing on this osd
                for pgid in pgs_by_osd[osd]:
                    items = tmp.pg_upmap_items.get(pgid)
                    if items and any(dst == osd
                                     for _src, dst in items):
                        tmp.pg_upmap_items.pop(pgid)
                        res.new_pg_upmap_items.pop(pgid, None)
                        res.old_pg_upmap_items.append(pgid)
                        res.num_changed += 1
                        changed = (pgid, None)
                        break
                if changed is not None:
                    break
                # 2) remap one PG shard off this osd
                for pgid in pgs_by_osd[osd]:
                    if pgid in tmp.pg_upmap \
                            or pgid in tmp.pg_upmap_items:
                        continue
                    pairs = _try_pg_upmap(tmp, pgid, overfull,
                                          underfull)
                    if pairs is None:
                        continue
                    tmp.pg_upmap_items[pgid] = pairs
                    res.new_pg_upmap_items[pgid] = pairs
                    res.num_changed += 1
                    changed = (pgid, pairs)
                    break
                if changed is not None:
                    break
            if changed is None:
                break
            remaining -= 1
            accepted += 1
            if accepted >= batch or remaining <= 0:
                break
            pgid, pairs = changed
            if pairs is None:
                # un-remap: the shard falls back to its raw CRUSH
                # placement, unknowable without a sweep — stop the
                # batch and resweep
                break
            # local bookkeeping: move the shard so the rest of the
            # batch sees it without paying for a device sweep
            for src, dst in pairs:
                if pgid in pgs_by_osd.get(src, ()):
                    pgs_by_osd[src].remove(pgid)
                    pgs_by_osd.setdefault(dst, []).append(pgid)
                    deviations[src] = deviations.get(src, 0.0) - 1
                    deviations[dst] = deviations.get(dst, 0.0) + 1
                    if deviations[src] < 1.0:
                        overfull.discard(src)
                    if deviations[dst] >= 1.0:
                        overfull.add(dst)
                        underfull_set.discard(dst)
                    if deviations[dst] >= -0.999:
                        underfull_set.discard(dst)
            underfull = [o for o in
                         sorted(underfull_set,
                                key=lambda o: (deviations.get(o, 0.0),
                                               o))
                         if deviations.get(o, 0.0) < -0.999]
            if not overfull or not underfull:
                break
        if accepted == 0:
            break                      # no further improvement found
    return res
