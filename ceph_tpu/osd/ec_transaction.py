"""EC write planning + per-shard transaction generation.

Role of the reference's ECTransaction (src/osd/ECTransaction.{h,cc}):

  get_write_plan          walk the PGTransaction computing, per object,
                          the stripe-aligned extents that must be READ
                          (partial head/tail stripes of an overwrite —
                          RMW) and WRITTEN (ECTransaction.h:44-183);
                          tracks projected object sizes via HashInfo
  generate_transactions   with the readback data in hand, overlay the
                          logical writes, ENCODE (the hot loop,
                          ECTransaction.cc:45 -> ECUtil::encode), and
                          emit one ObjectStore Transaction per shard
                          with the chunk writes + hinfo xattr
                          (:645-653)

TPU-first: each object's whole will_write region encodes in ONE batched
device call via ec_util.encode.
"""

from __future__ import annotations

import json

import numpy as np

from ..common.interval_set import IntervalSet
from ..store.object_store import Transaction
from . import ec_util

__all__ = ["WritePlan", "get_write_plan", "generate_transactions",
           "HINFO_KEY"]

HINFO_KEY = "hinfo_key"  # reference ECUtil::get_hinfo_key()


class WritePlan:
    def __init__(self):
        self.t = None                   # the PGTransaction
        self.invalidates_cache = False
        self.to_read: dict = {}         # oid -> IntervalSet (logical)
        self.will_write: dict = {}      # oid -> IntervalSet (superset)
        self.hash_infos: dict = {}      # oid -> HashInfo


def get_write_plan(sinfo: ec_util.StripeInfo, t, get_hinfo) -> WritePlan:
    """Mirror of the get_write_plan template (ECTransaction.h:44-183)."""
    plan = WritePlan()
    for oid, op in t.safe_create_traverse():
        hinfo = get_hinfo(oid)
        plan.hash_infos[oid] = hinfo
        projected_size = hinfo.get_projected_total_logical_size(sinfo)

        if op.deletes_first():
            projected_size = 0

        if op.has_source():
            plan.invalidates_cache = True
            shinfo = get_hinfo(op.source)
            projected_size = shinfo.get_projected_total_logical_size(sinfo)
            plan.hash_infos[op.source] = shinfo

        will_write = plan.will_write.setdefault(oid, IntervalSet())

        # a COMPRESSED object (fused write transform, hinfo.comp_info)
        # cannot be partially overwritten in place: logical offsets
        # don't map to stored chunk offsets.  Any mutation becomes a
        # full-object RMW — read the whole object back (the read path
        # decompresses), overlay, rewrite whole
        if getattr(hinfo, "comp_info", None) is not None \
                and not op.deletes_first() and projected_size > 0 \
                and (op.buffer_updates or op.truncate is not None):
            plan.to_read.setdefault(oid, IntervalSet()).union_insert(
                0, projected_size)
            will_write.union_insert(0, projected_size)

        # unaligned truncate-down: rewrite the boundary stripe
        if op.truncate is not None and op.truncate[0] < projected_size:
            trunc = op.truncate[0]
            if not sinfo.logical_offset_is_stripe_aligned(trunc):
                start = sinfo.logical_to_prev_stripe_offset(trunc)
                plan.to_read.setdefault(oid, IntervalSet()).union_insert(
                    start, sinfo.stripe_width)
                will_write.union_insert(start, sinfo.stripe_width)
            projected_size = sinfo.logical_to_next_stripe_offset(trunc)

        raw_write_set = IntervalSet()
        for upd in op.buffer_updates:
            off = upd[1]
            length = len(upd[2]) if upd[0] == "write" else upd[2]
            raw_write_set.union_insert(off, length)

        orig_size = projected_size
        for off, length in raw_write_set:
            head_start = sinfo.logical_to_prev_stripe_offset(off)
            head_finish = sinfo.logical_to_next_stripe_offset(off)
            if head_start > projected_size:
                head_start = projected_size
            if head_start != head_finish and head_start < orig_size:
                plan.to_read.setdefault(oid, IntervalSet()).union_insert(
                    head_start, sinfo.stripe_width)

            tail_start = sinfo.logical_to_prev_stripe_offset(off + length)
            tail_finish = sinfo.logical_to_next_stripe_offset(off + length)
            if tail_start != tail_finish and \
                    (head_start == head_finish or tail_start != head_start) \
                    and tail_start < orig_size:
                plan.to_read.setdefault(oid, IntervalSet()).union_insert(
                    tail_start, sinfo.stripe_width)

            if head_start != tail_finish:
                will_write.union_insert(head_start,
                                        tail_finish - head_start)
                if tail_finish > projected_size:
                    projected_size = tail_finish

        # truncate-up (or post-write final truncate) extends with zeros
        if op.truncate is not None and op.truncate[1] > projected_size:
            truncating_to = sinfo.logical_to_next_stripe_offset(
                op.truncate[1])
            will_write.union_insert(projected_size,
                                    truncating_to - projected_size)
            projected_size = truncating_to

        hinfo.set_projected_total_logical_size(sinfo, projected_size)
    plan.t = t
    return plan


def generate_transactions(plan: WritePlan, codec,
                          sinfo: ec_util.StripeInfo,
                          partial_extents: dict,
                          shards: list,
                          cid_of, dispatcher=None,
                          trace=None, tier=None,
                          tier_prefix=None,
                          fused_mode: str | None = None,
                          fused_required_ratio: float = 0.875,
                          fused_entropy_max: float = 7.0
                          ) -> tuple[dict, dict]:
    """Build {shard: Transaction} from the plan + readback data.

    partial_extents: oid -> ExtentMap with the to_read stripes filled
    (from cache or remote shard reads). cid_of(shard) names the target
    collection. Returns (transactions, written) where written maps
    oid -> ExtentMap of the logical bytes this op wrote (fed back into
    the ExtentCache, mirroring generate_transactions' `written` out-param).

    tier/tier_prefix wire the HbmChunkTier: EVERY mutation of an
    object first invalidates its resident entry (a stale resident copy
    must never serve a later scrub/recovery/read), and a whole-object
    write re-adopts the encode device-side through the dispatcher
    pipeline — partial RMWs stay host-planned and simply leave the
    object non-resident until its next full write.

    fused_mode routes whole-object writes through the fused write
    transform (ec_util.encode_fused: digests + compress decision + EC
    encode in one device program): "store" fuses digests+encode,
    "compress" additionally lets the device compress the stored
    stream; None/"off" keeps the classic encode.  Partial RMWs and
    ops carrying a truncate always take the classic path.
    """
    txns = {shard: Transaction() for shard in shards}
    written: dict = {}
    n = codec.get_chunk_count()
    fused_ok = (fused_mode not in (None, "", "off")
                and dispatcher is not None
                and dispatcher.fused_supported(codec))

    for oid, op in plan.t.safe_create_traverse():
        tier_key = None
        if tier is not None:
            tier_key = (tier_prefix, oid)
            tier.drop(tier_key)        # any mutation invalidates
        hinfo = plan.hash_infos[oid]

        if op.deletes_first():
            for shard, txn in txns.items():
                txn.remove(cid_of(shard), oid)
            hinfo.clear()

        if op.init_type == "clone":
            for shard, txn in txns.items():
                txn.clone(cid_of(shard), op.source, oid)
        elif op.init_type == "rename":
            for shard, txn in txns.items():
                txn.collection_move_rename(cid_of(shard), op.source,
                                           cid_of(shard), oid)
        elif op.init_type == "create":
            for shard, txn in txns.items():
                txn.touch(cid_of(shard), oid)

        will_write = plan.will_write.get(oid) or IntervalSet()
        if will_write:
            pex = partial_extents.get(oid)
            wmap = written.setdefault(oid, {})
            appends = {}
            extents = list(will_write)
            # residency: only a single extent covering the whole
            # (projected) object is adopted — its encode IS the full
            # chunk set, so the resident copy can serve any later
            # scrub digest, shard rebuild or whole-object read
            whole_object = (
                len(extents) == 1
                and extents[0][0] == 0 and extents[0][1] > 0
                and extents[0][1] ==
                hinfo.get_projected_total_logical_size(sinfo))
            # fused write transform: whole-object writes without a
            # truncate ride the single device program (a truncate's
            # chunk arithmetic runs in logical space and must not cut
            # a freshly compressed stream)
            use_fused = (fused_ok and whole_object
                         and op.truncate is None)
            fused_res = None
            for off, length in extents:
                # assemble the logical bytes for this extent: readback
                # stripes overlaid with the op's buffer updates,
                # zero-filled elsewhere
                buf = np.zeros(length, dtype=np.uint8)
                if pex is not None:
                    got = pex.get(off, length)
                    if got is None:
                        for s, d in pex:
                            e = s + d.size
                            lo, hi = max(s, off), min(e, off + length)
                            if lo < hi:
                                buf[lo - off:hi - off] = \
                                    d[lo - s:hi - s]
                    else:
                        buf[:] = got
                for upd in op.buffer_updates:
                    if upd[0] == "write":
                        uoff, data = upd[1], np.frombuffer(upd[2],
                                                           np.uint8)
                    else:
                        uoff, data = upd[1], np.zeros(upd[2], np.uint8)
                    lo = max(uoff, off)
                    hi = min(uoff + data.size, off + length)
                    if lo < hi:
                        buf[lo - off:hi - off] = data[lo - uoff:hi - uoff]

                res = (tier, tier_key) \
                    if whole_object and tier_key is not None else None
                if use_fused:
                    encoded, fused_res = ec_util.encode_fused(
                        sinfo, codec, buf, dispatcher=dispatcher,
                        trace=trace, resident=res,
                        mode="compress" if fused_mode == "compress"
                        else "store",
                        required_ratio=fused_required_ratio,
                        entropy_max_bits=fused_entropy_max)
                else:
                    encoded = ec_util.encode(
                        sinfo, codec, buf, dispatcher=dispatcher,
                        trace=trace, resident=res)
                chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off)
                for shard in range(n):
                    if shard in txns:
                        txns[shard].write(cid_of(shard), oid, chunk_off,
                                          encoded[shard].tobytes())
                wmap[off] = buf
                appends[chunk_off] = encoded

            # hinfo chains crcs only for pure appends (overwrites
            # invalidate the chunk hash, as in the reference's
            # overwrite path).  A fused write replaces the hinfo
            # wholesale with the DEVICE-computed shard crcs — zero
            # host hashing on the whole-object write path
            old_size = hinfo.get_total_chunk_size()
            if fused_res is not None:
                stored_chunk = fused_res.used_stripes * sinfo.chunk_size
                comp = None
                if fused_res.compressed:
                    from .fused_transform import COMP_ALG
                    comp = {"alg": COMP_ALG,
                            "orig_chunk_size":
                                sinfo.aligned_logical_offset_to_chunk_offset(
                                    extents[0][1]),
                            "comp_len": fused_res.comp_len,
                            "padded_len": fused_res.padded_len}
                hinfo.set_device_hashes(fused_res.shard_crcs,
                                        stored_chunk, comp_info=comp)
                # clamp every shard file to the stored stream: a
                # rewrite of a previously-longer (or previously-raw)
                # object must not leave a stale tail behind the
                # (possibly shorter) compressed container
                for shard, txn in txns.items():
                    txn.truncate(cid_of(shard), oid, stored_chunk)
            elif all(off >= old_size for off in appends):
                for chunk_off in sorted(appends):
                    hinfo.append(chunk_off, appends[chunk_off])
            else:
                hinfo.cumulative_shard_hashes = []
                hinfo.total_chunk_size = max(
                    hinfo.total_chunk_size,
                    hinfo.projected_total_chunk_size)
                hinfo.comp_info = None   # the object is raw again

        # shard truncate to the projected size
        if op.truncate is not None:
            target = hinfo.get_projected_total_logical_size(sinfo)
            chunk_target = sinfo.aligned_logical_offset_to_chunk_offset(
                target)
            for shard, txn in txns.items():
                txn.truncate(cid_of(shard), oid, chunk_target)
            hinfo.total_chunk_size = chunk_target
            if hinfo.cumulative_shard_hashes:
                hinfo.cumulative_shard_hashes = []

        # attrs/omap mirror to every shard; hinfo xattr carries the
        # integrity state (ECTransaction.cc:645-653). A pure delete
        # leaves nothing behind, so no hinfo either.
        leaves_object = (op.init_type != "none" or bool(will_write)
                         or op.truncate is not None
                         or not op.deletes_first())
        for shard, txn in txns.items():
            cid = cid_of(shard)
            for name, value in op.attr_updates.items():
                if value is None:
                    txn.rmattr(cid, oid, name)
                else:
                    txn.setattr(cid, oid, name, value)
            if op.omap_updates:
                txn.omap_setkeys(cid, oid, op.omap_updates)
            if op.omap_rmkeys:
                txn.omap_rmkeys(cid, oid, op.omap_rmkeys)
            if not op.is_none() and leaves_object:
                txn.setattr(cid, oid, HINFO_KEY,
                            json.dumps(hinfo.to_dict()).encode())

    # convert logical written maps to ExtentMaps
    from ..common.interval_set import ExtentMap
    out_written = {}
    for oid, wmap in written.items():
        em = ExtentMap()
        for off, buf in wmap.items():
            em.insert(off, buf)
        out_written[oid] = em
    return txns, out_written
