"""Erasure-coded PG backend: the two-phase write/read/recovery pipeline.

Role of the reference's ECBackend (src/osd/ECBackend.{h,cc}):

  write   submit_transaction (:1437) -> start_rmw plans the op (:1756)
          -> the op walks three wait queues — waiting_state (needs
          readback?), waiting_reads (readback in flight), waiting_commit
          (sub-writes in flight) — advanced by try_state_to_reads
          (:1782), try_reads_to_commit (:1857, where ECTransaction
          generates per-shard transactions and MOSDECSubOpWrite fans
          out :1989), try_finish_rmw (:2017). The local shard
          self-delivers (:1998). All-shards-commit completes the op.
  replica handle_sub_write (:917): apply the shard transaction, ack
          with sub_write_committed (:840).
  read    objects_read_and_reconstruct (:2258): pick min shards via
          minimum_to_decode (:1488-1556), sub-read chunk extents
          (handle_sub_read :982 on each shard), reassemble/decode on
          reply (:1115), complete in order.
  recovery  reconstruct a lost shard from k survivors and push it
          (continue_recovery_op :531 reshaped into the PG's recovery
          drive).

TPU-first: encode/decode of whole multi-stripe extents happen as single
batched device calls through ec_util; the per-op pipeline itself is
plain host orchestration.
"""

from __future__ import annotations

import itertools
import json
import threading

import numpy as np

from ..common.bounded import BoundedDict
from ..common.interval_set import ExtentMap, IntervalSet
from ..common.lockdep import make_rlock
from ..common.tracer import NULL_SPAN, trace_ctx
from ..msg.message import (MOSDECSubOpRead, MOSDECSubOpReadReply,
                           MOSDECSubOpRepairRead,
                           MOSDECSubOpRepairReadReply,
                           MOSDECSubOpWrite, MOSDECSubOpWriteReply)
from ..store.object_store import Transaction
from . import ec_transaction, ec_util
from .extent_cache import ExtentCache
from .osd_map import CRUSH_ITEM_NONE

__all__ = ["ECBackend"]


class _InflightWrite:
    def __init__(self, tid, pg_txn, at_version, on_commit,
                 trace=NULL_SPAN):
        self.tid = tid
        self.pg_txn = pg_txn
        self.at_version = at_version
        self.on_commit = on_commit
        self.trace = trace            # the client op's span (or null)
        self.sub_spans: dict = {}     # shard -> per-shard sub-write span
        self.plan = None
        self.pin = None
        self.must_read: dict = {}     # oid -> IntervalSet
        self.remote_read_result: dict = {}  # oid -> ExtentMap
        self.pending_reads = 0
        self.pending_commits: set = set()   # shard ids
        self.state = "state"          # state -> reads -> commit -> done


class _InflightRead:
    def __init__(self, tid, oid, off, length, on_done,
                 trace=NULL_SPAN):
        self.tid = tid
        self.oid = oid
        self.off = off
        self.length = length
        self.on_done = on_done
        self.trace = trace
        self.sub_spans: dict = {}     # shard -> per-shard sub-read span
        self.raw_shards_cb = None     # recovery: wants raw shard streams
        self.shard_data: dict = {}    # shard -> bytes
        self.want_shards: set = set()
        self.chunk_off = 0
        self.chunk_len = 0
        self.errors: dict = {}


class _InflightRepair:
    """One regenerating-code rebuild: d helper fraction reads in
    flight, with helper substitution on error and an ordered fallback
    to the full-survivor decode."""

    def __init__(self, tid, oid, target_shard, chunk_total, on_done,
                 fallback):
        self.tid = tid
        self.oid = oid
        self.target_shard = target_shard
        self.chunk_total = chunk_total
        self.on_done = on_done
        self.fallback = fallback      # () -> None: survivor decode
        self.helpers: set = set()     # current helper set (d shards)
        self.tried: set = set()       # every helper ever asked
        self.fractions: dict = {}     # shard -> fraction bytes


class ECBackend:
    def __init__(self, pg, codec, stripe_width: int):
        self.pg = pg                  # owning PG (listener interface)
        self.codec = codec
        self.sinfo = ec_util.StripeInfo(codec.get_data_chunk_count(),
                                        stripe_width)
        self.cache = ExtentCache()
        self._tids = itertools.count(1)
        self.lock = make_rlock("ec-backend:%s" % (pg.pgid,))
        # the three wait queues (ECBackend.h:561-563)
        self.waiting_state: list[_InflightWrite] = []
        self.waiting_reads: list[_InflightWrite] = []
        self.waiting_commit: list[_InflightWrite] = []
        self.inflight_reads: dict = {}
        self.inflight_repairs: dict = {}
        self.hinfo_cache: dict = {}
        import uuid
        self.instance = uuid.uuid4().hex  # incarnation nonce (dedup)
        self._sub_seen: BoundedDict = BoundedDict()  # key -> committed?

    # -- geometry ------------------------------------------------------

    @property
    def k(self) -> int:
        return self.codec.get_data_chunk_count()

    @property
    def n(self) -> int:
        return self.codec.get_chunk_count()

    def get_hinfo(self, oid) -> ec_util.HashInfo:
        h = self.hinfo_cache.get(oid)
        if h is None:
            raw = self.pg.local_getattr(oid, ec_transaction.HINFO_KEY)
            if raw is not None:
                h = ec_util.HashInfo.from_dict(json.loads(
                    raw.decode() if isinstance(raw, bytes) else raw))
            else:
                h = ec_util.HashInfo(self.n)
            self.hinfo_cache[oid] = h
        return h

    # =================================================================
    # write pipeline (primary)
    # =================================================================

    def submit_transaction(self, pg_txn, at_version: int,
                           on_commit, reqid: tuple = ("", 0),
                           trace=NULL_SPAN) -> int:
        tid = next(self._tids)
        op = _InflightWrite(tid, pg_txn, at_version, on_commit,
                            trace=trace if trace is not None
                            else NULL_SPAN)
        op.reqid = reqid
        with self.lock:
            self.waiting_state.append(op)
        self.check_ops()
        return tid

    def check_ops(self) -> None:
        """Advance every queue as far as possible (check_ops :2065)."""
        while self._try_state_to_reads():
            pass
        while self._try_reads_to_commit():
            pass

    def _try_state_to_reads(self) -> bool:
        with self.lock:
            if not self.waiting_state:
                return False
            op = self.waiting_state[0]
            op.plan = ec_transaction.get_write_plan(
                self.sinfo, op.pg_txn, self.get_hinfo)
            op.pin = self.cache.open_write_pin(op.tid)
            must_read_total = 0
            for oid, to_read in op.plan.to_read.items():
                will_write = op.plan.will_write.get(oid) or IntervalSet()
                must = self.cache.reserve_extents_for_rmw(
                    oid, op.pin, to_read, will_write)
                if must:
                    op.must_read[oid] = must
                    must_read_total += 1
            for oid in op.plan.will_write:
                if oid not in op.plan.to_read:
                    self.cache.reserve_extents_for_rmw(
                        oid, op.pin, IntervalSet(),
                        op.plan.will_write[oid])
            self.waiting_state.pop(0)
            op.state = "reads"
            self.waiting_reads.append(op)
            launch = dict(op.must_read)
        # launch RMW readbacks outside the lock
        for oid, must in launch.items():
            for off, length in must:
                op.pending_reads += 1
                self._start_read(oid, off, length,
                                 lambda data, o=op, i=oid, f=off:
                                 self._rmw_read_done(o, i, f, data),
                                 internal=True)
        return True

    def _rmw_read_done(self, op, oid, off, data) -> None:
        with self.lock:
            if data is not None:
                self.cache.present_read(oid, off, data)
            op.pending_reads -= 1
        self.check_ops()

    def _try_reads_to_commit(self) -> bool:
        with self.lock:
            if not self.waiting_reads:
                return False
            op = self.waiting_reads[0]
            if op.pending_reads > 0:
                return False
            self.waiting_reads.pop(0)
            op.state = "commit"
            # collect cached extents for the planner
            partial = {}
            for oid, to_read in op.plan.to_read.items():
                partial[oid] = self.cache.get_remaining_extents_for_rmw(
                    oid, to_read)
            shards = self.pg.acting_shards()     # shard -> osd (may hole)
            # encode under its own span so the dispatcher's tpu_queue /
            # tpu_device segments nest beneath it (ECBackend.cc:1857's
            # try_reads_to_commit is where the codec runs)
            enc_span = op.trace.child("ec_encode")
            txns, written = ec_transaction.generate_transactions(
                op.plan, self.codec, self.sinfo, partial,
                list(range(self.n)), self.pg.cid_of_shard,
                dispatcher=getattr(self.pg.daemon, "tpu_dispatcher",
                                   None),
                trace=enc_span,
                # whole-object encodes stay device-resident keyed by
                # (pg, oid): scrub/recovery (and opt-in repeat reads)
                # then never re-cross the host-device pipe
                tier=getattr(self.pg.daemon, "hbm_tier", None),
                tier_prefix=str(self.pg.pgid),
                # fused write transform config (osd_fused_transform /
                # osd_fused_compression_mode options via the daemon)
                fused_mode=getattr(self.pg.daemon, "fused_mode", None),
                fused_required_ratio=getattr(
                    self.pg.daemon, "fused_required_ratio", 0.875),
                fused_entropy_max=getattr(
                    self.pg.daemon, "fused_entropy_max", 7.0))
            enc_span.finish()
            for oid, wmap in written.items():
                self.cache.present_rmw_update(oid, wmap)
            op.pending_commits = {s for s, osd in shards.items()
                                  if osd != CRUSH_ITEM_NONE}
            self.waiting_commit.append(op)
            log_entry = self.pg.mint_log_entries(
                op.plan.t.op_map, op.at_version,
                getattr(op, "reqid", ("", 0)))
        op.sub_msgs = {}
        for shard, osd in shards.items():
            if osd == CRUSH_ITEM_NONE:
                continue
            # one child span per shard sub-write (ECBackend.cc:1978-83)
            sub_span = op.trace.child("sub_write(shard=%d)" % shard)
            sub_span.keyval("osd", osd)
            op.sub_spans[shard] = sub_span
            t_id, p_id = trace_ctx(sub_span)
            msg = MOSDECSubOpWrite(
                pgid=self.pg.pgid, shard=shard, from_osd=self.pg.whoami,
                tid=op.tid, at_version=op.at_version,
                log_entries=log_entry,
                txn_ops=txns[shard].ops, map_epoch=self.pg.map_epoch(),
                instance=self.instance, trace_id=t_id,
                parent_span=p_id)
            op.sub_msgs[shard] = (osd, msg)
            if osd == self.pg.whoami:
                self.handle_sub_write(msg, local=True)
            else:
                self.pg.send_to_osd(osd, msg)
        # at-least-once: re-fan-out to unacked shards until done (a
        # dropped sub-op must not wedge the write; replicas dedup)
        self.pg.daemon.timer.add_event_after(
            1.0, self._retry_sub_writes, op.tid)
        return True

    def _retry_sub_writes(self, tid: int) -> None:
        shards_now = self.pg.acting_shards()
        target = None
        with self.lock:
            op = next((o for o in self.waiting_commit
                       if o.tid == tid), None)
            if op is None:
                return                 # completed
            msgs = dict(getattr(op, "sub_msgs", {}))
            # shards whose OSD left the acting set can never ack:
            # stop waiting (peering roll-forward owns them now)
            for shard in list(op.pending_commits):
                osd, _ = msgs.get(shard, (None, None))
                if osd is None or shards_now.get(shard) != osd:
                    op.pending_commits.discard(shard)
            pending = set(op.pending_commits)
            if not pending:
                target = op
        if target is not None:
            self._try_finish_rmw(target)
            return
        for shard in pending:
            osd, msg = msgs.get(shard, (None, None))
            if msg is not None and osd != self.pg.whoami:
                self.pg.send_to_osd(osd, msg)
        self.pg.daemon.timer.add_event_after(
            1.0, self._retry_sub_writes, tid)

    def _try_finish_rmw(self, op) -> None:
        with self.lock:
            if op.pending_commits:
                return
            if op in self.waiting_commit:
                self.waiting_commit.remove(op)
            self.cache.release_write_pin(op.pin)
            on_commit = op.on_commit
            spans = list(op.sub_spans.values())
            op.sub_spans = {}
        for span in spans:   # shards dropped mid-interval finish here
            span.finish()
        if on_commit:
            on_commit()
        self.check_ops()

    # -- replica side --------------------------------------------------

    def handle_sub_write(self, msg, local: bool = False) -> None:
        """Apply a shard transaction + log, then ack (:917-979).
        Retransmits (the primary's at-least-once fan-out) replay the
        ack without re-applying."""
        key = (getattr(msg, "instance", "") or msg.from_osd,
               msg.tid, msg.shard)
        with self.lock:
            state = self._sub_seen.get(key)
            if state is None:
                self._sub_seen[key] = False   # received, uncommitted
        if state is not None:
            # replay the ack only for a COMMITTED original; an
            # in-flight one acks by itself when its commit lands
            if state:
                reply = MOSDECSubOpWriteReply(
                    pgid=self.pg.pgid, shard=msg.shard,
                    from_osd=self.pg.whoami, tid=msg.tid,
                    committed=True, applied=True)
                if local:
                    self.handle_sub_write_reply(reply)
                else:
                    self.pg.send_to_osd(msg.from_osd, reply)
            return
        # replica-side span, stitched under the primary's per-shard
        # child via the envelope context (covers store apply + commit)
        span = self.pg.daemon.tracer.continue_trace(
            "ec_sub_write", getattr(msg, "trace_id", 0),
            getattr(msg, "parent_span", 0))
        span.keyval("shard", msg.shard)
        span.keyval("tid", msg.tid)
        txn = Transaction()
        txn.ops = list(msg.txn_ops)
        txn.trace = span             # store-level spans nest under it
        # log keys ride the same store transaction as the shard data
        self.pg.log_operation(msg.log_entries, msg.at_version,
                              msg.shard, txn=txn)
        done = threading.Event()
        # the shard txn rewrites hinfo xattrs BEHIND the cache: a
        # replica whose cache kept a pre-write (empty) entry would,
        # on becoming primary, serve a stale size — which turns a
        # snapshot-capture write into a silent no-capture
        touched = {op[2] for op in msg.txn_ops
                   if len(op) > 2 and isinstance(op[2], str)}

        def on_commit():
            with self.lock:
                self._sub_seen[key] = True
                for oid in touched:
                    self.hinfo_cache.pop(oid, None)
            span.finish()
            reply = MOSDECSubOpWriteReply(
                pgid=self.pg.pgid, shard=msg.shard,
                from_osd=self.pg.whoami, tid=msg.tid,
                committed=True, applied=True)
            if local:
                self.handle_sub_write_reply(reply)
            else:
                self.pg.send_to_osd(msg.from_osd, reply)
            done.set()

        txn.register_on_commit(on_commit)
        self.pg.store.queue_transaction(txn)

    def handle_sub_write_reply(self, msg) -> None:
        target = None
        span = None
        with self.lock:
            for op in self.waiting_commit:
                if op.tid == msg.tid:
                    op.pending_commits.discard(msg.shard)
                    span = op.sub_spans.pop(msg.shard, None)
                    target = op
                    break
        if span is not None:
            span.finish()
        if target is not None:
            self._try_finish_rmw(target)

    # =================================================================
    # read path
    # =================================================================

    def objects_read(self, oid, off: int, length: int, on_done,
                     trace=NULL_SPAN) -> None:
        """Async logical read [off, off+length) -> on_done(bytes|None).

        Sub-reads the covering chunk range from the available shards
        (data shards when whole, any k when degraded), decodes if any
        data shard is missing, slices the requested range."""
        self._start_read(oid, off, length, on_done, trace=trace)

    def _start_read(self, oid, off, length, on_done,
                    internal: bool = False, trace=NULL_SPAN) -> None:
        size = self._object_logical_size(oid)
        if size == 0:
            on_done(b"" if not internal else None)
            return
        if length == 0:
            length = max(0, size - off)
        end = min(off + length, size)
        if off >= end:
            on_done(b"")
            return
        comp = getattr(self.get_hinfo(oid), "comp_info", None)
        if comp is not None:
            # compressed stored stream (fused write transform):
            # logical offsets don't map to stored chunk offsets — read
            # the WHOLE stored stream; completion decompresses + slices
            chunk_off = 0
            chunk_len = self.get_hinfo(oid).get_total_chunk_size()
        else:
            stripe_off, stripe_len = \
                self.sinfo.offset_len_to_stripe_bounds((off, end - off))
            chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(
                stripe_off)
            chunk_len = self.sinfo.aligned_logical_offset_to_chunk_offset(
                stripe_len)

        # opt-in residency read: a resident (pg, oid) entry holds the
        # committed full chunk set, so the read is one tiny d2h of the
        # data rows — zero sub-reads, zero decode (osd_hbm_tier_
        # serve_reads; the tier invalidates on every mutation and on
        # interval changes, so a hit is always current)
        if self._tier_read(oid, off, end, on_done):
            return

        shards_avail = self.pg.acting_shards()
        # a shard whose OSD is still recovering this object would serve
        # STALE bytes — reconstruct around it (peer_missing / the
        # reference's MissingLoc role)
        stale = self.pg.osds_missing_object(oid)
        avail = {s for s, osd in shards_avail.items()
                 if osd != CRUSH_ITEM_NONE and osd not in stale}
        want = {self.codec.chunk_index(i) for i in range(self.k)}
        try:
            to_read = self.codec.minimum_to_decode(want, avail)
        except Exception:
            on_done(None)
            return

        tid = next(self._tids)
        read = _InflightRead(tid, oid, off, end - off, on_done,
                             trace=trace if trace is not None
                             else NULL_SPAN)
        read.want_shards = set(to_read)
        read.chunk_off = chunk_off
        read.chunk_len = chunk_len
        with self.lock:
            self.inflight_reads[tid] = read
        for shard in to_read:
            osd = shards_avail[shard]
            # one child span per shard sub-read, mirroring the write
            # side's per-shard children
            sub_span = read.trace.child("sub_read(shard=%d)" % shard)
            sub_span.keyval("osd", osd)
            read.sub_spans[shard] = sub_span
            t_id, p_id = trace_ctx(sub_span)
            msg = MOSDECSubOpRead(
                pgid=self.pg.pgid, shard=shard, from_osd=self.pg.whoami,
                tid=tid, to_read=[(oid, chunk_off, chunk_len, 0)],
                map_epoch=self.pg.map_epoch(), trace_id=t_id,
                parent_span=p_id)
            if osd == self.pg.whoami:
                self.handle_sub_read(msg, local=True)
            else:
                self.pg.send_to_osd(osd, msg)

    def _object_logical_size(self, oid) -> int:
        return self.get_hinfo(oid).get_total_logical_size(self.sinfo)

    # -- HBM residency consumers ---------------------------------------

    def _tier_key(self, oid) -> tuple:
        return (str(self.pg.pgid), oid)

    def _tier(self):
        return getattr(self.pg.daemon, "hbm_tier", None)

    def _tier_read(self, oid, off: int, end: int, on_done) -> bool:
        """Serve a read straight from the resident chunk set (opt-in:
        osd_hbm_tier_serve_reads). Returns True when on_done was
        called; False falls through to the sub-read path."""
        daemon = self.pg.daemon
        tier = self._tier()
        if tier is None or not getattr(daemon, "hbm_serve_reads",
                                       False):
            return False
        key = self._tier_key(oid)
        full_dev = tier.get(key)      # counts the hit/miss itself
        if full_dev is None:
            return False
        try:
            full = np.asarray(full_dev, dtype=np.uint8)
            total = full.shape[1]
            if total % self.sinfo.chunk_size:
                return False
            stripes = total // self.sinfo.chunk_size
            # rows 0..k-1 are the data chunk streams; re-interleave the
            # stripes back into the logical byte order (decode_concat's
            # finish, without the decode)
            logical = np.ascontiguousarray(
                full[:self.k].reshape(self.k, stripes,
                                      self.sinfo.chunk_size)
                .transpose(1, 0, 2)).reshape(-1)
            comp = getattr(self.get_hinfo(oid), "comp_info", None)
            if comp is not None:
                # resident rows hold the compressed container: inflate
                from . import fused_transform
                raw = fused_transform.bitplane_decompress(
                    logical[:int(comp["comp_len"])].tobytes(),
                    int(comp["padded_len"]))
                logical = np.frombuffer(
                    raw, dtype=np.uint8)[:self.get_hinfo(oid)
                                         .get_total_logical_size(
                                             self.sinfo)]
        except Exception:
            return False
        if end > logical.size:
            return False
        on_done(logical[off:end].tobytes())
        return True

    def _tier_reconstruct(self, oid, target_shard: int,
                          chunk_total: int):
        """Rebuild one shard from the RESIDENT survivors — zero
        sub-reads, zero extra h2d (the decode runs over chunks already
        in HBM; only the rebuilt shard crosses back). Returns bytes or
        None (miss / shape drift -> the caller's network path)."""
        tier = self._tier()
        if tier is None:
            return None
        key = self._tier_key(oid)
        inv = {self.codec.chunk_index(i): i for i in range(self.n)}
        row = inv.get(target_shard)
        if row is None:
            return None
        try:
            if getattr(self.codec, "alpha", 1) > 1:
                # sub-symbol codec (msr): the resident rows are chunk
                # STREAMS, but the codeword boundary is the per-stripe
                # chunk — reshape to [S, n, chunk] and decode per
                # stripe on device (tier.reconstruct's whole-stream
                # rows are only valid for byte-linear codecs)
                rebuilt = self._tier_reconstruct_striped(tier, key, row)
            else:
                # reconstruct() accounts the hit (or KeyError + miss)
                rebuilt = np.asarray(tier.reconstruct(key, (row,)),
                                     dtype=np.uint8)[0]
        except Exception:
            return None
        data = rebuilt.tobytes()
        if len(data) != chunk_total:
            return None   # stale shape (e.g. truncate raced): miss
        return data

    def _tier_reconstruct_striped(self, tier, key, row: int):
        """Stripe-aware resident rebuild for sub-symbol codecs: view
        the resident [n, total] streams as [S, n, chunk] stripes and
        decode_batch over them (still zero host reads of chunk data —
        the reshape and decode run on the already-resident buffers)."""
        import jax.numpy as jnp
        full_dev = tier.get(key)      # counts the hit/miss itself
        if full_dev is None:
            raise KeyError(key)
        total = int(full_dev.shape[1])
        if total % self.sinfo.chunk_size:
            raise ValueError("stream not chunk-aligned")
        stripes = total // self.sinfo.chunk_size
        arr = jnp.asarray(full_dev).reshape(
            self.n, stripes, self.sinfo.chunk_size).transpose(1, 0, 2)
        avail = tuple(r for r in range(self.n) if r != row)[:self.k]
        survivors = jnp.take(arr, jnp.asarray(avail, dtype=jnp.int32),
                             axis=1)
        all_rows = self.codec.decode_batch(avail, survivors)
        return np.ascontiguousarray(
            np.asarray(all_rows, dtype=np.uint8)[:, row, :]).reshape(-1)

    def handle_sub_read(self, msg, local: bool = False) -> None:
        """Raw per-shard store read (:982-1012) — no decode here.

        Full-shard reads additionally verify the stored bytes against
        the write-time hinfo crc (the reference's handle_sub_read crc
        check): silent bit-rot becomes an EIO in the reply, so the
        primary reconstructs around it exactly like a loud disk error
        instead of decoding garbage into the client's buffer."""
        span = self.pg.daemon.tracer.continue_trace(
            "ec_sub_read", getattr(msg, "trace_id", 0),
            getattr(msg, "parent_span", 0))
        span.keyval("shard", msg.shard)
        reply = MOSDECSubOpReadReply(
            pgid=self.pg.pgid, shard=msg.shard, from_osd=self.pg.whoami,
            tid=msg.tid)
        for oid, chunk_off, chunk_len, _flags in msg.to_read:
            try:
                data = self.pg.local_read_shard(msg.shard, oid,
                                                chunk_off, chunk_len)
                if chunk_off == 0 and not self._shard_crc_ok(
                        oid, msg.shard, data):
                    raise OSError(5, "shard %d of %r failed crc"
                                  % (msg.shard, oid))
                if chunk_len and len(data) < chunk_len:
                    # shard shorter than requested (e.g. mid-recovery):
                    # zero-pad so decode sees equal-length streams
                    data = data + b"\0" * (chunk_len - len(data))
                reply.buffers_read.setdefault(oid, []).append(
                    (chunk_off, data))
            except (OSError, KeyError) as e:
                reply.errors[oid] = getattr(e, "errno", None) or 5
                # clog from the shard that failed (the reference's
                # ECBackend.cc:999 "Error(s) ignored" clog role)
                clog = getattr(self.pg.daemon, "clog", None)
                if clog is not None:
                    clog.error("pg %s: error reading shard %d of %r: "
                               "%s" % (self.pg.pgid, msg.shard, oid, e))
        for name in msg.attrs_to_read:
            reply.attrs_read[name] = self.pg.local_getattr(
                msg.to_read[0][0], name)
        span.finish()
        if local:
            self.handle_sub_read_reply(reply)
        else:
            self.pg.send_to_osd(msg.from_osd, reply)

    def _shard_crc_ok(self, oid, shard: int, data: bytes) -> bool:
        """True when the bytes are trustworthy: only a read covering
        the WHOLE shard stream can be checked against the cumulative
        hinfo crc (partial reads pass through unverified — deep scrub
        owns those)."""
        try:
            h = self.get_hinfo(oid)
        except Exception:
            return True
        if not h.has_chunk_hash() or h.get_total_chunk_size() == 0:
            return True
        if len(data) != h.get_total_chunk_size():
            return True
        import zlib
        return (zlib.crc32(data) & 0xFFFFFFFF) == h.get_chunk_hash(shard)

    def handle_sub_read_reply(self, msg) -> None:
        bad_oid = None
        done_span = None
        with self.lock:
            read = self.inflight_reads.get(msg.tid)
            if read is None:
                return
            done_span = read.sub_spans.pop(msg.shard, None)
            if msg.errors:
                bad_oid = read.oid
                read.errors[msg.shard] = msg.errors
                # error on a shard: try to substitute another shard
                shards_avail = self.pg.acting_shards()
                stale = self.pg.osds_missing_object(read.oid)
                avail = {s for s, osd in shards_avail.items()
                         if osd != CRUSH_ITEM_NONE
                         and osd not in stale
                         and s not in read.errors
                         and s not in read.want_shards}
                if avail:
                    sub = min(avail)
                    read.want_shards.discard(msg.shard)
                    read.want_shards.add(sub)
                    resend = (sub, shards_avail[sub])
                else:
                    self.inflight_reads.pop(msg.tid, None)
                    on_done, read = read.on_done, None
            else:
                for oid, bufs in msg.buffers_read.items():
                    data = b"".join(b for _off, b in bufs)
                    read.shard_data[msg.shard] = data
                resend = None
        if done_span is not None:
            if msg.errors:
                done_span.keyval("error", True)
            done_span.finish()
        if bad_oid is not None:
            # the bad shard is treated as missing for THIS read, and
            # self-healed behind it: reconstruct from the survivors
            # and rewrite it in place (l_osd_read_err/l_osd_repaired
            # accounting; repair_shard dedups concurrent reads)
            self.pg.daemon.perf.inc("read_err")
            bad_osd = self.pg.acting_shards().get(msg.shard)
            if bad_osd is not None and bad_osd != CRUSH_ITEM_NONE:
                self.pg.repair_shard(bad_oid, msg.shard, bad_osd)
        if read is None:
            on_done(None)
            return
        if msg.errors and resend is not None:
            sub, osd = resend
            sub_span = read.trace.child("sub_read(shard=%d)" % sub)
            sub_span.keyval("osd", osd)
            sub_span.keyval("substituted_for", msg.shard)
            with self.lock:
                read.sub_spans[sub] = sub_span
            t_id, p_id = trace_ctx(sub_span)
            m = MOSDECSubOpRead(
                pgid=self.pg.pgid, shard=sub, from_osd=self.pg.whoami,
                tid=msg.tid,
                to_read=[(read.oid, read.chunk_off, read.chunk_len, 0)],
                map_epoch=self.pg.map_epoch(), trace_id=t_id,
                parent_span=p_id)
            if osd == self.pg.whoami:
                self.handle_sub_read(m, local=True)
            else:
                self.pg.send_to_osd(osd, m)
            return
        self._maybe_complete_read(msg.tid)

    def _maybe_complete_read(self, tid) -> None:
        with self.lock:
            read = self.inflight_reads.get(tid)
            if read is None:
                return
            if set(read.shard_data) != read.want_shards:
                return
            self.inflight_reads.pop(tid)
        for span in read.sub_spans.values():
            span.finish()        # stragglers (substituted-away shards)
        read.sub_spans = {}
        if read.raw_shards_cb is not None:
            read.raw_shards_cb(dict(read.shard_data))
            return
        # reassemble: decode the chunk streams back to logical bytes
        dec_span = read.trace.child("ec_decode")
        try:
            out = ec_util.decode_concat(
                self.sinfo, self.codec, dict(read.shard_data),
                dispatcher=getattr(self.pg.daemon, "tpu_dispatcher",
                                   None),
                trace=dec_span)
        except Exception:
            dec_span.finish()
            read.on_done(None)
            return
        dec_span.finish()
        comp = getattr(self.get_hinfo(read.oid), "comp_info", None)
        if comp is not None:
            # the decoded stream is the compressed container (fused
            # write transform): inflate it back to the logical bytes
            from . import fused_transform
            try:
                out = fused_transform.bitplane_decompress(
                    out[:int(comp["comp_len"])],
                    int(comp["padded_len"]))
            except Exception:
                read.on_done(None)
                return
            out = out[:self.get_hinfo(read.oid)
                      .get_total_logical_size(self.sinfo)]
            read.on_done(out[read.off:read.off + read.length])
            return
        stripe_off = self.sinfo.aligned_chunk_offset_to_logical_offset(
            read.chunk_off)
        start = read.off - stripe_off
        read.on_done(out[start:start + read.length])

    # =================================================================
    # recovery (reconstruct one shard and push it)
    # =================================================================

    def recover_object(self, oid, target_shard: int, on_done) -> None:
        """Reconstruct target_shard's chunk stream from k survivors.

        continue_recovery_op reshaped: read the full chunk streams from
        the available shards, decode-all (ONE batched device call),
        hand the target shard's bytes + attrs to on_done(shard_bytes)."""
        h = self.get_hinfo(oid)
        if getattr(h, "comp_info", None) is not None:
            # compressed object: the shard streams on disk are the
            # STORED (compressed) length, not the logical-derived one
            chunk_total = h.get_total_chunk_size()
        else:
            size = self._object_logical_size(oid)
            chunk_total = \
                self.sinfo.aligned_logical_offset_to_chunk_offset(
                    self.sinfo.logical_to_next_stripe_offset(size))
        if chunk_total == 0:
            on_done(b"")
            return
        # residency first: the resident chunk set rebuilds the shard
        # on device with ZERO sub-reads and zero extra h2d — scrub
        # repair and recovery both land here (ROADMAP direction A /
        # carried item 1); a miss (evicted, never adopted, invalidated)
        # falls through to the survivor sub-read path below
        resident = self._tier_reconstruct(oid, target_shard,
                                          chunk_total)
        if resident is not None:
            on_done(resident)
            return
        # repair-bandwidth-optimal path (ROADMAP direction C): when the
        # codec advertises fraction repair, helpers compute and ship
        # only beta-fraction symbols (chunk/alpha bytes each) and the
        # primary reconstructs on device — d*chunk/alpha total traffic
        # instead of k*chunk. Fewer than d live helpers (or any combine
        # failure) falls back to the full-survivor decode below.
        if self._try_repair(oid, target_shard, chunk_total, on_done):
            return
        self._recover_survivors(oid, target_shard, chunk_total, on_done)

    def _recover_survivors(self, oid, target_shard: int,
                           chunk_total: int, on_done) -> None:
        """Full-survivor recovery: read k whole chunk streams and
        decode (the classic path; also the repair path's fallback)."""
        shards_avail = self.pg.acting_shards()
        stale = self.pg.osds_missing_object(oid)
        avail = {s for s, osd in shards_avail.items()
                 if osd != CRUSH_ITEM_NONE and s != target_shard
                 and osd not in stale}
        tid = next(self._tids)
        read = _InflightRead(tid, oid, 0, 0, None)
        # the codec picks the repair set: for RS any k survivors, for
        # locality codecs (lrc/shec) the local group — fewer reads AND
        # the only set guaranteed decodable
        try:
            use = tuple(sorted(self.codec.minimum_to_decode(
                {target_shard}, avail)))
        except Exception:
            on_done(None)
            return
        if not use:
            on_done(None)
            return
        read.want_shards = set(use)
        read.chunk_off = 0
        read.chunk_len = chunk_total

        def finish(shard_data: dict):
            # cross-chip leg (ROADMAP direction D): with more than
            # one local device the survivor chunk streams shard
            # across the mesh and reconstruct in place, guarded by a
            # psum checksum — the survivors never gather onto the
            # primary's chip.  Any mesh failure (checksum trip,
            # single device, locality codec) falls back to the
            # host-buffered decode below, which still holds the
            # bytes as received.
            try:
                rebuilt = ec_util.recover_cross_chip(
                    self.sinfo, self.codec, shard_data, target_shard)
            except Exception:
                rebuilt = None
            if rebuilt is not None:
                on_done(rebuilt)
                return
            try:
                decoded = ec_util.decode(self.sinfo, self.codec,
                                         shard_data,
                                         want={target_shard})
            except Exception:
                on_done(None)
                return
            on_done(np.asarray(
                decoded[target_shard], dtype=np.uint8).tobytes())

        read.raw_shards_cb = finish
        read.on_done = lambda _data: on_done(None)  # error path only
        with self.lock:
            self.inflight_reads[tid] = read
        for shard in use:
            osd = shards_avail[shard]
            msg = MOSDECSubOpRead(
                pgid=self.pg.pgid, shard=shard, from_osd=self.pg.whoami,
                tid=tid, to_read=[(oid, 0, chunk_total, 0)],
                map_epoch=self.pg.map_epoch())
            if osd == self.pg.whoami:
                self.handle_sub_read(msg, local=True)
            else:
                self.pg.send_to_osd(osd, msg)

    # =================================================================
    # regenerating-code repair (beta-fraction helper reads)
    # =================================================================

    def _count_repair(self, which: str, nbytes: int) -> None:
        """l_osd_repair_bytes_* accounting (best-effort like
        pg._count_push: harnesses run against daemon stubs without the
        full counter set)."""
        perf = getattr(self.pg.daemon, "perf", None)
        if perf is None:
            return
        try:
            perf.inc("l_osd_repair_bytes_%s" % which, nbytes)
        except KeyError:
            pass

    def _repair_helpers_avail(self, oid, target_shard: int) -> tuple:
        shards_avail = self.pg.acting_shards()
        stale = self.pg.osds_missing_object(oid)
        avail = {s for s, osd in shards_avail.items()
                 if osd != CRUSH_ITEM_NONE and s != target_shard
                 and osd not in stale}
        return shards_avail, avail

    def _try_repair(self, oid, target_shard: int, chunk_total: int,
                    on_done) -> bool:
        """Launch a beta-fraction repair when the codec supports it and
        enough helpers are live. Returns False (caller degrades to the
        full-survivor decode) otherwise."""
        codec = self.codec
        if not getattr(codec, "supports_repair", lambda: False)():
            return False
        try:
            if not self.pg.daemon.ctx.conf.get_val(
                    "osd_ec_repair_enable"):
                return False
        except (AttributeError, KeyError):
            pass
        if chunk_total % self.sinfo.chunk_size:
            return False
        shards_avail, avail = self._repair_helpers_avail(oid,
                                                         target_shard)
        try:
            helpers = codec.minimum_to_repair(target_shard, avail)
        except Exception:
            return False   # fewer than d live helpers
        tid = next(self._tids)
        rep = _InflightRepair(
            tid, oid, target_shard, chunk_total, on_done,
            fallback=lambda: self._recover_survivors(
                oid, target_shard, chunk_total, on_done))
        rep.helpers = set(helpers)
        rep.tried = set(helpers)
        with self.lock:
            self.inflight_repairs[tid] = rep
        for shard in sorted(helpers):
            self._send_repair_read(rep, shard, shards_avail)
        return True

    def _send_repair_read(self, rep, shard: int,
                          shards_avail: dict) -> None:
        msg = MOSDECSubOpRepairRead(
            pgid=self.pg.pgid, shard=shard, from_osd=self.pg.whoami,
            tid=rep.tid, oid=rep.oid, target_shard=rep.target_shard,
            chunk_len=rep.chunk_total, map_epoch=self.pg.map_epoch())
        osd = shards_avail.get(shard)
        if osd == self.pg.whoami:
            self.handle_repair_read(msg, local=True)
        else:
            self.pg.send_to_osd(osd, msg)

    def handle_repair_read(self, msg, local: bool = False) -> None:
        """Helper side: read own shard stream, verify its crc, project
        it to the beta fraction ON THIS OSD's device, ship only that.
        Any failure becomes an errno reply so the primary substitutes
        another helper (repair bytes are counted only on success, so a
        failed helper never inflates the traffic accounting)."""
        reply = MOSDECSubOpRepairReadReply(
            pgid=self.pg.pgid, shard=msg.shard,
            from_osd=self.pg.whoami, tid=msg.tid, oid=msg.oid)
        try:
            data = self.pg.local_read_shard(msg.shard, msg.oid, 0,
                                            msg.chunk_len)
            if not self._shard_crc_ok(msg.oid, msg.shard, data):
                raise OSError(5, "shard %d of %r failed crc"
                              % (msg.shard, msg.oid))
            if msg.chunk_len and len(data) < msg.chunk_len:
                data = data + b"\0" * (msg.chunk_len - len(data))
            reply.fraction = ec_util.repair_fraction(
                self.sinfo, self.codec, msg.target_shard, data,
                dispatcher=getattr(self.pg.daemon, "tpu_dispatcher",
                                   None))
            self._count_repair("read", len(data))
            self._count_repair("shipped", len(reply.fraction))
        except Exception as e:
            reply.error = getattr(e, "errno", None) or 5
            clog = getattr(self.pg.daemon, "clog", None)
            if clog is not None:
                clog.error("pg %s: repair fraction of shard %d of %r "
                           "failed: %s" % (self.pg.pgid, msg.shard,
                                           msg.oid, e))
        if local:
            self.handle_repair_read_reply(reply)
        else:
            self.pg.send_to_osd(msg.from_osd, reply)

    def handle_repair_read_reply(self, msg) -> None:
        """Primary side: collect fractions; on a helper error
        substitute an untried helper (any d survivors work for the
        product-matrix construction) or abandon to the full-survivor
        decode; combine when all d fractions are in."""
        fallback = None
        resend = None
        done = None
        bad = False
        with self.lock:
            rep = self.inflight_repairs.get(msg.tid)
            if rep is None:
                return
            if msg.error:
                bad = msg.shard in rep.helpers
                rep.helpers.discard(msg.shard)
                rep.fractions.pop(msg.shard, None)
                shards_avail, avail = self._repair_helpers_avail(
                    rep.oid, rep.target_shard)
                candidates = avail - rep.tried
                if candidates:
                    sub = min(candidates)
                    rep.helpers.add(sub)
                    rep.tried.add(sub)
                    resend = (rep, sub, shards_avail)
                else:
                    self.inflight_repairs.pop(msg.tid, None)
                    fallback = rep.fallback
            else:
                # accept only an awaited, not-yet-delivered fraction:
                # a duplicate delivery must not double-collect
                if msg.shard in rep.helpers and \
                        msg.shard not in rep.fractions:
                    rep.fractions[msg.shard] = msg.fraction
                if set(rep.fractions) == rep.helpers and \
                        len(rep.fractions) == \
                        self.codec.repair_helper_count():
                    self.inflight_repairs.pop(msg.tid, None)
                    done = rep
        if bad:
            # same self-heal as the read path: the helper's shard
            # failed its crc/read — rewrite it behind this rebuild
            self.pg.daemon.perf.inc("read_err")
            bad_osd = self.pg.acting_shards().get(msg.shard)
            if bad_osd is not None and bad_osd != CRUSH_ITEM_NONE:
                self.pg.repair_shard(msg.oid, msg.shard, bad_osd)
        if fallback is not None:
            fallback()
            return
        if resend is not None:
            rep, sub, shards_avail = resend
            self._send_repair_read(rep, sub, shards_avail)
            return
        if done is not None:
            self._finish_repair(done)

    def _finish_repair(self, rep) -> None:
        """All d fractions in: combine on device — mesh psum path
        first (parallel.mesh.repair_sharded), then the dispatcher/host
        combine; any failure degrades to the full-survivor decode."""
        out = None
        try:
            out = ec_util.repair_cross_chip(
                self.sinfo, self.codec, rep.target_shard,
                dict(rep.fractions))
        except Exception:
            out = None
        if out is None:
            try:
                out = ec_util.repair_combine(
                    self.sinfo, self.codec, rep.target_shard,
                    dict(rep.fractions),
                    dispatcher=getattr(self.pg.daemon,
                                       "tpu_dispatcher", None))
            except Exception:
                rep.fallback()
                return
        shipped = sum(len(v) for v in rep.fractions.values())
        self._count_repair(
            "saved", max(0, self.k * rep.chunk_total - shipped))
        rep.on_done(out)
