"""Per-op event history + in-flight op tracking.

Rendition of the reference's OpTracker/OpRequest
(/root/reference/src/osd/OpRequest.{h,cc},
src/common/TrackedOp.{h,cc}): every client op carries a timestamped
event trail (queued, reached_pg, started, commit_sent, done); the
tracker holds all in-flight ops plus a bounded history of completed
ones, served over the admin socket as `dump_ops_in_flight` /
`dump_historic_ops` — and flags ops older than the complaint time the
way the OSD's "slow request" warnings do.

Clocks: every duration/age/complaint decision runs on time.monotonic()
(a wall-clock step must not fabricate or mask slow requests); the
wall-clock `initiated_at` is kept for DISPLAY only, and event stamps
render as wall times derived from the monotonic deltas.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

__all__ = ["OpRequest", "OpTracker"]

_ids = itertools.count(1)


class OpRequest:
    def __init__(self, description: str, tracker: "OpTracker | None" = None):
        self.id = next(_ids)
        self.description = description
        self.initiated_at = time.time()        # wall clock, display only
        self.initiated_mono = time.monotonic()  # the timing anchor
        self.events: list[tuple[float, str]] = []  # (monotonic, name)
        self.done_at: float | None = None      # monotonic
        self._tracker = tracker
        # flight-recorder trace snapshot: the op's span tree, captured
        # at completion so the history retains it after the live
        # SpanCollector ring rolls over
        self.trace_id: int | None = None
        self.trace_spans: list[dict] | None = None
        # tail-sampler verdict: did this op's trace ship to the mgr
        # trace store, and why (slo | error | reservoir | "")
        self.trace_kept: bool = False
        self.trace_reason: str = ""

    def set_trace(self, trace_id: int, spans: list[dict],
                  kept: bool = False, reason: str = "") -> None:
        self.trace_id = trace_id
        self.trace_spans = spans
        self.trace_kept = kept
        self.trace_reason = reason

    def mark_event(self, name: str) -> None:
        self.events.append((time.monotonic(), name))

    def mark_started(self) -> None:
        self.mark_event("started")

    def mark_commit_sent(self) -> None:
        self.mark_event("commit_sent")

    def mark_done(self) -> None:
        self.done_at = time.monotonic()
        self.mark_event("done")
        if self._tracker is not None:
            self._tracker.unregister_inflight_op(self)

    @property
    def duration(self) -> float:
        end = self.done_at if self.done_at is not None \
            else time.monotonic()
        return end - self.initiated_mono

    def _to_wall(self, mono_ts: float) -> float:
        return self.initiated_at + (mono_ts - self.initiated_mono)

    def dump(self) -> dict:
        doc = {
            "id": self.id,
            "description": self.description,
            "initiated_at": self.initiated_at,
            "age": time.monotonic() - self.initiated_mono,
            "duration": self.duration,
            "type_data": {
                "events": [{"time": self._to_wall(ts), "event": name}
                           for ts, name in self.events],
            },
        }
        if self.trace_spans is not None:
            doc["type_data"]["trace"] = {"trace_id": self.trace_id,
                                         "kept": self.trace_kept,
                                         "reason": self.trace_reason,
                                         "spans": self.trace_spans}
        return doc


class OpTracker:
    """In-flight registry + completed-op history (TrackedOp machinery).

    history_size / history_duration mirror osd_op_history_size (20) and
    osd_op_history_duration (600s); complaint_time mirrors
    osd_op_complaint_time (30s).
    """

    def __init__(self, history_size: int = 20,
                 history_duration: float = 600.0,
                 complaint_time: float = 30.0,
                 slow_size: int = 20):
        self.history_size = history_size
        self.history_duration = history_duration
        self.complaint_time = complaint_time
        self.slow_size = slow_size
        self._lock = threading.Lock()
        self._inflight: dict[int, OpRequest] = {}
        self._history: deque[OpRequest] = deque()
        # flight recorder: the N SLOWEST completed ops, kept sorted
        # slowest-first — a fast op burst cannot flush the one 3s
        # outlier the operator is hunting out of the recent ring
        self._slowest: list[OpRequest] = []

    def create_request(self, description: str) -> OpRequest:
        op = OpRequest(description, tracker=self)
        op.mark_event("initiated")
        with self._lock:
            self._inflight[op.id] = op
        return op

    def unregister_inflight_op(self, op: OpRequest) -> None:
        with self._lock:
            self._inflight.pop(op.id, None)
            self._history.append(op)
            if self.slow_size > 0:
                self._slowest.append(op)
                self._slowest.sort(key=lambda o: o.duration,
                                   reverse=True)
                del self._slowest[self.slow_size:]
            self._prune_locked()

    def _prune_locked(self) -> None:
        now = time.monotonic()
        while len(self._history) > self.history_size:
            self._history.popleft()
        while self._history and (self._history[0].done_at or now) \
                < now - self.history_duration:
            self._history.popleft()
        cutoff = now - self.history_duration
        self._slowest = [o for o in self._slowest
                         if (o.done_at or now) >= cutoff]

    # -- introspection (admin socket surface) ---------------------------

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            self._prune_locked()
            ops = [op.dump() for op in self._history]
            slowest = [op.dump() for op in self._slowest]
        return {"num_ops": len(ops), "ops": ops,
                "num_slowest": len(slowest), "slowest_ops": slowest}

    def dump_historic_ops_by_duration(self) -> dict:
        """Slowest-first view spanning BOTH flight-recorder rings: the
        slowest ring contributes outliers the recent ring already
        dropped; recent ops not (yet) in the slowest ring still rank."""
        with self._lock:
            self._prune_locked()
            seen: set[int] = set()
            merged = []
            for op in list(self._slowest) + list(self._history):
                if op.id not in seen:
                    seen.add(op.id)
                    merged.append(op.dump())
        merged.sort(key=lambda o: o["duration"], reverse=True)
        return {"num_ops": len(merged), "ops": merged}

    def get_slow_ops(self, now: float | None = None) -> list[dict]:
        """Ops in flight longer than the complaint time (the OSD's
        'slow request' warning source; now is monotonic)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return [op.dump() for op in self._inflight.values()
                    if now - op.initiated_mono > self.complaint_time]

    def slow_ops_count(self, now: float | None = None) -> int:
        """Cheap slow-request count (the MPGStats -> OSD_SLOW_OPS
        health feed: no dump dicts on the heartbeat path)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return sum(1 for op in self._inflight.values()
                       if now - op.initiated_mono > self.complaint_time)

    def register_admin_commands(self, asok) -> None:
        asok.register("dump_ops_in_flight",
                      lambda args: self.dump_ops_in_flight(),
                      "show ops currently in flight")
        asok.register("dump_historic_ops",
                      lambda args: self.dump_historic_ops(),
                      "show recently completed ops")
        asok.register("dump_historic_ops_by_duration",
                      lambda args: self.dump_historic_ops_by_duration(),
                      "show slowest recent ops first")
