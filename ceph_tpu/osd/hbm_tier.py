"""HBM-resident EC chunk tier: object data crosses the pipe ONCE.

The architectural answer to "why ship data to the TPU at all" when the
host<->device link is the bottleneck: once an object's chunks are in
HBM, every downstream consumer — parity encode, deep-scrub digests,
shard reconstruction — reads the RESIDENT copy.  The reference runs
each of those as a separate CPU pass over host memory
(ECBackend::continue_recovery_op src/osd/ECBackend.cc:531 re-reads
shards; PGBackend::be_deep_scrub re-reads and re-digests); here the
host pays one H2D per object lifetime and tiny D2H for results
(digests are 8 bytes/chunk; recovery returns only the rebuilt shard).

Wired into the OSD (osd_daemon.py, osd_hbm_tier_enable): the
TpuDispatcher's pipeline ADOPTS each encode's staged data + computed
parity device-side (adopt_encode — zero extra transfers), keyed by
(pg, object); ECBackend recovery reconstruction, scrub repair
rebuilds, and (opt-in) repeat client reads then hit the resident copy
instead of re-crossing PCIe. Entries carry their codec, so one
OSD-wide tier serves every EC pool the daemon hosts. Any mutation of
an object invalidates its entry; a PG interval change (new acting
set) drops the whole PG's entries — a stale resident copy must never
survive a primaryship hand-off.

Capacity is bounded (HBM is small): inserts evict LRU objects — an
evicted object simply pays H2D again on its next op, exactly like any
cache.  Residency/utilization rides the l_hbm_* counters (telemetry
report + the `hbm status` asok command).

Digest: a vectorized Fletcher-style pair (sum, index-weighted sum)
over the chunk bytes, both mod 2^32.  Scrub only ever compares
digests computed by THIS tier (or its numpy twin `host_digest`), so
the algorithm needs to be deterministic and position-sensitive, not
crc32c-compatible; position sensitivity is what catches the
swapped-block corruption a plain sum misses.
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["HbmChunkTier", "host_digest"]


def host_digest(chunks: np.ndarray) -> np.ndarray:
    """Numpy twin of the device digest: chunks [..., n] uint8 ->
    uint64 digest per chunk ((weighted_sum << 32) | sum)."""
    x = chunks.astype(np.uint64)
    n = x.shape[-1]
    w = (np.arange(n, dtype=np.uint64) % 0xFFFF) + 1
    s = x.sum(axis=-1) & 0xFFFFFFFF
    ws = (x * w).sum(axis=-1) & 0xFFFFFFFF
    return (ws << np.uint64(32)) | s


_device_digest = None


def _init_device_digest():
    """Module-level jitted digest: one compile per chunk shape no
    matter how many tier instances exist."""
    global _device_digest
    if _device_digest is not None:
        return
    import jax
    import jax.numpy as jnp

    @jax.jit
    def digest(chunks):
        x = chunks.astype(jnp.uint32)
        n = x.shape[-1]
        w = (jnp.arange(n, dtype=jnp.uint32) % 0xFFFF) + 1
        s = x.sum(axis=-1, dtype=jnp.uint32)
        ws = (x * w).sum(axis=-1, dtype=jnp.uint32)
        return s, ws
    from ..common.profiler import PROFILER
    _device_digest = PROFILER.wrap_jit("hbm_tier.digest", digest)


class _Batch:
    """One resident device array [B, k+m, n] shared by the B objects
    uploaded together.  Keeping BATCH granularity is what keeps the
    consumer dispatch count independent of object count: per-object
    device slices would turn a 48-object scrub into a 48-operand
    gather (dozens of transport round trips on a tunneled device);
    per-batch arrays make it one take per batch."""

    __slots__ = ("arr", "live", "codec", "obj_bytes", "digests")

    def __init__(self, arr, live: int, codec=None, obj_bytes: int = 0,
                 digests=None):
        self.arr = arr
        self.live = live
        self.codec = codec
        self.obj_bytes = obj_bytes
        # per-object per-shard crc32 (zlib poly) rows [B, k+m], computed
        # ON DEVICE by the fused write transform and adopted beside the
        # chunks: deep-scrub of a resident object verifies against these
        # without hashing a single byte on the host
        self.digests = digests


class HbmChunkTier:
    """Keyed store of device-resident chunk arrays [k+m, chunk] with
    fused device programs for the consumers.  `codec` is the default
    for put_encode; entries adopted from the dispatcher carry their
    own codec, so one tier serves heterogeneous pools."""

    def __init__(self, codec=None, capacity_objects: int = 64,
                 device=None):
        _init_device_digest()
        self.codec = codec
        self.capacity = capacity_objects
        # home device (parallel/placement.py): uploads commit here and
        # residency is accounted under a per-device ledger category, so
        # N tiers on N chips never fight over one global gauge
        self.device = device
        from ..parallel.placement import device_label
        self._mem_category = "hbm_tier" if device is None \
            else "hbm_tier[%s]" % device_label(device)
        self._lock = threading.Lock()
        self._objs: dict = {}          # name -> (_Batch, row index)
        self._order: list = []         # LRU, oldest first
        self._resident_bytes = 0
        # residency/utilization gauges (telemetry pipeline: the OSD
        # report's status bag + an optional ctx.perf registration)
        from ..common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("osd_hbm")
                     .add_u64("l_hbm_resident_objects",
                              "objects resident in HBM")
                     .add_u64("l_hbm_resident_bytes",
                              "HBM bytes held by resident chunks")
                     .add_u64_counter("l_hbm_hits",
                                      "consumer reads served resident")
                     .add_u64_counter("l_hbm_misses",
                                      "lookups that missed residency")
                     .add_u64_counter("l_hbm_evictions",
                                      "objects evicted over capacity")
                     .add_u64_counter("l_hbm_adopted",
                                      "encodes adopted device-side "
                                      "from the dispatcher pipeline")
                     .create_perf_counters())

    # -- residency -----------------------------------------------------

    def _touch(self, name) -> None:
        if name in self._order:
            self._order.remove(name)
        self._order.append(name)

    def _drop_locked(self, name) -> None:
        ent = self._objs.pop(name, None)
        if ent is not None:
            ent[0].live -= 1
            self._resident_bytes -= ent[0].obj_bytes
            # HBM frees at batch granularity: the array goes when its
            # LAST object is evicted (documented coarseness)
            if ent[0].live <= 0:
                ent[0].arr = None
        if name in self._order:
            self._order.remove(name)

    def _evict_over_capacity(self) -> None:
        while len(self._objs) > self.capacity and self._order:
            self._drop_locked(self._order[0])
            self.perf.inc("l_hbm_evictions")

    def _update_gauges_locked(self) -> None:
        self.perf.set("l_hbm_resident_objects", len(self._objs))
        self.perf.set("l_hbm_resident_bytes", self._resident_bytes)
        # device-memory ledger: tier residency is the dominant HBM
        # category, so every gauge refresh updates the profiler too
        from ..common.profiler import PROFILER
        PROFILER.mem_set(self._mem_category, self._resident_bytes)

    def _insert_locked(self, name, batch: _Batch, row: int) -> None:
        if name in self._objs:
            self._drop_locked(name)
        self._objs[name] = (batch, row)
        self._resident_bytes += batch.obj_bytes
        self._touch(name)
        self._evict_over_capacity()

    def put_encode(self, names: list, data_host: np.ndarray,
                   codec=None):
        """THE one H2D: upload a batch of objects' data chunks
        [batch, k, n], encode parity on device, and retain the full
        [batch, k+m, n] array resident.  Returns the device parity
        [batch, m, n] (callers usually leave it on device)."""
        import jax.numpy as jnp
        codec = codec if codec is not None else self.codec
        if self.device is not None:
            import jax
            data_dev = jax.device_put(data_host, self.device)
        else:
            data_dev = jnp.asarray(data_host)   # single transfer
        parity = codec.encode_batch(data_dev)
        full = jnp.concatenate([data_dev, parity], axis=1)
        obj_bytes = int(full.shape[1]) * int(full.shape[2])
        batch = _Batch(full, len(names), codec, obj_bytes)
        with self._lock:
            for i, name in enumerate(names):
                self._insert_locked(name, batch, i)
            self._update_gauges_locked()
        return parity

    def adopt_encode(self, name, data_rows, parity_rows, codec,
                     digests=None) -> None:
        """Adopt one object's ALREADY-STAGED encode from the dispatcher
        pipeline: data_rows [S, k, chunk] (the staged h2d input) and
        parity_rows [S, m, chunk] (the compute output) are device
        arrays, so residency costs zero extra transfers — this is how
        "the data crosses the pipe once" becomes true on the production
        write path rather than only in the bench harness.  Host arrays
        are accepted too (the no-jax dispatcher path): adoption is then
        itself the one h2d.

        Stored layout matches put_encode: [k+m, S*chunk] — shard i's
        whole chunk stream is row i.

        digests, when given, is the fused transform's device-computed
        per-shard crc32 list (k+m entries, zlib poly over each shard's
        stored stream) — retained beside the rows for scrub-from-digest
        (shard_digests)."""
        import jax.numpy as jnp
        if self.device is not None and not (
                type(data_rows).__module__.startswith("jax")):
            # host-array adoption (no-jax dispatcher path): the one h2d
            # goes straight to the home device
            import jax
            data_dev = jax.device_put(data_rows, self.device)
            parity_dev = jax.device_put(parity_rows, self.device)
        else:
            data_dev = jnp.asarray(data_rows)
            parity_dev = jnp.asarray(parity_rows)
        # [S, k+m, chunk] -> [k+m, S, chunk] -> [k+m, S*chunk]
        full = jnp.concatenate([data_dev, parity_dev], axis=1)
        full = jnp.transpose(full, (1, 0, 2)).reshape(
            full.shape[1], -1)
        obj_bytes = int(full.shape[0]) * int(full.shape[1])
        dig = None if digests is None else np.asarray(
            digests, dtype=np.uint32)[None]
        batch = _Batch(full[None], 1, codec, obj_bytes, dig)
        with self._lock:
            self._insert_locked(name, batch, 0)
            self._update_gauges_locked()
        self.perf.inc("l_hbm_adopted")

    def _gather(self, names: list):
        """Stack the named objects' chunk arrays [len, k+m, n] in name
        order — one take per underlying batch run, not per object."""
        import jax.numpy as jnp
        parts = []
        i = 0
        while i < len(names):
            batch, idx = self._objs[names[i]]
            rows = [idx]
            j = i + 1
            while j < len(names) and \
                    self._objs[names[j]][0] is batch:
                rows.append(self._objs[names[j]][1])
                j += 1
            parts.append(jnp.take(
                batch.arr, jnp.asarray(rows, dtype=jnp.int32), axis=0))
            i = j
        return parts[0] if len(parts) == 1 else \
            jnp.concatenate(parts, axis=0)

    def resident(self, name) -> bool:
        with self._lock:
            return name in self._objs

    def get(self, name):
        with self._lock:
            ent = self._objs.get(name)
            if ent is None:
                self.perf.inc("l_hbm_misses")
                return None
            self._touch(name)
            self.perf.inc("l_hbm_hits")
            return ent[0].arr[ent[1]]

    def codec_of(self, name):
        """The codec an entry was encoded with (None when absent)."""
        with self._lock:
            ent = self._objs.get(name)
            return None if ent is None else (ent[0].codec or self.codec)

    def shard_digests(self, name):
        """Device-computed per-shard crc32 row for a resident object
        (uint32[k+m], zlib poly over each shard's stored stream), or
        None when the entry was adopted without digests.  This is the
        scrub-from-digest surface: a deep scrub that finds one here
        verifies the object with ZERO host hashing."""
        with self._lock:
            ent = self._objs.get(name)
            if ent is None or ent[0].digests is None:
                return None
            self._touch(name)
            self.perf.inc("l_hbm_hits")
            return np.asarray(ent[0].digests[ent[1]])

    def drop(self, name) -> None:
        with self._lock:
            self._drop_locked(name)
            self._update_gauges_locked()

    def drop_prefix(self, prefix) -> int:
        """Invalidate every entry whose tuple key starts with `prefix`
        (the PG interval-change hook: a primaryship hand-off must drop
        the PG's residency — another primary may have written since).
        Returns the number of entries dropped."""
        with self._lock:
            victims = [name for name in self._objs
                       if isinstance(name, tuple) and name
                       and name[0] == prefix]
            for name in victims:
                self._drop_locked(name)
            if victims:
                self._update_gauges_locked()
        return len(victims)

    # -- consumers (all read the RESIDENT copy) ------------------------

    def _digests(self, stacked):
        return _device_digest(stacked)

    def deep_scrub(self, names: list, device_out: bool = False):
        """Per-chunk digests of every named resident object, computed
        on device in one fused call per chunk shape; only the digests
        (8 bytes/chunk) cross back.  Returns {name: uint64[k+m]} — or,
        with device_out, the raw device (s, ws) pair so callers
        batching several consumers can defer every host read to the
        end (finalize_digests turns the pair into the dict; device_out
        requires a homogeneous shape across names)."""
        with self._lock:
            by_shape: dict = {}
            for name in names:
                ent = self._objs[name]
                shape = tuple(ent[0].arr.shape[1:])
                by_shape.setdefault(shape, []).append(name)
            gathered = [(group, self._gather(group))
                        for group in by_shape.values()]
        if device_out:
            if len(gathered) != 1:
                raise ValueError("device_out needs one chunk shape, "
                                 "got %d" % len(gathered))
            return self._digests(gathered[0][1])
        out: dict = {}
        for group, stacked in gathered:
            s, ws = self._digests(stacked)
            out.update(self.finalize_digests(group, s, ws))
        return out

    @staticmethod
    def finalize_digests(names: list, s, ws) -> dict:
        s = np.asarray(s).astype(np.uint64)
        ws = np.asarray(ws).astype(np.uint64)
        dig = (ws << np.uint64(32)) | s
        return {name: dig[i] for i, name in enumerate(names)}

    def reconstruct(self, name, lost_shards: tuple):
        """Rebuild the lost shard(s) from the RESIDENT survivors —
        zero host reads of chunk data (ECBackend recovery's read
        phase priced out).  Returns the device array of rebuilt rows
        [len(lost), n]."""
        import jax.numpy as jnp
        with self._lock:
            ent = self._objs.get(name)
            if ent is None:
                self.perf.inc("l_hbm_misses")
                raise KeyError(name)
            self._touch(name)
            self.perf.inc("l_hbm_hits")
            obj = ent[0].arr[ent[1]]
            codec = ent[0].codec or self.codec
        nn = codec.get_chunk_count()
        avail = tuple(i for i in range(nn) if i not in lost_shards)
        k = codec.get_data_chunk_count()
        survivors = jnp.take(obj[None],
                             jnp.asarray(avail[:k], dtype=jnp.int32),
                             axis=1)
        # decode_batch maps k survivors -> all k+m rows; keep the lost
        all_rows = codec.decode_batch(avail[:k], survivors)
        return jnp.take(all_rows[0],
                        jnp.asarray(lost_shards, dtype=jnp.int32),
                        axis=0)

    def reconstruct_batch(self, names: list, lost_per_name: list):
        """One fused device program rebuilding one lost shard per
        named object — per-lane decode matrices over the RESIDENT
        survivors (the shape the OSD coalesces concurrent recovery
        ops into).  Requires one codec/shape across names.  Returns
        the device array [len(names), n]."""
        import jax.numpy as jnp

        from ..ops import xor_mm
        with self._lock:
            codec = self._objs[names[0]][0].codec or self.codec
            stacked = self._gather(names)
        nn = codec.get_chunk_count()
        k = codec.get_data_chunk_count()
        bitmats = []
        avail_idx = []
        lost_pos = []
        for lost in lost_per_name:
            avail = tuple(i for i in range(nn) if i != lost)[:k]
            entry = codec._decode_entry(avail)
            bitmats.append(entry["bitmat"])
            avail_idx.append(avail)
            lost_pos.append(lost)
        bitmats_dev = jnp.asarray(np.stack(bitmats))
        idx = jnp.asarray(np.asarray(avail_idx, dtype=np.int32))
        survivors = jnp.take_along_axis(stacked, idx[:, :, None],
                                        axis=1)
        out = xor_mm.matrix_encode_multi(bitmats_dev,
                                         survivors[:, None],
                                         codec.w)[:, 0]
        lp = jnp.asarray(np.asarray(lost_pos, dtype=np.int32))
        return jnp.take_along_axis(out, lp[:, None, None],
                                   axis=1)[:, 0]

    def stats(self) -> dict:
        from ..parallel.placement import device_label
        with self._lock:
            hits = self.perf.get("l_hbm_hits")
            misses = self.perf.get("l_hbm_misses")
            return {"device": device_label(self.device),
                    "resident_objects": len(self._objs),
                    "resident_bytes": self._resident_bytes,
                    "capacity": self.capacity,
                    "occupancy": round(len(self._objs) / self.capacity,
                                       4) if self.capacity else 0.0,
                    "hits": hits,
                    "misses": misses,
                    "hit_rate": round(hits / (hits + misses), 3)
                    if hits + misses else 0.0,
                    "adopted": self.perf.get("l_hbm_adopted"),
                    "digested": sum(
                        1 for ent in self._objs.values()
                        if ent[0].digests is not None),
                    "evictions": self.perf.get("l_hbm_evictions")}

    def occupancy(self) -> float:
        """Occupancy ratio for the DEVICE_MEM_NEARFULL feed (objects
        over capacity — the eviction trigger is object-count, so the
        pressure signal keys on the same axis)."""
        with self._lock:
            return len(self._objs) / self.capacity \
                if self.capacity else 0.0
