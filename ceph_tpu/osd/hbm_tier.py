"""HBM-resident EC chunk tier: object data crosses the pipe ONCE.

The architectural answer to "why ship data to the TPU at all" when the
host<->device link is the bottleneck: once an object's chunks are in
HBM, every downstream consumer — parity encode, deep-scrub digests,
shard reconstruction — reads the RESIDENT copy.  The reference runs
each of those as a separate CPU pass over host memory
(ECBackend::continue_recovery_op src/osd/ECBackend.cc:531 re-reads
shards; PGBackend::be_deep_scrub re-reads and re-digests); here the
host pays one H2D per object lifetime and tiny D2H for results
(digests are 8 bytes/chunk; recovery returns only the rebuilt shard).

Capacity is bounded (HBM is small): inserts evict LRU objects — an
evicted object simply pays H2D again on its next op, exactly like any
cache.

Digest: a vectorized Fletcher-style pair (sum, index-weighted sum)
over the chunk bytes, both mod 2^32.  Scrub only ever compares
digests computed by THIS tier (or its numpy twin `host_digest`), so
the algorithm needs to be deterministic and position-sensitive, not
crc32c-compatible; position sensitivity is what catches the
swapped-block corruption a plain sum misses.
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["HbmChunkTier", "host_digest"]


def host_digest(chunks: np.ndarray) -> np.ndarray:
    """Numpy twin of the device digest: chunks [..., n] uint8 ->
    uint64 digest per chunk ((weighted_sum << 32) | sum)."""
    x = chunks.astype(np.uint64)
    n = x.shape[-1]
    w = (np.arange(n, dtype=np.uint64) % 0xFFFF) + 1
    s = x.sum(axis=-1) & 0xFFFFFFFF
    ws = (x * w).sum(axis=-1) & 0xFFFFFFFF
    return (ws << np.uint64(32)) | s


_device_digest = None


def _init_device_digest():
    """Module-level jitted digest: one compile per chunk shape no
    matter how many tier instances exist."""
    global _device_digest
    if _device_digest is not None:
        return
    import jax
    import jax.numpy as jnp

    @jax.jit
    def digest(chunks):
        x = chunks.astype(jnp.uint32)
        n = x.shape[-1]
        w = (jnp.arange(n, dtype=jnp.uint32) % 0xFFFF) + 1
        s = x.sum(axis=-1, dtype=jnp.uint32)
        ws = (x * w).sum(axis=-1, dtype=jnp.uint32)
        return s, ws
    _device_digest = digest


class _Batch:
    """One resident device array [B, k+m, n] shared by the B objects
    uploaded together.  Keeping BATCH granularity is what keeps the
    consumer dispatch count independent of object count: per-object
    device slices would turn a 48-object scrub into a 48-operand
    gather (dozens of transport round trips on a tunneled device);
    per-batch arrays make it one take per batch."""

    __slots__ = ("arr", "live")

    def __init__(self, arr, live: int):
        self.arr = arr
        self.live = live


class HbmChunkTier:
    """Keyed store of device-resident chunk arrays [k+m, chunk] with
    fused device programs for the consumers."""

    def __init__(self, codec, capacity_objects: int = 64):
        _init_device_digest()
        self.codec = codec
        self.capacity = capacity_objects
        self._lock = threading.Lock()
        self._objs: dict = {}          # name -> (_Batch, row index)
        self._order: list = []         # LRU, oldest first
        self._obj_bytes = 0            # per-object [k+m, n] footprint
        # residency/utilization gauges (telemetry pipeline: the OSD
        # report's status bag + an optional ctx.perf registration)
        from ..common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("osd_hbm")
                     .add_u64("l_hbm_resident_objects",
                              "objects resident in HBM")
                     .add_u64("l_hbm_resident_bytes",
                              "HBM bytes held by resident chunks")
                     .add_u64_counter("l_hbm_hits",
                                      "consumer reads served resident")
                     .add_u64_counter("l_hbm_misses",
                                      "lookups that missed residency")
                     .add_u64_counter("l_hbm_evictions",
                                      "objects evicted over capacity")
                     .create_perf_counters())

    # -- residency -----------------------------------------------------

    def _touch(self, name) -> None:
        if name in self._order:
            self._order.remove(name)
        self._order.append(name)

    def _drop_locked(self, name) -> None:
        ent = self._objs.pop(name, None)
        if ent is not None:
            ent[0].live -= 1
            # HBM frees at batch granularity: the array goes when its
            # LAST object is evicted (documented coarseness)
            if ent[0].live <= 0:
                ent[0].arr = None
        if name in self._order:
            self._order.remove(name)

    def _evict_over_capacity(self) -> None:
        while len(self._objs) > self.capacity and self._order:
            self._drop_locked(self._order[0])
            self.perf.inc("l_hbm_evictions")

    def _update_gauges_locked(self) -> None:
        self.perf.set("l_hbm_resident_objects", len(self._objs))
        self.perf.set("l_hbm_resident_bytes",
                      len(self._objs) * self._obj_bytes)

    def put_encode(self, names: list, data_host: np.ndarray):
        """THE one H2D: upload a batch of objects' data chunks
        [batch, k, n], encode parity on device, and retain the full
        [batch, k+m, n] array resident.  Returns the device parity
        [batch, m, n] (callers usually leave it on device)."""
        import jax.numpy as jnp
        data_dev = jnp.asarray(data_host)       # single transfer
        parity = self.codec.encode_batch(data_dev)
        full = jnp.concatenate([data_dev, parity], axis=1)
        batch = _Batch(full, len(names))
        with self._lock:
            self._obj_bytes = int(full.shape[1]) * int(full.shape[2])
            for i, name in enumerate(names):
                if name in self._objs:
                    self._drop_locked(name)
                self._objs[name] = (batch, i)
                self._touch(name)
                self._evict_over_capacity()
            self._update_gauges_locked()
        return parity

    def _gather(self, names: list):
        """Stack the named objects' chunk arrays [len, k+m, n] in name
        order — one take per underlying batch run, not per object."""
        import jax.numpy as jnp
        parts = []
        i = 0
        while i < len(names):
            batch, idx = self._objs[names[i]]
            rows = [idx]
            j = i + 1
            while j < len(names) and \
                    self._objs[names[j]][0] is batch:
                rows.append(self._objs[names[j]][1])
                j += 1
            parts.append(jnp.take(
                batch.arr, jnp.asarray(rows, dtype=jnp.int32), axis=0))
            i = j
        return parts[0] if len(parts) == 1 else \
            jnp.concatenate(parts, axis=0)

    def resident(self, name) -> bool:
        with self._lock:
            return name in self._objs

    def get(self, name):
        with self._lock:
            ent = self._objs.get(name)
            if ent is None:
                self.perf.inc("l_hbm_misses")
                return None
            self._touch(name)
            self.perf.inc("l_hbm_hits")
            return ent[0].arr[ent[1]]

    def drop(self, name) -> None:
        with self._lock:
            self._drop_locked(name)
            self._update_gauges_locked()

    # -- consumers (all read the RESIDENT copy) ------------------------

    def _digests(self, stacked):
        return _device_digest(stacked)

    def deep_scrub(self, names: list, device_out: bool = False):
        """Per-chunk digests of every named resident object, computed
        on device in one fused call; only the digests (8 bytes/chunk)
        cross back.  Returns {name: uint64[k+m]} — or, with
        device_out, the raw device (s, ws) pair so callers batching
        several consumers can defer every host read to the end
        (finalize_digests turns the pair into the dict)."""
        with self._lock:
            stacked = self._gather(names)
        s, ws = self._digests(stacked)
        if device_out:
            return s, ws
        return self.finalize_digests(names, s, ws)

    @staticmethod
    def finalize_digests(names: list, s, ws) -> dict:
        s = np.asarray(s).astype(np.uint64)
        ws = np.asarray(ws).astype(np.uint64)
        dig = (ws << np.uint64(32)) | s
        return {name: dig[i] for i, name in enumerate(names)}

    def reconstruct(self, name, lost_shards: tuple):
        """Rebuild the lost shard(s) from the RESIDENT survivors —
        zero host reads of chunk data (ECBackend recovery's read
        phase priced out).  Returns the device array of rebuilt rows
        [len(lost), n]."""
        import jax.numpy as jnp
        obj = self.get(name)
        if obj is None:
            raise KeyError(name)
        nn = self.codec.get_chunk_count()
        avail = tuple(i for i in range(nn) if i not in lost_shards)
        k = self.codec.get_data_chunk_count()
        survivors = jnp.take(obj[None],
                             jnp.asarray(avail[:k], dtype=jnp.int32),
                             axis=1)
        # decode_batch maps k survivors -> all k+m rows; keep the lost
        all_rows = self.codec.decode_batch(avail[:k], survivors)
        return jnp.take(all_rows[0],
                        jnp.asarray(lost_shards, dtype=jnp.int32),
                        axis=0)

    def reconstruct_batch(self, names: list, lost_per_name: list):
        """One fused device program rebuilding one lost shard per
        named object — per-lane decode matrices over the RESIDENT
        survivors (the shape the OSD coalesces concurrent recovery
        ops into).  Returns the device array [len(names), n]."""
        import jax.numpy as jnp
        from ..ops import xor_mm
        nn = self.codec.get_chunk_count()
        k = self.codec.get_data_chunk_count()
        with self._lock:
            stacked = self._gather(names)
        bitmats = []
        avail_idx = []
        lost_pos = []
        for lost in lost_per_name:
            avail = tuple(i for i in range(nn) if i != lost)[:k]
            entry = self.codec._decode_entry(avail)
            bitmats.append(entry["bitmat"])
            avail_idx.append(avail)
            lost_pos.append(lost)
        bitmats_dev = jnp.asarray(np.stack(bitmats))
        idx = jnp.asarray(np.asarray(avail_idx, dtype=np.int32))
        survivors = jnp.take_along_axis(stacked, idx[:, :, None],
                                        axis=1)
        out = xor_mm.matrix_encode_multi(bitmats_dev,
                                         survivors[:, None],
                                         self.codec.w)[:, 0]
        lp = jnp.asarray(np.asarray(lost_pos, dtype=np.int32))
        return jnp.take_along_axis(out, lp[:, None, None],
                                   axis=1)[:, 0]

    def stats(self) -> dict:
        with self._lock:
            return {"resident_objects": len(self._objs),
                    "resident_bytes":
                        len(self._objs) * self._obj_bytes,
                    "capacity": self.capacity,
                    "hits": self.perf.get("l_hbm_hits"),
                    "misses": self.perf.get("l_hbm_misses"),
                    "evictions": self.perf.get("l_hbm_evictions")}
