"""Cross-op device-call coalescing for the OSD's EC hot path.

Role: the twin of the native bridge (native/src/tpu_bridge.cc) inside
the Python OSD. The reference's ECBackend enters the codec once per op
(src/osd/ECBackend.cc:1437 submit_transaction -> ECUtil::encode per
transaction); under concurrency each op would pay its own device
dispatch. Stripes are embarrassingly parallel, so concurrent ops that
share a generator (same pool/codec) or a decode matrix (same erasure
signature) CONCATENATE along the stripe axis and ride ONE device
program — N dispatches become ceil(N / max_batch), and on a remote
transport N round-trips collapse the same way.

The dispatcher presents a synchronous facade (submitters block until
their slice of the fused result lands), so the EC pipeline's ordering
guarantees are untouched — only the device traffic is batched.

Knobs ride the options schema: osd_tpu_coalesce (default on),
osd_tpu_coalesce_max_batch, osd_tpu_coalesce_max_delay_ms.
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = ["TpuDispatcher"]


class _Pending:
    __slots__ = ("batch", "event", "out", "error")

    def __init__(self, batch):
        self.batch = batch
        self.event = threading.Event()
        self.out = None
        self.error = None


class TpuDispatcher:
    """Coalesces same-key codec calls into single device dispatches.

    Key = (codec identity, kind, per-stripe shape): ops whose batches
    stack along axis 0 into one well-formed [S_total, k, chunk] call.
    """

    def __init__(self, max_batch: int = 8, max_delay: float = 0.002):
        self.max_batch = max_batch
        self.max_delay = max_delay
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.queues: dict = {}     # key -> (fn, [_Pending])
        self.stats = {"ops": 0, "dispatches": 0, "coalesced": 0}
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="tpu-dispatch", daemon=True)
        self._thread.start()

    # -- public API ----------------------------------------------------

    @staticmethod
    def _codec_key(codec):
        """Identity BY VALUE: every PG backend holds its own codec
        instance, so keying on id() would never coalesce across PGs.
        Codecs with the same generator bitmatrix (and layout params)
        compute the same function."""
        cached = getattr(codec, "_dispatch_key", None)
        if cached is not None:
            return cached
        bm = getattr(codec, "_bitmat", None)
        if bm is not None:
            # full digest, not hash(): a 64-bit hash collision between
            # two generators of the same shape would silently coalesce
            # different codecs into one dispatch and return wrong bytes
            import hashlib
            key = (type(codec).__name__, getattr(codec, "w", 0),
                   getattr(codec, "packetsize", 0), bm.shape,
                   hashlib.sha256(bm.tobytes()).digest())
        else:
            key = ("id", id(codec))
        try:
            codec._dispatch_key = key
        except AttributeError:
            pass
        return key

    def encode(self, codec, batch: np.ndarray) -> np.ndarray:
        """codec.encode_batch(batch), coalesced across submitters."""
        key = (self._codec_key(codec), "enc", batch.shape[1:],
               str(batch.dtype))
        return self._submit(key, codec.encode_batch, batch)

    def decode(self, codec, avail_rows: tuple,
               chunks: np.ndarray) -> np.ndarray:
        """codec.decode_batch for one erasure signature, coalesced with
        ops sharing the same signature (same decode matrix)."""
        avail_rows = tuple(avail_rows)
        key = (self._codec_key(codec), "dec", avail_rows,
               chunks.shape[1:], str(chunks.dtype))
        return self._submit(
            key, lambda stacked: codec.decode_batch(avail_rows, stacked),
            chunks)

    def shutdown(self) -> None:
        with self.cv:
            self._stop = True
            self.cv.notify_all()
        self._thread.join(timeout=5)

    # -- internals -----------------------------------------------------

    def _submit(self, key, fn, batch):
        p = _Pending(np.asarray(batch))
        with self.cv:
            q = self.queues.get(key)
            if q is None:
                q = self.queues[key] = (fn, [])
            q[1].append(p)
            self.stats["ops"] += 1
            self.cv.notify_all()
        if not p.event.wait(timeout=120):
            raise TimeoutError("tpu dispatcher wedged")
        if p.error is not None:
            raise p.error
        return p.out

    def _take_group(self):
        """Pick the fullest queue; wait up to max_delay for stragglers
        unless it is already at max_batch."""
        deadline = None
        while True:
            with self.cv:
                if self._stop:
                    return None
                best_key, best = None, None
                for key, (fn, pend) in self.queues.items():
                    if pend and (best is None or
                                 len(pend) > len(best[1])):
                        best_key, best = key, (fn, pend)
                if best is None:
                    deadline = None
                    self.cv.wait(0.5)
                    continue
                if len(best[1]) >= self.max_batch or (
                        deadline is not None
                        and time.monotonic() >= deadline):
                    fn, pend = best
                    take = pend[:self.max_batch]
                    del pend[:len(take)]
                    if not pend:
                        self.queues.pop(best_key, None)
                    deadline = None
                    return fn, take
                if deadline is None:
                    deadline = time.monotonic() + self.max_delay
                self.cv.wait(self.max_delay)

    def _run(self):
        while True:
            group = self._take_group()
            if group is None:
                return
            fn, pend = group
            self.stats["dispatches"] += 1
            if len(pend) > 1:
                self.stats["coalesced"] += len(pend)
            try:
                if len(pend) == 1:
                    out = np.asarray(fn(pend[0].batch))
                    pend[0].out = out
                else:
                    stacked = np.concatenate([p.batch for p in pend])
                    out = np.asarray(fn(stacked))
                    off = 0
                    for p in pend:
                        s = p.batch.shape[0]
                        p.out = out[off:off + s]
                        off += s
            except BaseException as e:   # deliver, don't kill the loop
                for p in pend:
                    p.error = e
            for p in pend:
                p.event.set()
