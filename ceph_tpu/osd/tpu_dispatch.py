"""Cross-op device-call coalescing for the OSD's EC hot path.

Role: the twin of the native bridge (native/src/tpu_bridge.cc) inside
the Python OSD. The reference's ECBackend enters the codec once per op
(src/osd/ECBackend.cc:1437 submit_transaction -> ECUtil::encode per
transaction); under concurrency each op would pay its own device
dispatch. Stripes are embarrassingly parallel, so concurrent ops that
share a generator (same pool/codec) or a decode matrix (same erasure
signature) CONCATENATE along the stripe axis and ride ONE device
program — N dispatches become ceil(N / max_batch), and on a remote
transport N round-trips collapse the same way.

The dispatcher presents a synchronous facade (submitters block until
their slice of the fused result lands), so the EC pipeline's ordering
guarantees are untouched — only the device traffic is batched.

Knobs ride the options schema: osd_tpu_coalesce (default on),
osd_tpu_coalesce_max_batch, osd_tpu_coalesce_max_delay_ms.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ..common.perf_counters import PerfCountersBuilder
from ..common.tracer import NULL_SPAN, device_segments

__all__ = ["TpuDispatcher"]


class _Pending:
    __slots__ = ("batch", "event", "out", "error", "trace", "t_submit")

    def __init__(self, batch, trace=NULL_SPAN):
        self.batch = batch
        self.event = threading.Event()
        self.out = None
        self.error = None
        self.trace = trace if trace is not None else NULL_SPAN
        self.t_submit = time.monotonic()


class TpuDispatcher:
    """Coalesces same-key codec calls into single device dispatches.

    Key = (codec identity, kind, per-stripe shape): ops whose batches
    stack along axis 0 into one well-formed [S_total, k, chunk] call.

    Observability: with a tracer whose collection is enabled, each
    submitter's span grows a queue-delay child plus a device span split
    into h2d / compute / d2h segments (measured once per fused dispatch
    and mirrored under every participating op — the ZTracer device-
    attribution role), and the l_tpu_* PerfCounters aggregate the same
    segments.  With tracing disabled the dispatch path is byte-for-byte
    the old one: no extra device syncs, no span allocation.
    """

    def __init__(self, max_batch: int = 8, max_delay: float = 0.002,
                 tracer=None):
        self.max_batch = max_batch
        self.max_delay = max_delay
        self.tracer = tracer
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.queues: dict = {}     # key -> (fn, [_Pending])
        self.stats = {"ops": 0, "dispatches": 0, "coalesced": 0}
        # per-codec throughput ledger: label -> {enc/dec bytes + a
        # bounded (t, bytes) window for the rolling-MB/s gauges the
        # telemetry report exports with codec labels}
        self.codec_stats: dict = {}
        self._telemetry_window = 10.0
        # l_tpu_* counters: device-segment attribution (exported via
        # the daemon's PerfCountersCollection -> mgr -> prometheus)
        self.perf = (PerfCountersBuilder("osd_tpu")
                     .add_time_avg("l_tpu_h2d",
                                   "host->device transfer time")
                     .add_time_avg("l_tpu_compute",
                                   "device compute (block_until_ready)")
                     .add_time_avg("l_tpu_d2h",
                                   "device->host transfer time")
                     .add_time_avg("l_tpu_dispatch_queue",
                                   "op wait in the coalescing queue")
                     .add_u64_counter("l_tpu_ops", "codec ops submitted")
                     .add_u64_counter("l_tpu_dispatches",
                                      "device programs dispatched")
                     .add_u64_counter("l_tpu_coalesced",
                                      "ops that shared a dispatch")
                     .add_u64("l_tpu_queue_depth",
                              "ops waiting in the coalescing queues")
                     .add_u64_counter("l_tpu_enc_bytes",
                                      "bytes through device encode")
                     .add_u64_counter("l_tpu_dec_bytes",
                                      "bytes through device decode")
                     .create_perf_counters())
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="tpu-dispatch", daemon=True)
        self._thread.start()

    # -- public API ----------------------------------------------------

    @staticmethod
    def _codec_key(codec):
        """Identity BY VALUE: every PG backend holds its own codec
        instance, so keying on id() would never coalesce across PGs.
        Codecs with the same generator bitmatrix (and layout params)
        compute the same function."""
        cached = getattr(codec, "_dispatch_key", None)
        if cached is not None:
            return cached
        bm = getattr(codec, "_bitmat", None)
        if bm is not None:
            # full digest, not hash(): a 64-bit hash collision between
            # two generators of the same shape would silently coalesce
            # different codecs into one dispatch and return wrong bytes
            import hashlib
            key = (type(codec).__name__, getattr(codec, "w", 0),
                   getattr(codec, "packetsize", 0), bm.shape,
                   hashlib.sha256(bm.tobytes()).digest())
        else:
            key = ("id", id(codec))
        try:
            codec._dispatch_key = key
        except AttributeError:
            pass
        return key

    @staticmethod
    def _codec_label(codec):
        """Stable human label for per-codec telemetry series
        (prometheus codec= label): class name + layout params."""
        cached = getattr(codec, "_dispatch_label", None)
        if cached is not None:
            return cached
        label = type(codec).__name__
        try:
            k = codec.get_data_chunk_count()
            m = codec.get_chunk_count() - k
            label = "%s_k%dm%d" % (label, k, m)
        except Exception:
            pass
        try:
            codec._dispatch_label = label
        except AttributeError:
            pass
        return label

    def _account_codec(self, codec, kind: str, nbytes: int) -> None:
        now = time.monotonic()
        with self.lock:
            row = self.codec_stats.setdefault(
                self._codec_label(codec),
                {"enc_bytes": 0, "dec_bytes": 0, "window": deque()})
            row[kind + "_bytes"] += nbytes
            w = row["window"]
            w.append((now, kind, nbytes))
            cutoff = now - self._telemetry_window
            while w and w[0][0] < cutoff:
                w.popleft()
        self.perf.inc("l_tpu_%s_bytes" % kind, nbytes)

    def encode(self, codec, batch: np.ndarray,
               trace=NULL_SPAN) -> np.ndarray:
        """codec.encode_batch(batch), coalesced across submitters."""
        key = (self._codec_key(codec), "enc", batch.shape[1:],
               str(batch.dtype))
        self._account_codec(codec, "enc",
                            getattr(batch, "nbytes", 0))
        return self._submit(key, codec.encode_batch, batch, trace)

    def decode(self, codec, avail_rows: tuple,
               chunks: np.ndarray, trace=NULL_SPAN) -> np.ndarray:
        """codec.decode_batch for one erasure signature, coalesced with
        ops sharing the same signature (same decode matrix)."""
        avail_rows = tuple(avail_rows)
        key = (self._codec_key(codec), "dec", avail_rows,
               chunks.shape[1:], str(chunks.dtype))
        self._account_codec(codec, "dec",
                            getattr(chunks, "nbytes", 0))
        return self._submit(
            key, lambda stacked: codec.decode_batch(avail_rows, stacked),
            chunks, trace)

    def telemetry(self) -> dict:
        """The device-utilization gauge bag the OSD ships in its mgr
        report: live queue depth, lifetime coalescing ratio, and
        rolling per-codec encode/decode MB/s (bytes through the
        dispatcher over the last telemetry window)."""
        now = time.monotonic()
        with self.lock:
            depth = sum(len(pend) for _, pend in self.queues.values())
            ops = self.stats["ops"]
            disp = self.stats["dispatches"]
            codecs = {}
            cutoff = now - self._telemetry_window
            for label, row in self.codec_stats.items():
                enc_b = dec_b = 0
                for t, kind, nb in row["window"]:
                    if t < cutoff:
                        continue
                    if kind == "enc":
                        enc_b += nb
                    else:
                        dec_b += nb
                codecs[label] = {
                    "enc_bytes": row["enc_bytes"],
                    "dec_bytes": row["dec_bytes"],
                    "enc_MBps": round(
                        enc_b / self._telemetry_window / 1e6, 3),
                    "dec_MBps": round(
                        dec_b / self._telemetry_window / 1e6, 3)}
        self.perf.set("l_tpu_queue_depth", depth)
        return {"queue_depth": depth,
                "ops": ops, "dispatches": disp,
                "coalesce_ratio": round(disp / ops, 3) if ops else 1.0,
                "codecs": codecs}

    def shutdown(self) -> None:
        with self.cv:
            self._stop = True
            self.cv.notify_all()
        self._thread.join(timeout=5)

    # -- internals -----------------------------------------------------

    def _submit(self, key, fn, batch, trace=NULL_SPAN):
        p = _Pending(np.asarray(batch), trace)
        with self.cv:
            q = self.queues.get(key)
            if q is None:
                q = self.queues[key] = (fn, [])
            q[1].append(p)
            self.stats["ops"] += 1
            depth = sum(len(pend) for _, pend in self.queues.values())
            self.cv.notify_all()
        self.perf.set("l_tpu_queue_depth", depth)
        if not p.event.wait(timeout=120):
            raise TimeoutError("tpu dispatcher wedged")
        if p.error is not None:
            raise p.error
        return p.out

    def _take_group(self):
        """Pick the fullest queue; wait up to max_delay for stragglers
        unless it is already at max_batch."""
        deadline = None
        while True:
            with self.cv:
                if self._stop:
                    return None
                best_key, best = None, None
                for key, (fn, pend) in self.queues.items():
                    if pend and (best is None or
                                 len(pend) > len(best[1])):
                        best_key, best = key, (fn, pend)
                if best is None:
                    deadline = None
                    self.cv.wait(0.5)
                    continue
                if len(best[1]) >= self.max_batch or (
                        deadline is not None
                        and time.monotonic() >= deadline):
                    fn, pend = best
                    take = pend[:self.max_batch]
                    del pend[:len(take)]
                    if not pend:
                        self.queues.pop(best_key, None)
                    deadline = None
                    return fn, take
                if deadline is None:
                    deadline = time.monotonic() + self.max_delay
                self.cv.wait(self.max_delay)

    def _instrumenting(self) -> bool:
        return self.tracer is not None and self.tracer.enabled

    def _run(self):
        while True:
            group = self._take_group()
            if group is None:
                return
            fn, pend = group
            self.stats["dispatches"] += 1
            self.perf.inc("l_tpu_dispatches")
            self.perf.inc("l_tpu_ops", len(pend))
            if len(pend) > 1:
                self.stats["coalesced"] += len(pend)
                self.perf.inc("l_tpu_coalesced", len(pend))
            instrument = self._instrumenting()
            t_start = time.monotonic()
            try:
                stacked = pend[0].batch if len(pend) == 1 \
                    else np.concatenate([p.batch for p in pend])
                if instrument:
                    # explicit h2d/compute/d2h segmentation (two extra
                    # device syncs — the disabled path never pays them)
                    out, seg = device_segments(fn, stacked)
                else:
                    out = np.asarray(fn(stacked))
                    seg = None
                if len(pend) == 1:
                    pend[0].out = out
                else:
                    off = 0
                    for p in pend:
                        s = p.batch.shape[0]
                        p.out = out[off:off + s]
                        off += s
                if seg is not None:
                    self._account(pend, seg, t_start)
            except BaseException as e:   # deliver, don't kill the loop
                for p in pend:
                    p.error = e
            for p in pend:
                p.event.set()

    def _account(self, pend, seg, t_start: float) -> None:
        """Fold one dispatch's measured segments into the l_tpu_*
        counters and back-fill queue/device spans under every
        participating op's trace (the segments are shared: a fused
        dispatch ran once for all of them)."""
        t_end = time.monotonic()
        self.perf.tinc("l_tpu_h2d", seg["h2d"])
        self.perf.tinc("l_tpu_compute", seg["compute"])
        self.perf.tinc("l_tpu_d2h", seg["d2h"])
        t1 = t_start + seg["h2d"]
        t2 = t1 + seg["compute"]
        for p in pend:
            self.perf.tinc("l_tpu_dispatch_queue",
                           max(0.0, t_start - p.t_submit))
            if not p.trace.valid():
                continue
            p.trace.child_interval("tpu_queue", p.t_submit, t_start)
            dev = p.trace.child_interval(
                "tpu_device", t_start, t_end,
                batch=int(sum(q.batch.shape[0] for q in pend)),
                coalesced=len(pend))
            dev.child_interval("h2d", t_start, t1)
            dev.child_interval("compute", t1, t2)
            dev.child_interval("d2h", t2, t2 + seg["d2h"])
