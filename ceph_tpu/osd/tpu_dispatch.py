"""Cross-op device-call coalescing + overlapped pipeline for the OSD's
EC hot path.

Role: the twin of the native bridge (native/src/tpu_bridge.cc) inside
the Python OSD. The reference's ECBackend enters the codec once per op
(src/osd/ECBackend.cc:1437 submit_transaction -> ECUtil::encode per
transaction); under concurrency each op would pay its own device
dispatch. Stripes are embarrassingly parallel, so concurrent ops that
share a generator (same pool/codec) or a decode matrix (same erasure
signature) CONCATENATE along the stripe axis and ride ONE device
program — N dispatches become ceil(N / max_batch), and on a remote
transport N round-trips collapse the same way.

The dispatcher is an overlapped depth-N pipeline (ROADMAP direction A:
the TPU historically spent >99% of streaming wall-clock waiting on the
host because every dispatch serialized h2d -> compute -> d2h):

    collector ──> [h2d stage] ──> [compute stage] ──> [d2h stage]
                 stage batch n+1    run batch n       drain batch n-1

Each stage runs on its own thread; the bounded queues between them ARE
the staging ring (at most `pipeline_depth` fused batches in flight per
stage). While batch n computes, batch n+1's host->device transfer is
already in progress and batch n-1's results are draining back — the
transfer wall hides behind compute, which is the whole point. Decode
dispatches additionally pre-stage their decode table (matrix inversion
+ bitmatrix upload) in the h2d stage, so a fresh erasure signature's
table cost overlaps the previous batch's compute instead of serializing
in front of its own.

The device input buffer staged by the h2d stage is dispatcher-private,
so for jax-backed codecs the compute stage donates it to the device
program (jax.jit donate_argnums) — HBM holds one buffer per stage
instead of two, and submitters' HOST arrays are never donated (no
use-after-donate is possible from the caller's side). Donation is
skipped when the dispatch adopts its results into the HbmChunkTier
(adoption needs the staged input alive after compute) and on backends
that cannot honor it.

Facades: submit_async()/encode_async()/decode_async() return futures;
encode()/decode() keep the original blocking surface, so the EC
pipeline's ordering guarantees are untouched — only the device traffic
is batched and overlapped. Errors propagate strictly per batch: a
failed stage fails ONLY that fused batch's submitters; batches behind
it keep flowing.

Knobs ride the options schema: osd_tpu_coalesce (default on),
osd_tpu_coalesce_max_batch, osd_tpu_coalesce_max_delay_ms,
osd_tpu_pipeline_depth (1 = the legacy synchronous loop).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque

import numpy as np

from ..common.perf_counters import PerfCountersBuilder
from ..common.profiler import PROFILER
from ..common.tracer import NULL_SPAN, device_segments

__all__ = ["TpuDispatcher"]

# pipeline stages in flow order; the three device stages carry the
# bound-stage verdict, the collector carries the starvation verdict
_STAGES = ("collector", "h2d", "compute", "d2h")
_STATES = ("busy", "idle", "blocked")


class _Pending:
    """One submitter's slot in a fused dispatch — and the future the
    async API hands back (result()/done()/exception())."""

    __slots__ = ("batch", "event", "out", "error", "trace", "t_submit",
                 "resident")

    def __init__(self, batch, trace=NULL_SPAN, resident=None):
        self.batch = batch
        self.event = threading.Event()
        self.out = None
        self.error = None
        self.trace = trace if trace is not None else NULL_SPAN
        self.t_submit = time.monotonic()
        self.resident = resident     # (tier, key, codec) adoption ask

    # -- future surface ------------------------------------------------

    def done(self) -> bool:
        return self.event.is_set()

    def exception(self):
        return self.error if self.event.is_set() else None

    def result(self, timeout: float = 120.0):
        if not self.event.wait(timeout=timeout):
            raise TimeoutError("tpu dispatcher wedged")
        if self.error is not None:
            raise self.error
        return self.out


class _Dispatch:
    """One fused device program moving through the pipeline stages."""

    __slots__ = ("key", "fn", "pend", "kind", "prefetch", "stacked",
                 "dev", "out_dev", "t_take", "seg", "mem_bytes")

    def __init__(self, key, fn, pend, kind, prefetch=None):
        self.key = key
        self.fn = fn
        self.pend = pend
        self.kind = kind             # "enc" | "dec" | other
        self.prefetch = prefetch     # () -> None decode-table staging
        self.stacked = None          # host ndarray (kept for fallback)
        self.dev = None              # staged device input
        self.out_dev = None          # device output
        self.t_take = time.monotonic()
        self.seg = {}                # stage -> (t_start, t_end)
        self.mem_bytes = 0           # staged bytes on the mem ledger


class _JaxDevOps:
    """Explicit h2d / compute / d2h legs on a jax device. Each leg
    blocks — in its OWN pipeline thread, which is what lets leg X of
    batch n overlap leg Y of batch m.

    `device` is the dispatcher's home device (parallel/placement.py):
    h2d commits the staged buffer there explicitly, so N dispatchers
    pinned to N chips stage and compute concurrently instead of
    funnelling through jax's implicit default device. None keeps the
    historical un-pinned behavior."""

    def __init__(self, device=None):
        self.device = device

    def h2d(self, host):
        import jax
        if self.device is None:
            return jax.block_until_ready(jax.device_put(host))
        return jax.block_until_ready(jax.device_put(host, self.device))

    def run(self, fn, dev):
        import jax
        return jax.block_until_ready(fn(dev))

    def d2h(self, out):
        if isinstance(out, dict):
            # fused-transform output dict: ONE device_get drains parity
            # + digests + compressed payload together (the fused path's
            # single d2h)
            import jax
            return jax.device_get(out)
        return np.asarray(out)


class _HostDevOps:
    """No-jax fallback: the stages degenerate to a plain call (the
    fake-device tests substitute their own instrumented ops here)."""

    def h2d(self, host):
        return host

    def run(self, fn, x):
        return fn(x)

    def d2h(self, out):
        if isinstance(out, dict):
            return {k: np.asarray(v) for k, v in out.items()}
        return np.asarray(out)


class _StageProf:
    """Per-stage wall-clock state machine: every instant a stage thread
    is in exactly one of busy (doing its leg's work) / idle (waiting on
    its upstream ring) / blocked (waiting to push downstream).  enter()
    folds the elapsed interval into the outgoing state's bucket;
    snapshot() is non-destructive and folds the in-progress interval
    in, so attribution is exact even mid-long-op."""

    __slots__ = ("lock", "acc", "state", "since")

    def __init__(self):
        self.lock = threading.Lock()
        self.acc = {s: 0.0 for s in _STATES}
        self.state = "idle"
        self.since = time.monotonic()

    def enter(self, state: str, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self.lock:
            self.acc[self.state] += max(0.0, now - self.since)
            self.state = state
            self.since = now

    def credit(self, state: str, seconds: float) -> None:
        """Direct accrual without a state switch (the depth-1 inline
        path, which runs every leg on the collector thread)."""
        with self.lock:
            self.acc[state] += max(0.0, seconds)

    def snapshot(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        with self.lock:
            acc = dict(self.acc)
            acc[self.state] += max(0.0, now - self.since)
        return acc

    def reset(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self.lock:
            for s in self.acc:
                self.acc[s] = 0.0
            self.since = now


class _RingQueue(queue.Queue):
    """Bounded stage ring with an occupancy time-integral: each mutation
    advances integral(qsize dt), so integral/wall is the ring's average
    occupancy over the profile window — the queue-theory complement to
    the stage state machine (a persistently full staging ring + an idle
    compute stage reads 'h2d-bound' before anyone eyeballs thread
    stacks).  _put/_get run under queue.Queue's own mutex."""

    def __init__(self, maxsize: int):
        super().__init__(maxsize)
        self._occ_integral = 0.0
        self._occ_t_last = time.monotonic()

    def _advance_locked(self, now: float) -> None:
        self._occ_integral += len(self.queue) \
            * max(0.0, now - self._occ_t_last)
        self._occ_t_last = now

    def _put(self, item) -> None:
        self._advance_locked(time.monotonic())
        super()._put(item)

    def _get(self):
        self._advance_locked(time.monotonic())
        return super()._get()

    def occupancy_integral(self) -> float:
        with self.mutex:
            self._advance_locked(time.monotonic())
            return self._occ_integral

    def occupancy_reset(self) -> None:
        with self.mutex:
            self._occ_integral = 0.0
            self._occ_t_last = time.monotonic()


class TpuDispatcher:
    """Coalesces same-key codec calls into single device dispatches and
    overlaps consecutive dispatches' h2d / compute / d2h legs.

    Key = (codec identity, kind, per-stripe shape): ops whose batches
    stack along axis 0 into one well-formed [S_total, k, chunk] call.

    Observability: with a tracer whose collection is enabled, each
    submitter's span grows a queue-delay child plus a device span split
    into h2d / compute / d2h segments. In pipelined mode the segments
    are the MEASURED stage intervals (monotonic stamps), so spans from
    consecutive dispatches visibly overlap — the regression evidence
    bench.py gates on. The l_tpu_* PerfCounters aggregate the same
    segments. With pipelining off and tracing off the dispatch path is
    byte-for-byte the historical one: no extra device syncs, no span
    allocation.
    """

    def __init__(self, max_batch: int = 8, max_delay: float = 0.002,
                 tracer=None, pipeline_depth: int = 2, device=None):
        self.max_batch = max_batch
        self.max_delay = max_delay
        self.tracer = tracer
        self.device = device        # home device (None = implicit default)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.queues: dict = {}     # key -> (fn, [_Pending])
        self.stats = {"ops": 0, "dispatches": 0, "coalesced": 0}
        # per-codec throughput ledger: label -> {enc/dec bytes + a
        # bounded (t, bytes) window for the rolling-MB/s gauges the
        # telemetry report exports with codec labels}
        self.codec_stats: dict = {}
        self._telemetry_window = 10.0
        # l_tpu_* counters: device-segment attribution (exported via
        # the daemon's PerfCountersCollection -> mgr -> prometheus)
        self.perf = (PerfCountersBuilder("osd_tpu")
                     .add_time_avg("l_tpu_h2d",
                                   "host->device transfer time")
                     .add_time_avg("l_tpu_compute",
                                   "device compute (block_until_ready)")
                     .add_time_avg("l_tpu_d2h",
                                   "device->host transfer time")
                     .add_time_avg("l_tpu_dispatch_queue",
                                   "op wait in the coalescing queue")
                     .add_u64_counter("l_tpu_ops", "codec ops submitted")
                     .add_u64_counter("l_tpu_dispatches",
                                      "device programs dispatched")
                     .add_u64_counter("l_tpu_coalesced",
                                      "ops that shared a dispatch")
                     .add_u64("l_tpu_queue_depth",
                              "ops waiting in the coalescing queues")
                     .add_u64_counter("l_tpu_enc_bytes",
                                      "bytes through device encode")
                     .add_u64_counter("l_tpu_dec_bytes",
                                      "bytes through device decode")
                     .add_u64_counter("l_tpu_donated",
                                      "dispatches whose staged input "
                                      "was donated to the program")
                     .add_u64_counter("l_tpu_fused_dispatches",
                                      "fused write-transform programs "
                                      "dispatched")
                     .add_u64_counter("l_tpu_fused_bytes_in",
                                      "raw bytes into the fused write "
                                      "transform")
                     .add_u64_counter("l_tpu_fused_bytes_out",
                                      "stored+parity bytes out of the "
                                      "fused transform")
                     .add_u64_counter("l_tpu_fused_compressed",
                                      "fused writes stored compressed")
                     .add_u64_counter("l_tpu_fused_probe_rejects",
                                      "fused writes whose entropy probe "
                                      "rejected compression")
                     .add_u64_avg("l_tpu_fused_ratio_milli",
                                  "stored/raw size ratio per fused "
                                  "write (x1000)"))
        # stall-attribution counters: cumulative per-stage wall time in
        # each state, synced from the _StageProf machines on telemetry
        # ticks so they ride MMgrReport -> mgr -> prometheus
        for stage in _STAGES:
            for state in _STATES:
                self.perf.add_time(
                    "l_tpu_stage_%s_%s" % (stage, state),
                    "%s stage wall seconds %s" % (stage, state))
        self.perf = self.perf.create_perf_counters()
        # rolling dispatch-wall EWMA (submit -> results landed): the
        # straggler-wait heuristic in _take_group scales its coalesce
        # window from THIS instead of always burning the full
        # max_delay, so the window tracks what a dispatch actually
        # costs on this device (ROADMAP direction J satellite)
        self._lat_ewma: float | None = None
        self._lat_alpha = 0.25
        # device leg implementations (tests substitute a fake here)
        self._jax = self._probe_jax()
        self._devops = _JaxDevOps(self.device) if self._jax \
            else _HostDevOps()
        self._donate_fns: dict = {}   # key -> jitted donating fn | False
        self._donate_ok = self._probe_donation()
        # fused write-transform ledger (dispatch_status "fused" section)
        self._fused_seq = 0
        self.fused_stats = {"dispatches": 0, "bytes_in": 0,
                            "bytes_out": 0, "compressed": 0,
                            "probe_rejects": 0, "ratio_milli_sum": 0}
        # stall attribution: one state machine per pipeline stage plus
        # the profile window anchor (profile_reset() restarts both)
        self._stage_prof = {s: _StageProf() for s in _STAGES}
        self._profile_t0 = time.monotonic()
        self._stop = False
        self._threads: list = []
        if self.pipeline_depth > 1:
            # the staging ring: bounded hand-off queues between stages.
            # depth bounds how many fused batches are in flight per
            # stage; the collector blocks when the ring is full.
            self._q_h2d: queue.Queue = _RingQueue(self.pipeline_depth)
            self._q_compute: queue.Queue = _RingQueue(
                self.pipeline_depth)
            self._q_d2h: queue.Queue = _RingQueue(self.pipeline_depth)
            for name, fn in (("tpu-h2d", self._h2d_loop),
                             ("tpu-compute", self._compute_loop),
                             ("tpu-d2h", self._d2h_loop)):
                t = threading.Thread(target=fn, name=name, daemon=True)
                t.start()
                self._threads.append(t)
        self._thread = threading.Thread(
            target=self._run, name="tpu-dispatch", daemon=True)
        self._thread.start()
        self._threads.append(self._thread)

    @staticmethod
    def _probe_jax() -> bool:
        try:
            import jax  # noqa: F401
            return True
        except Exception:
            return False

    def _probe_donation(self) -> bool:
        """Donation is only honored on real accelerators; the CPU
        backend ignores it (with a warning per compile), so don't ask.
        The probe checks the PINNED device's platform — a mixed host
        could pin one OSD to an accelerator and another to cpu."""
        if not self._jax:
            return False
        try:
            import jax
            dev = self.device if self.device is not None \
                else jax.devices()[0]
            return dev.platform not in ("cpu",)
        except Exception:
            return False

    # -- public API ----------------------------------------------------

    @staticmethod
    def _codec_key(codec):
        """Identity BY VALUE: every PG backend holds its own codec
        instance, so keying on id() would never coalesce across PGs.
        Codecs with the same generator bitmatrix (and layout params)
        compute the same function."""
        cached = getattr(codec, "_dispatch_key", None)
        if cached is not None:
            return cached
        bm = getattr(codec, "_bitmat", None)
        if bm is not None:
            # full digest, not hash(): a 64-bit hash collision between
            # two generators of the same shape would silently coalesce
            # different codecs into one dispatch and return wrong bytes
            import hashlib
            key = (type(codec).__name__, getattr(codec, "w", 0),
                   getattr(codec, "packetsize", 0), bm.shape,
                   hashlib.sha256(bm.tobytes()).digest())
        else:
            key = ("id", id(codec))
        try:
            codec._dispatch_key = key
        except AttributeError:
            pass
        return key

    @staticmethod
    def _codec_label(codec):
        """Stable human label for per-codec telemetry series
        (prometheus codec= label): class name + layout params."""
        cached = getattr(codec, "_dispatch_label", None)
        if cached is not None:
            return cached
        label = type(codec).__name__
        try:
            k = codec.get_data_chunk_count()
            m = codec.get_chunk_count() - k
            label = "%s_k%dm%d" % (label, k, m)
        except Exception:
            pass
        try:
            codec._dispatch_label = label
        except AttributeError:
            pass
        return label

    def _account_codec(self, codec, kind: str, nbytes: int) -> None:
        now = time.monotonic()
        with self.lock:
            row = self.codec_stats.setdefault(
                self._codec_label(codec),
                {"enc_bytes": 0, "dec_bytes": 0, "window": deque()})
            row[kind + "_bytes"] += nbytes
            w = row["window"]
            w.append((now, kind, nbytes))
            cutoff = now - self._telemetry_window
            while w and w[0][0] < cutoff:
                w.popleft()
        self.perf.inc("l_tpu_%s_bytes" % kind, nbytes)

    def encode_async(self, codec, batch: np.ndarray, trace=NULL_SPAN,
                     resident=None) -> _Pending:
        """Async codec.encode_batch(batch): returns a future whose
        result() is the parity array. resident=(tier, key) asks the
        pipeline to adopt the staged data + computed parity into the
        HbmChunkTier device-side (zero extra transfers)."""
        key = (self._codec_key(codec), "enc", batch.shape[1:],
               str(batch.dtype))
        self._account_codec(codec, "enc",
                            getattr(batch, "nbytes", 0))
        if resident is not None:
            resident = (resident[0], resident[1], codec)
        return self._submit_async(key, codec.encode_batch, batch, trace,
                                  kind="enc", resident=resident)

    def decode_async(self, codec, avail_rows: tuple,
                     chunks: np.ndarray, trace=NULL_SPAN) -> _Pending:
        """Async codec.decode_batch for one erasure signature; the
        decode table (inversion + device upload) is pre-staged in the
        pipeline's h2d stage so a fresh signature's table cost overlaps
        the previous dispatch's compute."""
        avail_rows = tuple(avail_rows)
        key = (self._codec_key(codec), "dec", avail_rows,
               chunks.shape[1:], str(chunks.dtype))
        self._account_codec(codec, "dec",
                            getattr(chunks, "nbytes", 0))
        prefetch = None
        entry_fn = getattr(codec, "_decode_entry", None)
        if entry_fn is not None:
            def prefetch(avail=avail_rows, entry_fn=entry_fn):
                entry = entry_fn(avail)
                if self._jax and isinstance(entry, dict) \
                        and "bitmat" in entry:
                    # the device copy is keyed per HOME device: a
                    # second pinned dispatcher must stage its own copy,
                    # not consume (or clobber) the first device's
                    from ..models.table_cache import device_entry_key
                    devkey = device_entry_key(self.device)
                    if devkey not in entry:
                        import jax
                        import jax.numpy as jnp
                        bm = jnp.asarray(entry["bitmat"])
                        if self.device is not None:
                            bm = jax.device_put(bm, self.device)
                        entry.setdefault(devkey, bm)
        return self._submit_async(
            key, lambda stacked: codec.decode_batch(avail_rows, stacked),
            chunks, trace, kind="dec", prefetch=prefetch)

    def _stage_entry(self, entry: dict) -> None:
        """Stage a TableCache entry's bitmatrix onto this dispatcher's
        home device (same per-device keying as the decode prefetch)."""
        if not (self._jax and isinstance(entry, dict)
                and "bitmat" in entry):
            return
        from ..models.table_cache import device_entry_key
        devkey = device_entry_key(self.device)
        if devkey not in entry:
            import jax
            import jax.numpy as jnp
            bm = jnp.asarray(entry["bitmat"])
            if self.device is not None:
                bm = jax.device_put(bm, self.device)
            entry.setdefault(devkey, bm)

    def repair_fraction_async(self, codec, target: int,
                              chunks: np.ndarray,
                              trace=NULL_SPAN) -> _Pending:
        """Async codec.repair_fraction_batch: the helper-side beta
        projection of [B, chunk] survivor streams into [B, chunk/alpha]
        repair fractions for rebuilding `target`. The [1, alpha]
        projection matrix is pre-staged like a decode table; repair
        work accounts as decode-direction codec traffic."""
        key = (self._codec_key(codec), "rfrac", target,
               chunks.shape[1:], str(chunks.dtype))
        self._account_codec(codec, "dec",
                            getattr(chunks, "nbytes", 0))
        prefetch = None
        entry_fn = getattr(codec, "_fraction_entry", None)
        if entry_fn is not None:
            def prefetch(target=target, entry_fn=entry_fn):
                self._stage_entry(entry_fn(target))
        return self._submit_async(
            key,
            lambda stacked: codec.repair_fraction_batch(target, stacked),
            chunks, trace, kind="dec", prefetch=prefetch)

    def repair_combine_async(self, codec, target: int, helpers: tuple,
                             fractions: np.ndarray,
                             trace=NULL_SPAN) -> _Pending:
        """Async codec.repair_combine_batch: [B, d, sub] stacked helper
        fractions (rows in `helpers` order) -> rebuilt [B, chunk]
        target chunks, with the per-(target, helper-set) combine matrix
        pre-staged in the h2d stage."""
        helpers = tuple(helpers)
        key = (self._codec_key(codec), "rcomb", target, helpers,
               fractions.shape[1:], str(fractions.dtype))
        self._account_codec(codec, "dec",
                            getattr(fractions, "nbytes", 0))
        prefetch = None
        entry_fn = getattr(codec, "_combine_entry", None)
        if entry_fn is not None:
            def prefetch(target=target, helpers=helpers,
                         entry_fn=entry_fn):
                self._stage_entry(entry_fn(target, helpers))
        return self._submit_async(
            key,
            lambda stacked: codec.repair_combine_batch(
                target, helpers, stacked),
            fractions, trace, kind="dec", prefetch=prefetch)

    def repair_fraction(self, codec, target: int, chunks: np.ndarray,
                        trace=NULL_SPAN) -> np.ndarray:
        """Blocking facade over repair_fraction_async."""
        return self.repair_fraction_async(codec, target, chunks,
                                          trace).result()

    def repair_combine(self, codec, target: int, helpers: tuple,
                       fractions: np.ndarray,
                       trace=NULL_SPAN) -> np.ndarray:
        """Blocking facade over repair_combine_async."""
        return self.repair_combine_async(codec, target, helpers,
                                         fractions, trace).result()

    def encode(self, codec, batch: np.ndarray, trace=NULL_SPAN,
               resident=None) -> np.ndarray:
        """codec.encode_batch(batch), coalesced across submitters —
        the blocking facade over encode_async (EC pipeline ordering
        untouched)."""
        return self.encode_async(codec, batch, trace,
                                 resident=resident).result()

    def decode(self, codec, avail_rows: tuple,
               chunks: np.ndarray, trace=NULL_SPAN) -> np.ndarray:
        """codec.decode_batch for one erasure signature, coalesced with
        ops sharing the same signature (same decode matrix)."""
        return self.decode_async(codec, avail_rows, chunks,
                                 trace).result()

    def fused_supported(self, codec) -> bool:
        """Whether whole-object writes through this codec can ride the
        fused write transform (jax backend + matrix codec)."""
        from . import fused_transform
        return self._jax and fused_transform.fused_supported(codec)

    def fused_write_async(self, codec, batch: np.ndarray,
                          mode: str = "store",
                          required_ratio: float = 0.875,
                          entropy_max_bits: float = 7.0,
                          trace=NULL_SPAN, resident=None) -> _Pending:
        """Async fused write transform over one whole-object batch:
        digests + compressibility decision + EC encode in ONE device
        program (one h2d, one program, one d2h).

        Fused dispatches never coalesce across submitters — the
        compression decision and the per-shard crc chains are
        per OBJECT — but consecutive fused writes still overlap
        through the h2d/compute/d2h pipeline stages. The future's
        result() is the fused host output dict (the caller builds a
        FusedResult via fused_transform.result_from_host)."""
        from . import fused_transform
        batch = np.asarray(batch)
        self._account_codec(codec, "enc", getattr(batch, "nbytes", 0))
        donate = self._donate_ok and (mode == "compress"
                                      or resident is None)

        def fn(dev, _codec=codec, _mode=mode, _rr=required_ratio,
               _em=entropy_max_bits, _donate=donate):
            return fused_transform.run_fused(
                _codec, dev, mode=_mode, required_ratio=_rr,
                entropy_max_bits=_em, device=self.device,
                data_dev=dev if not isinstance(dev, np.ndarray)
                else None, donate=_donate)

        with self.lock:
            self._fused_seq += 1
            seq = self._fused_seq
        key = (self._codec_key(codec), "fused", mode, seq)
        if resident is not None:
            resident = (resident[0], resident[1], codec)
        return self._submit_async(key, fn, batch, trace, kind="fused",
                                  resident=resident)

    def fused_write(self, codec, batch: np.ndarray, mode: str = "store",
                    required_ratio: float = 0.875,
                    entropy_max_bits: float = 7.0,
                    trace=NULL_SPAN, resident=None):
        """Blocking facade over fused_write_async -> FusedResult."""
        from . import fused_transform
        batch = np.asarray(batch)
        S, k, chunk = batch.shape
        host = self.fused_write_async(
            codec, batch, mode=mode, required_ratio=required_ratio,
            entropy_max_bits=entropy_max_bits, trace=trace,
            resident=resident).result()
        return fused_transform.result_from_host(host, S, k, chunk, mode)

    def telemetry(self) -> dict:
        """The device-utilization gauge bag the OSD ships in its mgr
        report: live queue depth, lifetime coalescing ratio, and
        rolling per-codec encode/decode MB/s (bytes through the
        dispatcher over the last telemetry window)."""
        now = time.monotonic()
        with self.lock:
            depth = sum(len(e[1]) for e in self.queues.values())
            ops = self.stats["ops"]
            disp = self.stats["dispatches"]
            codecs = {}
            cutoff = now - self._telemetry_window
            for label, row in self.codec_stats.items():
                enc_b = dec_b = 0
                for t, kind, nb in row["window"]:
                    if t < cutoff:
                        continue
                    if kind == "enc":
                        enc_b += nb
                    else:
                        dec_b += nb
                codecs[label] = {
                    "enc_bytes": row["enc_bytes"],
                    "dec_bytes": row["dec_bytes"],
                    "enc_MBps": round(
                        enc_b / self._telemetry_window / 1e6, 3),
                    "dec_MBps": round(
                        dec_b / self._telemetry_window / 1e6, 3)}
        self.perf.set("l_tpu_queue_depth", depth)
        from ..parallel.placement import device_label
        return {"queue_depth": depth,
                "device": device_label(self.device),
                "ops": ops, "dispatches": disp,
                "coalesce_ratio": round(disp / ops, 3) if ops else 1.0,
                "fused": self._fused_summary(),
                "codecs": codecs}

    def _fused_summary(self) -> dict:
        """The fused-write ledger: dispatch count, bytes through the
        fused program, compress decisions and the mean stored/raw
        ratio. Rides telemetry() (mgr report) and `dispatch status`."""
        with self.lock:
            st = dict(self.fused_stats)
        ratio_sum = st.pop("ratio_milli_sum")
        n = st["dispatches"]
        st["ratio_avg"] = round(ratio_sum / n / 1000.0, 4) if n else 1.0
        return st

    def dispatch_status(self) -> dict:
        """The `dispatch status` asok payload: pipeline shape, ring
        occupancy per stage, and the coalescing ledger."""
        ring = {"staging": 0, "computing": 0, "draining": 0}
        if self.pipeline_depth > 1:
            ring = {"staging": self._q_h2d.qsize(),
                    "computing": self._q_compute.qsize(),
                    "draining": self._q_d2h.qsize()}
        tel = self.telemetry()
        return {"pipeline_depth": self.pipeline_depth,
                "overlapped": self.pipeline_depth > 1,
                "device": tel["device"],
                "ring": ring,
                "queue_depth": tel["queue_depth"],
                "ops": tel["ops"],
                "dispatches": tel["dispatches"],
                "coalesce_ratio": tel["coalesce_ratio"],
                "lat_ewma_ms": round(self._lat_ewma * 1e3, 3)
                if self._lat_ewma is not None else None,
                "coalesce_wait_ms": round(
                    self._coalesce_wait() * 1e3, 3),
                "donated_dispatches": self.perf.get("l_tpu_donated"),
                "fused": tel["fused"],
                "segments_s": {
                    "h2d_avg": self.perf.avg("l_tpu_h2d"),
                    "compute_avg": self.perf.avg("l_tpu_compute"),
                    "d2h_avg": self.perf.avg("l_tpu_d2h"),
                    "queue_avg": self.perf.avg("l_tpu_dispatch_queue")},
                "profile": self.dispatch_profile()}

    def dispatch_profile(self) -> dict:
        """Stall attribution over the current profile window: per-stage
        busy/idle/blocked wall seconds and fractions, ring occupancy
        time-averages, and a one-line verdict.

        The verdict logic: the device stage with the highest busy
        fraction is the wall ("h2d-bound 71%") — unless no stage is
        busy even half the window AND the collector out-idles it, in
        which case the device isn't the problem, the feed is
        ("collector-starved 88%": submitters aren't producing work)."""
        now = time.monotonic()
        wall = max(1e-9, now - self._profile_t0)
        stages = {}
        for name, prof in self._stage_prof.items():
            acc = prof.snapshot(now)
            row = {}
            for state in _STATES:
                row[state + "_s"] = round(acc[state], 6)
                row[state + "_frac"] = round(
                    min(1.0, acc[state] / wall), 4)
            stages[name] = row
            # cumulative counters ride MMgrReport with the next tick
            for state in _STATES:
                self.perf.set("l_tpu_stage_%s_%s" % (name, state),
                              acc[state])
        occupancy = {"staging": 0.0, "computing": 0.0, "draining": 0.0}
        if self.pipeline_depth > 1:
            occupancy = {
                "staging": round(
                    self._q_h2d.occupancy_integral() / wall, 4),
                "computing": round(
                    self._q_compute.occupancy_integral() / wall, 4),
                "draining": round(
                    self._q_d2h.occupancy_integral() / wall, 4)}
        device = ("h2d", "compute", "d2h")
        bound = max(device, key=lambda s: stages[s]["busy_frac"])
        attribution = stages[bound]["busy_frac"]
        collector_idle = stages["collector"]["idle_frac"]
        if attribution < 0.5 and collector_idle > attribution:
            bound = "collector"
            attribution = collector_idle
            verdict = "collector-starved %d%%" \
                % round(collector_idle * 100)
        else:
            verdict = "%s-bound %d%%" % (bound,
                                         round(attribution * 100))
        return {"window_s": round(wall, 6),
                "verdict": verdict,
                "bound": bound,
                "attribution": attribution,
                "stages": stages,
                "queue_occupancy_avg": occupancy}

    def profile_reset(self) -> None:
        """Restart the attribution window (asok `profile reset`)."""
        now = time.monotonic()
        for prof in self._stage_prof.values():
            prof.reset(now)
        self._profile_t0 = now
        if self.pipeline_depth > 1:
            self._q_h2d.occupancy_reset()
            self._q_compute.occupancy_reset()
            self._q_d2h.occupancy_reset()

    def shutdown(self) -> None:
        with self.cv:
            self._stop = True
            self.cv.notify_all()
        if self.pipeline_depth > 1:
            # sentinels flush the stage threads in order
            self._q_h2d.put(None)
        for t in self._threads:
            t.join(timeout=5)

    # -- internals -----------------------------------------------------

    def _submit_async(self, key, fn, batch, trace=NULL_SPAN,
                      kind: str = "enc", prefetch=None,
                      resident=None) -> _Pending:
        p = _Pending(np.asarray(batch), trace, resident=resident)
        with self.cv:
            q = self.queues.get(key)
            if q is None:
                q = self.queues[key] = (fn, [], kind, prefetch)
            q[1].append(p)
            self.stats["ops"] += 1
            depth = sum(len(e[1]) for e in self.queues.values())
            self.cv.notify_all()
        self.perf.set("l_tpu_queue_depth", depth)
        return p

    def _note_dispatch_wall(self, wall: float) -> None:
        """Fold one dispatch's submit->results wall into the latency
        EWMA the coalesce window scales from."""
        if wall <= 0:
            return
        # single-writer per stage thread; a torn read in the window
        # heuristic only mis-sizes one wait, so no lock (and the
        # collector calls _coalesce_wait while HOLDING self.cv's lock)
        prev = self._lat_ewma
        self._lat_ewma = wall if prev is None \
            else (1.0 - self._lat_alpha) * prev \
            + self._lat_alpha * wall

    def _coalesce_wait(self) -> float:
        """Adaptive straggler wait: half the rolling dispatch-wall
        EWMA, floored at max_delay/8 and CAPPED at max_delay — a fast
        device stops burning the full fixed window on every dispatch,
        and a known-slow device can never stretch the window beyond
        the configured max (the pre-EWMA failure mode: one wedged
        h2d inflating every subsequent coalesce wait)."""
        ewma = self._lat_ewma
        if ewma is None:
            return self.max_delay
        return min(self.max_delay,
                   max(self.max_delay / 8.0, 0.5 * ewma))

    def _take_group(self):
        """Pick the fullest queue; wait up to the EWMA-scaled coalesce
        window for stragglers unless it is already at max_batch."""
        deadline = None
        while True:
            with self.cv:
                if self._stop:
                    return None
                best_key, best = None, None
                for key, entry in self.queues.items():
                    pend = entry[1]
                    if pend and (best is None or
                                 len(pend) > len(best[1])):
                        best_key, best = key, entry
                if best is None:
                    deadline = None
                    self.cv.wait(0.5)
                    continue
                if len(best[1]) >= self.max_batch or (
                        deadline is not None
                        and time.monotonic() >= deadline):
                    fn, pend, kind, prefetch = best
                    take = pend[:self.max_batch]
                    del pend[:len(take)]
                    if not pend:
                        self.queues.pop(best_key, None)
                    deadline = None
                    return _Dispatch(best_key, fn, take, kind, prefetch)
                wait = self._coalesce_wait()
                if deadline is None:
                    deadline = time.monotonic() + wait
                self.cv.wait(wait)

    def _instrumenting(self) -> bool:
        return self.tracer is not None and self.tracer.enabled

    def _run(self):
        """Collector: group submitters into fused dispatches and feed
        the pipeline (or, depth 1, run the legacy synchronous loop)."""
        prof = self._stage_prof["collector"]
        while True:
            # idle = waiting for submitters (or stragglers): a starved
            # collector is the "upstream can't feed the device" verdict
            prof.enter("idle")
            d = self._take_group()
            if d is None:
                return
            prof.enter("busy")
            self.stats["dispatches"] += 1
            self.perf.inc("l_tpu_dispatches")
            self.perf.inc("l_tpu_ops", len(d.pend))
            if len(d.pend) > 1:
                self.stats["coalesced"] += len(d.pend)
                self.perf.inc("l_tpu_coalesced", len(d.pend))
            if self.pipeline_depth > 1:
                # blocks when the staging ring is full: that back-
                # pressure IS the depth-N bound
                prof.enter("blocked")
                self._q_h2d.put(d)
            else:
                self._dispatch_inline(d)

    # -- legacy (depth-1) synchronous path ------------------------------

    def _dispatch_inline(self, d: _Dispatch) -> None:
        instrument = self._instrumenting()
        t_start = time.monotonic()
        try:
            stacked = d.pend[0].batch if len(d.pend) == 1 \
                else np.concatenate([p.batch for p in d.pend])
            if instrument:
                # explicit h2d/compute/d2h segmentation (two extra
                # device syncs — the disabled path never pays them)
                out, seg = device_segments(d.fn, stacked)
            else:
                out = d.fn(stacked)
                # fused programs return an output dict: drain it in one
                # transfer instead of np-coercing it
                out = self._devops.d2h(out) if isinstance(out, dict) \
                    else np.asarray(out)
                seg = None
            self._slice_results(d, out)
            self._adopt_residents(d, stacked, out)
            if d.kind == "fused":
                self._account_fused(d)
            if seg is not None:
                t1 = t_start + seg["h2d"]
                t2 = t1 + seg["compute"]
                d.seg = {"h2d": (t_start, t1), "compute": (t1, t2),
                         "d2h": (t2, t2 + seg["d2h"])}
                self._account(d)
                # depth-1 runs every leg on the collector thread; the
                # per-stage machines never switch state, so credit the
                # measured segments directly (attribution still works
                # on the legacy synchronous path when instrumented)
                for stage in ("h2d", "compute", "d2h"):
                    a, b = d.seg[stage]
                    self._stage_prof[stage].credit("busy", b - a)
        except BaseException as e:   # deliver, don't kill the loop
            for p in d.pend:
                p.error = e
        self._note_dispatch_wall(
            time.monotonic() - min(p.t_submit for p in d.pend))
        for p in d.pend:
            p.event.set()

    # -- pipelined stages ----------------------------------------------

    def _fail(self, d: _Dispatch, e: BaseException) -> None:
        """Strict per-batch error propagation: the failed stage fails
        ONLY this fused batch's submitters; later batches proceed."""
        if d.mem_bytes:
            PROFILER.mem_sub("staging_ring", d.mem_bytes)
            d.mem_bytes = 0
        for p in d.pend:
            p.error = e
            p.event.set()

    def _h2d_loop(self) -> None:
        prof = self._stage_prof["h2d"]
        while True:
            prof.enter("idle")
            d = self._q_h2d.get()
            if d is None:
                self._q_compute.put(None)
                return
            prof.enter("busy")
            try:
                t0 = time.monotonic()
                d.stacked = d.pend[0].batch if len(d.pend) == 1 \
                    else np.concatenate([p.batch for p in d.pend])
                d.dev = self._devops.h2d(d.stacked)
                d.mem_bytes = int(getattr(d.stacked, "nbytes", 0))
                PROFILER.mem_add("staging_ring", d.mem_bytes)
                if d.prefetch is not None:
                    # decode-table staging rides the h2d stage: the
                    # inversion + bitmatrix upload of THIS dispatch
                    # overlap the PREVIOUS dispatch's compute
                    d.prefetch()
                d.seg["h2d"] = (t0, time.monotonic())
            except BaseException as e:
                self._fail(d, e)
                continue
            prof.enter("blocked")
            self._q_compute.put(d)

    def _compute_loop(self) -> None:
        prof = self._stage_prof["compute"]
        while True:
            prof.enter("idle")
            d = self._q_compute.get()
            if d is None:
                self._q_d2h.put(None)
                return
            prof.enter("busy")
            try:
                t0 = time.monotonic()
                d.out_dev = self._run_compute(d)
                d.seg["compute"] = (t0, time.monotonic())
            except BaseException as e:
                self._fail(d, e)
                continue
            prof.enter("blocked")
            self._q_d2h.put(d)

    def _d2h_loop(self) -> None:
        prof = self._stage_prof["d2h"]
        while True:
            prof.enter("idle")
            d = self._q_d2h.get()
            if d is None:
                return
            prof.enter("busy")
            try:
                t0 = time.monotonic()
                out = self._devops.d2h(d.out_dev)
                d.seg["d2h"] = (t0, time.monotonic())
                self._slice_results(d, out)
                self._adopt_residents(d, d.dev, d.out_dev)
                self._account(d)
                if d.kind == "fused":
                    self._account_fused(d)
            except BaseException as e:
                self._fail(d, e)
                continue
            finally:
                if d.mem_bytes:
                    PROFILER.mem_sub("staging_ring", d.mem_bytes)
                    d.mem_bytes = 0
            self._note_dispatch_wall(
                time.monotonic() - min(p.t_submit for p in d.pend))
            for p in d.pend:
                p.event.set()

    def _run_compute(self, d: _Dispatch):
        """Run the fused program, donating the staged input when safe.

        The staged buffer is dispatcher-private (h2d made a fresh device
        copy; submitters only ever hold their host arrays), so donation
        can never invalidate caller-visible data. It is skipped when the
        dispatch adopts into the HBM tier — adoption reads the staged
        input after compute."""
        wants_adopt = any(p.resident is not None for p in d.pend)
        # encode only: an encode fn is one trace per (codec, shape),
        # but a decode fn closes over its erasure signature — jitting
        # it per signature would pay a fresh trace/compile for every
        # new pattern, exactly the cost the table bank exists to avoid
        if self._donate_ok and d.kind == "enc" and not wants_adopt:
            dfn = self._donate_fns.get(d.key)
            fresh_trace = dfn is None
            if dfn is None:
                import jax
                if len(self._donate_fns) >= 256:
                    # bounded: distinct (codec, kind, shape, signature)
                    # keys grow without limit on a long-lived OSD
                    self._donate_fns.clear()
                dfn = self._donate_fns.setdefault(
                    d.key, jax.jit(d.fn, donate_argnums=(0,)))
            if dfn is not False:
                try:
                    nbytes = int(getattr(d.dev, "nbytes", 0))
                    PROFILER.mem_add("donated_buffers", nbytes)
                    try:
                        t0 = time.perf_counter()
                        out = self._devops.run(dfn, d.dev)
                        if fresh_trace and PROFILER.enabled:
                            # first run of a fresh donate fn IS its
                            # trace+compile; register the event so the
                            # storm detector sees dispatcher churn too
                            PROFILER.record_compile(
                                "tpu_dispatch.donate",
                                ("key", hash(d.key)),
                                time.perf_counter() - t0)
                    finally:
                        PROFILER.mem_sub("donated_buffers", nbytes)
                    self.perf.inc("l_tpu_donated")
                    return out
                except BaseException:
                    # not traceable / donation rejected: remember, and
                    # re-stage (the donated buffer may be gone) for the
                    # plain call
                    self._donate_fns[d.key] = False
                    d.dev = self._devops.h2d(d.stacked)
        return self._devops.run(d.fn, d.dev)

    def _slice_results(self, d: _Dispatch, out) -> None:
        if len(d.pend) == 1:
            d.pend[0].out = out
            return
        off = 0
        for p in d.pend:
            s = p.batch.shape[0]
            p.out = out[off:off + s]
            off += s

    def _adopt_residents(self, d: _Dispatch, data_src, parity_src
                         ) -> None:
        """Hand the staged data rows + computed parity rows to the HBM
        tier for any submitter that asked — the arrays are already
        device-side in pipelined mode, so residency costs ZERO extra
        transfers. Adoption failures never fail the submitter (the tier
        is a cache)."""
        if d.kind == "fused":
            # one submitter per fused dispatch (the key is unique):
            # adopt what was actually STORED — the compressed rows when
            # the device chose to compress, the staged raw rows when it
            # chose store — and keep the device-computed shard crcs
            # beside them for scrub-from-digest
            p = d.pend[0]
            if p.resident is None or not isinstance(p.out, dict):
                return
            tier, key, codec = p.resident
            host = p.out
            out = parity_src if isinstance(parity_src, dict) else host
            try:
                if "do_compress" in host:
                    # compress-mode runs adopt from the program's
                    # stored buffer (== raw when the device chose
                    # store): the staged input may have been DONATED
                    # to the fused program and must not be read
                    used = int(host["used_stripes"])
                    rows, par = out["stored"][:used], \
                        out["parity"][:used]
                else:
                    rows = data_src
                    par = out["parity"][:data_src.shape[0]]
                tier.adopt_encode(
                    key, rows, par, codec,
                    digests=np.asarray(host["shard_crcs"],
                                       dtype=np.uint32))
            except Exception:
                pass
            return
        off = 0
        for p in d.pend:
            s = p.batch.shape[0]
            if p.resident is not None:
                tier, key, codec = p.resident
                try:
                    tier.adopt_encode(key, data_src[off:off + s],
                                      parity_src[off:off + s], codec)
                except Exception:
                    pass
            off += s

    def _account(self, d: _Dispatch) -> None:
        """Fold one dispatch's measured stage intervals into the
        l_tpu_* counters and back-fill queue/device spans under every
        participating op's trace (the segments are shared: a fused
        dispatch ran once for all of them). In pipelined mode the
        intervals are REAL wall stamps, so spans from consecutive
        dispatches overlap — that overlap is the proof the pipeline
        works, and bench.py gates on it."""
        seg = d.seg
        if not seg:
            return
        h0, h1 = seg.get("h2d", (d.t_take, d.t_take))
        c0, c1 = seg.get("compute", (h1, h1))
        d0, d1 = seg.get("d2h", (c1, c1))
        self.perf.tinc("l_tpu_h2d", h1 - h0)
        self.perf.tinc("l_tpu_compute", c1 - c0)
        self.perf.tinc("l_tpu_d2h", d1 - d0)
        for p in d.pend:
            self.perf.tinc("l_tpu_dispatch_queue",
                           max(0.0, d.t_take - p.t_submit))
            if not p.trace.valid():
                continue
            p.trace.child_interval("tpu_queue", p.t_submit, d.t_take)
            dev = p.trace.child_interval(
                "tpu_device", h0, d1,
                batch=int(sum(q.batch.shape[0] for q in d.pend)),
                coalesced=len(d.pend))
            dev.child_interval("h2d", h0, h1)
            dev.child_interval("compute", c0, c1)
            dev.child_interval("d2h", d0, d1)

    def _account_fused(self, d: _Dispatch) -> None:
        """Fold one fused write's outcome into the l_tpu_fused_*
        counters and the fused_stats bag (the `dispatch status` fused
        section + the ceph_tpu_fused_* Prometheus series)."""
        p = d.pend[0]
        host = p.out
        if not isinstance(host, dict):
            return
        raw = int(getattr(p.batch, "nbytes", 0))
        compressed = bool(host.get("do_compress", False))
        stored = int(host["comp_len"]) if compressed else raw
        par = host.get("parity")
        m_chunk = int(par.shape[1]) * int(par.shape[2]) \
            if par is not None and getattr(par, "ndim", 0) == 3 else 0
        stripes = int(host["used_stripes"]) if "used_stripes" in host \
            else (raw // (p.batch.shape[1] * p.batch.shape[2])
                  if raw else 0)
        out_bytes = stored + stripes * m_chunk
        probe_reject = "probe_ok" in host and not bool(host["probe_ok"])
        ratio_milli = (stored * 1000) // raw if raw else 1000
        self.perf.inc("l_tpu_fused_dispatches")
        self.perf.inc("l_tpu_fused_bytes_in", raw)
        self.perf.inc("l_tpu_fused_bytes_out", out_bytes)
        if compressed:
            self.perf.inc("l_tpu_fused_compressed")
        if probe_reject:
            self.perf.inc("l_tpu_fused_probe_rejects")
        self.perf.tinc("l_tpu_fused_ratio_milli", ratio_milli)
        with self.lock:
            st = self.fused_stats
            st["dispatches"] += 1
            st["bytes_in"] += raw
            st["bytes_out"] += out_bytes
            st["compressed"] += int(compressed)
            st["probe_rejects"] += int(probe_reject)
            st["ratio_milli_sum"] += ratio_milli
