"""Stripe math + the batched encode/decode seam + integrity hashes.

Role of the reference's ECUtil (src/osd/ECUtil.{h,cc}):

  stripe_info_t   offset arithmetic between the logical object address
                  space and per-shard chunk address spaces
                  (ECUtil.h:31-84) — reproduced operation-for-operation
                  since every byte of RMW planning depends on it
  encode/decode   the reference loops one stripe_width per codec call
                  (ECUtil.cc:100-139, loop :116). Here the whole
                  multi-stripe payload is reshaped to [S, k, chunk] and
                  encoded in ONE batched device call — the structural
                  change the TPU design exists for
  HashInfo        cumulative per-shard crc xattr (ECUtil.h:105-163)

All byte movement stays in numpy; the codec's encode_batch/decode_batch
own the device.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..errors import ErasureCodeError

__all__ = ["StripeInfo", "encode", "encode_fused", "decode",
           "recover_cross_chip", "repair_fraction", "repair_combine",
           "repair_cross_chip", "HashInfo"]

CHUNK_ALIGNMENT = 64


class StripeInfo:
    """stripe_info_t: (stripe_count=k, stripe_width=k*chunk)."""

    def __init__(self, stripe_count: int, stripe_width: int):
        if stripe_width % stripe_count != 0:
            raise ValueError("stripe_width %d %% stripe_count %d != 0"
                             % (stripe_width, stripe_count))
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // stripe_count

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1) // self.stripe_width) \
            * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset - rem + self.stripe_width if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def aligned_offset_len_to_chunk(self, off_len: tuple) -> tuple:
        off, length = off_len
        return (self.aligned_logical_offset_to_chunk_offset(off),
                self.aligned_logical_offset_to_chunk_offset(length))

    def offset_len_to_stripe_bounds(self, off_len: tuple) -> tuple:
        off, length = off_len
        start = self.logical_to_prev_stripe_offset(off)
        return (start,
                self.logical_to_next_stripe_offset((off - start) + length))


def encode(sinfo: StripeInfo, codec, data, want=None,
           dispatcher=None, trace=None, resident=None) -> dict:
    """Encode a stripe-aligned payload -> {shard: chunk bytes}.

    data: bytes/uint8 array whose length is a multiple of stripe_width.
    ONE batched device call for all stripes (vs the reference's
    per-stripe loop). Returns every shard unless `want` restricts it.
    With a dispatcher (osd/tpu_dispatch.py), concurrent callers sharing
    this codec coalesce into one fused device call.

    resident=(tier, key) retains the encode device-side in the
    HbmChunkTier: through the dispatcher the pipeline adopts the
    STAGED device arrays (zero extra transfers); without one the tier
    adopts the host arrays itself (that h2d is then the object's one
    crossing).
    """
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else \
        np.asarray(data, dtype=np.uint8).reshape(-1)
    if arr.size % sinfo.stripe_width != 0:
        raise ErasureCodeError(
            22, "payload %d not stripe aligned (width %d)"
            % (arr.size, sinfo.stripe_width))
    if arr.size == 0:
        return {}
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    stripes = arr.size // sinfo.stripe_width
    # [S, k, chunk]: stripes become the device batch dimension
    batch = arr.reshape(stripes, k, sinfo.chunk_size)
    if dispatcher is not None:
        parity = np.asarray(dispatcher.encode(codec, batch, trace=trace,
                                              resident=resident))
    else:
        parity = np.asarray(codec.encode_batch(batch))
        if resident is not None:
            tier, key = resident
            try:
                tier.adopt_encode(key, batch, parity, codec)
            except Exception:
                pass   # the tier is a cache: adoption never fails a write
    out = {}
    for i in range(n):
        idx = codec.chunk_index(i)
        if want is not None and idx not in want:
            continue
        src = batch[:, i, :] if i < k else parity[:, i - k, :]
        out[idx] = np.ascontiguousarray(src).reshape(-1)
    return out


def encode_fused(sinfo: StripeInfo, codec, data, want=None,
                 dispatcher=None, trace=None, resident=None,
                 mode: str = "store", required_ratio: float = 0.875,
                 entropy_max_bits: float = 7.0) -> tuple:
    """Whole-object write through the fused device transform: per-chunk
    digests, the compressibility probe + compress-vs-store decision,
    and the EC encode run as ONE device program — one h2d of the raw
    payload, one fused program, one d2h of parity + digests (+ the
    compressed payload when the device chose to compress).

    Returns (shard_map, FusedResult).  shard_map is {shard: chunk
    stream} of what must LAND ON DISK — the compressed container's
    stripes when mode="compress" and the probe accepted, the raw
    stripes otherwise.  The FusedResult carries the device-computed
    per-shard crcs (HashInfo.set_device_hashes), the per-chunk
    crc32c/xxh32 digests, and the compression verdict the caller
    records in the hinfo xattr.

    resident=(tier, key) adopts the STORED rows + shard crcs into the
    HbmChunkTier (scrub-from-digest), exactly like encode()'s resident
    contract.
    """
    from . import fused_transform
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else \
        np.asarray(data, dtype=np.uint8).reshape(-1)
    if arr.size % sinfo.stripe_width != 0:
        raise ErasureCodeError(
            22, "payload %d not stripe aligned (width %d)"
            % (arr.size, sinfo.stripe_width))
    if arr.size == 0:
        return {}, None
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    stripes = arr.size // sinfo.stripe_width
    batch = arr.reshape(stripes, k, sinfo.chunk_size)
    if dispatcher is not None:
        r = dispatcher.fused_write(
            codec, batch, mode=mode, required_ratio=required_ratio,
            entropy_max_bits=entropy_max_bits, trace=trace,
            resident=resident)
    else:
        out = fused_transform.run_fused(
            codec, batch, mode=mode, required_ratio=required_ratio,
            entropy_max_bits=entropy_max_bits)
        r = fused_transform.finish_fused(out, stripes, k,
                                         sinfo.chunk_size, mode)
        if resident is not None:
            tier, key = resident
            try:
                rows = r.stored if r.stored is not None else batch
                tier.adopt_encode(
                    key, rows, r.parity, codec,
                    digests=np.asarray(r.shard_crcs, dtype=np.uint32))
            except Exception:
                pass   # the tier is a cache: adoption never fails
    rows = r.stored if r.stored is not None else batch
    parity = np.asarray(r.parity)
    shard_map = {}
    for i in range(n):
        idx = codec.chunk_index(i)
        if want is not None and idx not in want:
            continue
        src = rows[:, i, :] if i < k else parity[:, i - k, :]
        shard_map[idx] = np.ascontiguousarray(
            np.asarray(src)).reshape(-1)
    return shard_map, r


def decode(sinfo: StripeInfo, codec, to_decode: dict,
           want=None, dispatcher=None, trace=None) -> dict:
    """Reconstruct shards from per-shard chunk streams.

    to_decode: {shard: bytes of >= 1 chunks, equal lengths}. Returns
    {shard: bytes} for `want` (default: all shards). Batched across
    stripes in one device call (reference decode loops per stripe,
    ECUtil.cc:8-99). With a dispatcher, concurrent reads sharing an
    erasure signature coalesce into one fused device call (matrix
    codecs only — the locality codecs' want_rows plumbing stays
    direct).
    """
    if not to_decode:
        raise ErasureCodeError(22, "decode with no chunks")
    to_decode = {
        shard: (np.frombuffer(v, dtype=np.uint8)
                if isinstance(v, (bytes, bytearray, memoryview))
                else np.asarray(v, dtype=np.uint8).reshape(-1))
        for shard, v in to_decode.items()}
    lengths = {v.size for v in to_decode.values()}
    if len(lengths) != 1:
        raise ErasureCodeError(22, "chunks have unequal lengths %s" % lengths)
    total = lengths.pop()
    if total % sinfo.chunk_size != 0:
        raise ErasureCodeError(
            22, "chunk stream %d not chunk aligned (%d)"
            % (total, sinfo.chunk_size))
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    want = set(range(n)) if want is None else set(want)
    stripes = total // sinfo.chunk_size

    inv = {codec.chunk_index(i): i for i in range(n)}
    logical = {inv[shard]: buf.reshape(stripes, sinfo.chunk_size)
               for shard, buf in to_decode.items()}

    have = set(to_decode)
    if want <= have:
        return {s: np.ascontiguousarray(
            logical[inv[s]]).reshape(-1) for s in want}

    # Single-erasure region-XOR shortcut (isa/xor_op analog), batched over
    # every stripe in the extent: if the one missing wanted shard is
    # covered by an XOR parity group that fully survived, reconstruct it
    # with one vectorized XOR instead of the matrix path.
    missing_want = want - have
    if len(missing_want) == 1 and hasattr(codec, "xor_plan"):
        m_phys = next(iter(missing_want))
        plan = codec.xor_plan(m_phys, have)
        if plan is not None:
            from ..models.table_cache import xor_recover
            rec = xor_recover({s: logical[inv[s]] for s in plan})
            codec.xor_fast_hits += 1
            out = {}
            for s in want:
                out[s] = (to_decode[s] if s in to_decode
                          else np.ascontiguousarray(rec).reshape(-1))
            return out

    if getattr(codec, "DECODE_BATCH_ANY", False):
        # locality codecs (lrc/shec) accept any recoverable subset and
        # need to know which rows are wanted (a local repair hands over
        # fewer than k shards; unwanted rows may come back as zeros)
        use = tuple(sorted(logical))
        stacked = np.stack([logical[i] for i in use], axis=1)
        full = np.asarray(codec.decode_batch(
            use, stacked,
            want_rows=tuple(sorted(inv[s] for s in want))))
    else:
        use = tuple(sorted(logical))[:k]
        if len(use) < k:
            raise ErasureCodeError(
                5, "not enough chunks to decode (%d < %d)"
                % (len(use), k))
        stacked = np.stack([logical[i] for i in use], axis=1)  # [S,k,chunk]
        if dispatcher is not None:
            full = np.asarray(dispatcher.decode(codec, use, stacked,
                                                trace=trace))
        else:
            full = np.asarray(codec.decode_batch(use, stacked))  # [S,n,chunk]
    out = {}
    for i in range(n):
        idx = codec.chunk_index(i)
        if idx not in want:
            continue
        if idx in to_decode:
            out[idx] = to_decode[idx]
        else:
            out[idx] = np.ascontiguousarray(full[:, i, :]).reshape(-1)
    return out


def recover_cross_chip(sinfo: StripeInfo, codec, to_decode: dict,
                       target_shard: int, mesh=None,
                       expected_sum=None):
    """Mesh-path recovery (ROADMAP direction D): reconstruct ONE
    missing shard with the survivor chunk streams sharded across the
    local device mesh (parallel.mesh.recover_sharded) instead of
    gathered onto the primary's chip.  A psum checksum over the mesh
    verifies the device-resident survivors against their host sum and
    raises MeshChecksumError on mismatch.

    Returns the target shard's bytes, or None when the mesh path does
    not apply (single device, locality codec, non-matrix codec, or a
    survivor set that isn't exactly k matrix rows) — the caller falls
    back to decode().
    """
    if getattr(codec, "DECODE_BATCH_ANY", False) or \
            not hasattr(codec, "_decode_entry"):
        return None
    if getattr(codec, "alpha", 1) > 1:
        # sub-symbol codecs (msr): the decode bitmatrix acts on
        # sub-symbol rows, not chunk rows, so the chunk-shaped
        # recover_sharded program does not apply — their mesh leg is
        # repair_cross_chip (beta-fraction combine), and full-survivor
        # decode falls back to the dispatcher/host path
        return None
    if mesh is None:
        try:
            import jax
            if len(jax.devices()) < 2:
                return None
        except Exception:
            return None
    to_decode = {
        shard: (np.frombuffer(v, dtype=np.uint8)
                if isinstance(v, (bytes, bytearray, memoryview))
                else np.asarray(v, dtype=np.uint8).reshape(-1))
        for shard, v in to_decode.items()}
    lengths = {v.size for v in to_decode.values()}
    if len(lengths) != 1:
        raise ErasureCodeError(22,
                               "chunks have unequal lengths %s" % lengths)
    total = lengths.pop()
    if total == 0 or total % sinfo.chunk_size != 0:
        return None
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    if target_shard in to_decode:
        return np.ascontiguousarray(
            to_decode[target_shard]).tobytes()
    stripes = total // sinfo.chunk_size
    inv = {codec.chunk_index(i): i for i in range(n)}
    logical = {inv[shard]: buf.reshape(stripes, sinfo.chunk_size)
               for shard, buf in to_decode.items()}
    use = tuple(sorted(logical))[:k]
    if len(use) < k:
        raise ErasureCodeError(
            5, "not enough chunks to decode (%d < %d)"
            % (len(use), k))
    stacked = np.stack([logical[i] for i in use], axis=1)  # [S,k,chunk]
    # rateless path first (ROADMAP direction J): the survivor batch is
    # over-decomposed into micro-batches on the shared device work
    # queue, so one slow or dead chip takes fewer micro-batches
    # instead of gating the whole reconstruction.  Same trust boundary
    # as the fixed-shard path: the bytes about to hit the mesh are
    # checksummed against the host sum taken at receive time.
    from ..parallel import rateless as _rl
    disp = _rl.get_dispatcher() if mesh is None else None
    if disp is not None:
        if expected_sum is not None:
            got = int(stacked.astype(np.uint64).sum()) % (1 << 32)
            if got != expected_sum % (1 << 32):
                from ..parallel.mesh import MeshChecksumError
                raise MeshChecksumError(
                    "rateless recovery checksum mismatch: survivor "
                    "sum %d != expected %d"
                    % (got, expected_sum % (1 << 32)))
        full = disp.decode(codec, use, stacked)
        return np.ascontiguousarray(
            full[:, inv[target_shard], :]).reshape(-1).tobytes()
    from ..parallel.mesh import recover_sharded
    row = recover_sharded(codec, use, stacked, inv[target_shard],
                          mesh=mesh, expected_sum=expected_sum)
    return np.ascontiguousarray(row).reshape(-1).tobytes()


def repair_fraction(sinfo: StripeInfo, codec, target_shard: int,
                    chunk_stream, dispatcher=None, trace=None) -> bytes:
    """Helper-side beta projection for regenerating repair: one
    surviving shard's chunk stream -> the fraction stream it ships to
    the primary rebuilding `target_shard` (chunk/alpha bytes per
    chunk).  Batched across stripes in one device call; with a
    dispatcher the projection rides the staged pipeline on the
    helper's own pinned device."""
    arr = np.frombuffer(chunk_stream, dtype=np.uint8) if isinstance(
        chunk_stream, (bytes, bytearray, memoryview)) else \
        np.asarray(chunk_stream, dtype=np.uint8).reshape(-1)
    if arr.size == 0 or arr.size % sinfo.chunk_size != 0:
        raise ErasureCodeError(
            22, "chunk stream %d not chunk aligned (%d)"
            % (arr.size, sinfo.chunk_size))
    stripes = arr.size // sinfo.chunk_size
    batch = arr.reshape(stripes, sinfo.chunk_size)
    if dispatcher is not None:
        frac = np.asarray(dispatcher.repair_fraction(
            codec, target_shard, batch, trace=trace))
    else:
        frac = np.asarray(codec.repair_fraction_batch(
            target_shard, batch))
    return np.ascontiguousarray(frac).reshape(-1).tobytes()


def _stack_fractions(sinfo: StripeInfo, codec, fractions: dict):
    """{helper shard: fraction stream} -> (helpers tuple, [S, d, sub])."""
    d = codec.repair_helper_count()
    if len(fractions) != d:
        raise ErasureCodeError(
            5, "repair combine needs %d fractions, got %d"
            % (d, len(fractions)))
    helpers = tuple(sorted(fractions))
    bufs = {
        h: (np.frombuffer(v, dtype=np.uint8)
            if isinstance(v, (bytes, bytearray, memoryview))
            else np.asarray(v, dtype=np.uint8).reshape(-1))
        for h, v in fractions.items()}
    lengths = {v.size for v in bufs.values()}
    if len(lengths) != 1:
        raise ErasureCodeError(
            22, "fractions have unequal lengths %s" % lengths)
    total = lengths.pop()
    sub = codec.repair_sub_size(sinfo.chunk_size)
    if total == 0 or total % sub != 0:
        raise ErasureCodeError(
            22, "fraction stream %d not sub-symbol aligned (%d)"
            % (total, sub))
    stripes = total // sub
    stacked = np.stack([bufs[h].reshape(stripes, sub)
                        for h in helpers], axis=1)  # [S, d, sub]
    return helpers, stacked


def repair_combine(sinfo: StripeInfo, codec, target_shard: int,
                   fractions: dict, dispatcher=None,
                   trace=None) -> bytes:
    """Primary-side combine: the d helper fraction streams -> the
    rebuilt target shard's chunk stream (dispatcher/host path)."""
    helpers, stacked = _stack_fractions(sinfo, codec, fractions)
    if dispatcher is not None:
        out = np.asarray(dispatcher.repair_combine(
            codec, target_shard, helpers, stacked, trace=trace))
    else:
        out = np.asarray(codec.repair_combine_batch(
            target_shard, helpers, stacked))
    return np.ascontiguousarray(out).reshape(-1).tobytes()


def repair_cross_chip(sinfo: StripeInfo, codec, target_shard: int,
                      fractions: dict, mesh=None, expected_sum=None):
    """Mesh-path repair combine (the repair analog of
    recover_cross_chip): the stacked beta-fractions are sharded across
    the local device mesh, psum-checksummed against their host sum,
    and combined there (parallel.mesh.repair_sharded) — a rebuild
    storm never gathers full survivors anywhere.

    Returns the rebuilt shard's bytes, or None when the mesh path does
    not apply (single device, codec without fraction repair) — the
    caller falls back to repair_combine()."""
    if not getattr(codec, "supports_repair", lambda: False)() or \
            not hasattr(codec, "_combine_entry"):
        return None
    if mesh is None:
        try:
            import jax
            if len(jax.devices()) < 2:
                return None
        except Exception:
            return None
    helpers, stacked = _stack_fractions(sinfo, codec, fractions)
    # rateless path first (direction J): beta-fraction combine rides
    # the shared micro-batch queue; a straggling chip degrades the
    # combine proportionally instead of gating it
    from ..parallel import rateless as _rl
    disp = _rl.get_dispatcher() if mesh is None else None
    if disp is not None:
        if expected_sum is not None:
            got = int(stacked.astype(np.uint64).sum()) % (1 << 32)
            if got != expected_sum % (1 << 32):
                from ..parallel.mesh import MeshChecksumError
                raise MeshChecksumError(
                    "rateless repair checksum mismatch: fraction "
                    "sum %d != expected %d"
                    % (got, expected_sum % (1 << 32)))
        out = disp.repair_combine(codec, target_shard, helpers,
                                  stacked)
        return np.ascontiguousarray(out).reshape(-1).tobytes()
    from ..parallel.mesh import repair_sharded
    out = repair_sharded(codec, target_shard, helpers, stacked,
                         mesh=mesh, expected_sum=expected_sum)
    return np.ascontiguousarray(out).reshape(-1).tobytes()


def decode_concat(sinfo: StripeInfo, codec, to_decode: dict,
                  dispatcher=None, trace=None) -> bytes:
    """Reconstruct and concatenate the data shards back into the logical
    payload (the read-path finish, ECUtil.cc:46-99)."""
    k = codec.get_data_chunk_count()
    want = {codec.chunk_index(i) for i in range(k)}
    shards = decode(sinfo, codec, to_decode, want, dispatcher=dispatcher,
                    trace=trace)
    total = len(next(iter(shards.values())))
    stripes = total // sinfo.chunk_size
    stacked = np.stack(
        [np.asarray(shards[codec.chunk_index(i)]).reshape(
            stripes, sinfo.chunk_size) for i in range(k)], axis=1)
    return np.ascontiguousarray(stacked).reshape(-1).tobytes()


class HashInfo:
    """Cumulative per-shard crc + size xattr (ECUtil.h:105-163).

    append() must be called with stripe-aligned same-length per-shard
    appends; the crc chains so any historical corruption is detectable
    on deep scrub.

    The fused write transform (osd/fused_transform.py) bypasses the
    host crc chain entirely: set_device_hashes() accepts the
    device-computed per-shard crcs wholesale for a full-object write,
    and comp_info records the on-device compression of the stored
    stream ({"alg", "orig_chunk_size", "comp_len", "padded_len"}) —
    when set, total_chunk_size is the STORED (compressed) per-shard
    stream length while logical sizes derive from orig_chunk_size.
    """

    def __init__(self, num_chunks: int = 0):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0] * num_chunks
        self.projected_total_chunk_size = 0
        self.comp_info: dict | None = None

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def append(self, old_size: int, to_append: dict) -> None:
        assert old_size == self.total_chunk_size
        sizes = {len(np.asarray(v).reshape(-1)) for v in to_append.values()}
        assert len(sizes) == 1
        size = sizes.pop()
        if self.has_chunk_hash():
            assert len(to_append) == len(self.cumulative_shard_hashes)
            for shard, buf in to_append.items():
                data = np.asarray(buf, dtype=np.uint8).reshape(-1).tobytes()
                self.cumulative_shard_hashes[shard] = zlib.crc32(
                    data, self.cumulative_shard_hashes[shard]) & 0xFFFFFFFF
        self.total_chunk_size += size

    def set_device_hashes(self, shard_crcs, total_chunk_size: int,
                          comp_info: dict | None = None) -> None:
        """Accept device-computed cumulative shard crcs wholesale (the
        fused write transform's output) — valid only as a FULL-object
        (re)write, which is exactly when the fused path runs.  Zero
        host hashing: the crcs were computed beside the encode on
        device.  comp_info records (or, None, clears) the stored
        stream's compression."""
        self.cumulative_shard_hashes = [int(c) & 0xFFFFFFFF
                                        for c in shard_crcs]
        self.total_chunk_size = int(total_chunk_size)
        self.comp_info = dict(comp_info) if comp_info else None

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def get_total_logical_size(self, sinfo: StripeInfo) -> int:
        base = self.comp_info["orig_chunk_size"] \
            if self.comp_info is not None else self.total_chunk_size
        return base * (sinfo.stripe_width // sinfo.chunk_size)

    def get_projected_total_logical_size(self, sinfo: StripeInfo) -> int:
        return self.projected_total_chunk_size * (sinfo.stripe_width //
                                                  sinfo.chunk_size)

    def set_projected_total_logical_size(self, sinfo: StripeInfo,
                                         logical_size: int) -> None:
        assert sinfo.logical_offset_is_stripe_aligned(logical_size)
        self.projected_total_chunk_size = \
            sinfo.aligned_logical_offset_to_chunk_offset(logical_size)

    def clear(self) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0] * len(
            self.cumulative_shard_hashes)
        self.comp_info = None

    def to_dict(self) -> dict:
        d = {"total_chunk_size": self.total_chunk_size,
             "cumulative_shard_hashes": list(
                 self.cumulative_shard_hashes)}
        if self.comp_info is not None:
            # only compressed objects carry the key: hinfo xattrs
            # written before the fused transform stay byte-identical
            d["comp_info"] = dict(self.comp_info)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "HashInfo":
        h = cls(len(d["cumulative_shard_hashes"]))
        h.total_chunk_size = d["total_chunk_size"]
        h.cumulative_shard_hashes = list(d["cumulative_shard_hashes"])
        h.comp_info = dict(d["comp_info"]) if d.get("comp_info") \
            else None
        # projections live in LOGICAL space: a compressed object's
        # projected size derives from the raw-equivalent chunk size
        h.projected_total_chunk_size = \
            h.comp_info["orig_chunk_size"] if h.comp_info is not None \
            else h.total_chunk_size
        return h
