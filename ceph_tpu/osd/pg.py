"""Placement group: op execution, logging, peering-lite, recovery drive.

Role of the reference's PG/PrimaryLogPG (src/osd/PG.{h,cc},
PrimaryLogPG.cc): a PG executes client ops in order through its backend
(do_op -> execute_ctx -> submit_transaction), maintains a per-PG op log
(PGLog), reacts to map changes (the peering statechart collapsed into
on_map_change: new interval -> re-role -> primary drives recovery), and
recovers missing objects by comparing inventories and pushing
reconstructed state (the storage world's elastic recovery).

Collections: one per (pg, shard) — EC shard s lives in cid
("pg", str(pgid), s) on its host OSD; replicated uses shard -1
(mirroring ghobject shard_id_t namespacing).
"""

from __future__ import annotations

import threading
import time as _time

from ..msg.message import MOSDPGPull, MOSDPGPush, MOSDPGScan
from ..store.object_store import Transaction
from .ec_backend import ECBackend
from .osd_map import CRUSH_ITEM_NONE, POOL_TYPE_ERASURE
from .pg_transaction import PGTransaction
from .replicated_backend import ReplicatedBackend

__all__ = ["PG"]

VERSION_ATTR = "_v"


class PG:
    def __init__(self, daemon, pgid, pool):
        self.daemon = daemon
        self.pgid = pgid
        self.pool = pool
        self.whoami = daemon.whoami
        self.store = daemon.store
        self.lock = threading.RLock()
        self.acting: list[int] = []
        self.acting_primary = -1
        self.up: list[int] = []
        self.interval = 0
        self.last_version = 0
        self.pg_log: list[tuple] = []
        self.waiting_for_active: list = []
        self._pulling: dict = {}   # oid -> pull sent at (monotonic)
        self._deleted_log: dict = {}   # oid -> version it was deleted at
        self.scrub_stats: dict = {"state": "never"}
        self._scrub_waiting: set = set()
        self._scrub_replies: dict = {}
        if pool.is_erasure():
            from .. import registry
            profile = daemon.ec_profile_for(pool)
            codec = registry.factory(profile["plugin"], dict(profile))
            self.backend = ECBackend(self, codec, pool.stripe_width)
        else:
            self.backend = ReplicatedBackend(self)
        self._ensure_collections()
        # a (re)started OSD must never mint versions below what its own
        # store has seen, or recovery judges stale peer copies "newer"
        # and clobbers acked writes
        for shard in ([-1] if not pool.is_erasure()
                      else list(range(pool.size)) + [-1]):
            for v in self._local_inventory(shard).values():
                if v > self.last_version:
                    self.last_version = v

    # -- identity / listener interface for backends --------------------

    def cid_of_shard(self, shard: int):
        return ("pg", str(self.pgid), shard)

    def my_shard(self) -> int:
        """This OSD's shard in the acting set (-1 for replicated)."""
        if not self.pool.is_erasure():
            return -1
        with self.lock:
            for i, osd in enumerate(self.acting):
                if osd == self.whoami:
                    return i
        return -1

    def acting_osds(self) -> list:
        with self.lock:
            return list(self.acting)

    def acting_shards(self) -> dict:
        """shard -> osd (CRUSH_ITEM_NONE holes preserved for EC)."""
        with self.lock:
            return {i: osd for i, osd in enumerate(self.acting)}

    def is_primary(self) -> bool:
        with self.lock:
            return self.acting_primary == self.whoami

    def map_epoch(self) -> int:
        return self.daemon.map_epoch()

    def send_to_osd(self, osd: int, msg) -> None:
        self.daemon.send_to_osd_cluster(osd, msg)

    def local_read_shard(self, shard: int, oid, off: int,
                         length: int) -> bytes:
        if shard != -1 and self.pool.is_erasure():
            # replicas serve THEIR shard; the cid names it explicitly
            return self.store.read(self.cid_of_shard(shard), oid, off,
                                   length)
        return self.store.read(self.cid_of_shard(-1), oid, off, length)

    def local_getattr(self, oid, name):
        shard = self.my_shard()
        try:
            return self.store.getattr(self.cid_of_shard(shard), oid, name)
        except KeyError:
            return None

    PG_LOG_CAP = 5000

    def log_operation(self, log_entries, at_version, shard) -> None:
        with self.lock:
            self.pg_log.extend(log_entries)
            if len(self.pg_log) > self.PG_LOG_CAP:
                del self.pg_log[:len(self.pg_log) - self.PG_LOG_CAP]
            for entry in log_entries:
                if len(entry) < 3:
                    continue
                v, oid, kind = entry[0], entry[1], entry[2]
                if kind == "delete":
                    # divergence oracle: "oid was deleted at version v".
                    # Re-insert so dict-order eviction below stays LRU:
                    # a re-deleted oid must not keep its ancient slot.
                    if v > self._deleted_log.get(oid, -1):
                        self._deleted_log.pop(oid, None)
                        self._deleted_log[oid] = v
                elif v > self._deleted_log.get(oid, -1):
                    # a LATER re-create supersedes the delete record;
                    # an older (duplicate/retransmitted) modify must not
                    self._deleted_log.pop(oid, None)
            while len(self._deleted_log) > self.PG_LOG_CAP:
                self._deleted_log.pop(next(iter(self._deleted_log)))
            self.last_version = max(self.last_version, at_version)

    def _ensure_collections(self) -> None:
        txn = Transaction()
        if self.pool.is_erasure():
            for shard in range(self.pool.size):
                txn.create_collection(self.cid_of_shard(shard))
        txn.create_collection(self.cid_of_shard(-1))
        self.store.queue_transaction(txn)

    # -- peering-lite --------------------------------------------------

    def on_map_change(self) -> None:
        m = self.daemon.osdmap
        up, upp, acting, actp = m.pg_to_up_acting_osds(self.pgid)
        with self.lock:
            changed = acting != self.acting or actp != self.acting_primary
            self.up = up
            self.acting = acting
            self.acting_primary = actp
            if changed:
                self.interval += 1
            waiting, self.waiting_for_active = \
                self.waiting_for_active, []
        if changed and self.is_primary():
            self.daemon.queue_recovery(self)
        for fn in waiting:
            fn()

    def active_for_write(self) -> bool:
        with self.lock:
            alive = sum(1 for o in self.acting if o != CRUSH_ITEM_NONE)
            return alive >= self.pool.min_size and self.is_primary()

    def active_for_read(self) -> bool:
        with self.lock:
            alive = sum(1 for o in self.acting if o != CRUSH_ITEM_NONE)
            if self.pool.is_erasure():
                k = self.backend.codec.get_data_chunk_count()
                return alive >= k and self.is_primary()
            return self.is_primary()

    # -- client op execution (PrimaryLogPG::do_op collapsed) -----------

    def do_op(self, msg, reply_fn) -> None:
        if not self.is_primary():
            reply_fn(-11, None)  # EAGAIN: wrong primary / not peered
            return
        if any(op[0] == "call" for op in msg.ops):
            self._do_call_op(msg, reply_fn)
            return
        reads = [op for op in msg.ops if op[0] in
                 ("read", "stat", "getxattr", "omap_get", "list")]
        if reads and len(reads) == len(msg.ops):
            self._do_read_ops(msg, reply_fn)
            return
        if not self.active_for_write():
            # hold until peered enough (waiting_for_active)
            with self.lock:
                self.waiting_for_active.append(
                    lambda: self.do_op(msg, reply_fn))
            return
        self._do_write_ops(msg, reply_fn)

    def _do_call_op(self, msg, reply_fn) -> None:
        """Object-class exec (PrimaryLogPG do_osd_ops CEPH_OSD_OP_CALL).

        Classes need synchronous local reads, which EC pools cannot
        serve (objects_read_sync -EOPNOTSUPP, ecbackend.rst:79-83) —
        so, like the reference, cls is refused on erasure pools.
        """
        from .objclass import CLS_METHOD_WR, ClassHandler, MethodContext
        if self.pool.is_erasure():
            reply_fn(-95, None)  # EOPNOTSUPP
            return
        if len(msg.ops) != 1:
            # mixing exec with other ops in one message would silently
            # drop the rest; reject the vector outright
            reply_fn(-22, None)  # EINVAL
            return
        _, cls_name, method_name, indata = msg.ops[0]
        method = ClassHandler.instance().get_method(cls_name, method_name)
        if method is None:
            reply_fn(-95, None)  # unknown class/method (reference: same)
            return
        if method.flags & CLS_METHOD_WR and not self.active_for_write():
            with self.lock:
                self.waiting_for_active.append(
                    lambda: self.do_op(msg, reply_fn))
            return
        hctx = MethodContext(self, msg.oid)
        try:
            ret, out = method.fn(hctx, indata)
        except Exception:
            reply_fn(-5, None)
            return
        if ret != 0 or not hctx.wrote:
            reply_fn(ret, out)
            return
        if not method.flags & CLS_METHOD_WR:
            reply_fn(-1, None)  # EPERM: RD-only method tried to write
            return
        with self.lock:
            self.last_version += 1
            version = self.last_version
        if not hctx.removed:  # a version xattr would resurrect the object
            hctx.txn.setattr(msg.oid, VERSION_ATTR, str(version).encode())
        self.backend.submit_transaction(
            hctx.txn, version, lambda: reply_fn(ret, out))

    def _do_read_ops(self, msg, reply_fn) -> None:
        if not self.active_for_read():
            with self.lock:
                self.waiting_for_active.append(
                    lambda: self.do_op(msg, reply_fn))
            return
        op = msg.ops[0]
        kind = op[0]
        oid = msg.oid
        if kind == "stat":
            size = self._object_size(oid)
            if size is None:
                reply_fn(-2, None)
            else:
                reply_fn(0, {"size": size})
            return
        if kind == "getxattr":
            cid = self.cid_of_shard(self.my_shard())
            try:
                reply_fn(0, self.store.getattr(cid, oid, op[1]))
            except KeyError:
                reply_fn(-2, None)
            return
        if kind == "omap_get":
            cid = self.cid_of_shard(self.my_shard())
            try:
                reply_fn(0, self.store.omap_get(cid, oid))
            except KeyError:
                reply_fn(-2, None)
            return
        if kind == "list":
            cid = self.cid_of_shard(self.my_shard())
            reply_fn(0, self.store.list_objects(cid))
            return
        # read (off, len)
        size = self._object_size(oid)
        if size is None:
            reply_fn(-2, None)
            return
        off, length = op[1], op[2]
        # clamp to the LOGICAL size: the EC backend's hinfo only knows
        # padded chunk-stream bounds (object_info_t.size analog)
        if length == 0:
            length = max(0, size - off)
        else:
            length = max(0, min(length, size - off))
        if length == 0:
            reply_fn(0, b"")
            return
        self.backend.objects_read(
            oid, off, length,
            lambda data: reply_fn(0 if data is not None else -5, data))

    def _object_size(self, oid):
        if self.pool.is_erasure():
            h = self.backend.get_hinfo(oid)
            if h.get_total_chunk_size() == 0:
                # distinguish empty object from absent
                st = self.store.stat(self.cid_of_shard(self.my_shard()),
                                     oid)
                return 0 if st is not None else None
            # logical size tracked via size xattr for exactness
            raw = self.local_getattr(oid, "_size")
            if raw is not None:
                return int(raw)
            return h.get_total_logical_size(self.backend.sinfo)
        st = self.store.stat(self.cid_of_shard(-1), oid)
        return st["size"] if st is not None else None

    def _do_write_ops(self, msg, reply_fn) -> None:
        t = PGTransaction()
        oid = msg.oid
        logical_size = self._object_size(oid) or 0
        for op in msg.ops:
            kind = op[0]
            if kind == "create":
                t.create(oid)
            elif kind == "write":
                t.write(oid, op[1], op[2])
                logical_size = max(logical_size, op[1] + len(op[2]))
            elif kind == "writefull":
                if self._object_size(oid) is not None:
                    t.remove(oid)
                t.create(oid)
                t.write(oid, 0, op[1])
                logical_size = len(op[1])
            elif kind == "append":
                t.write(oid, logical_size, op[1])
                logical_size += len(op[1])
            elif kind == "zero":
                t.zero(oid, op[1], op[2])
            elif kind == "truncate":
                t.truncate(oid, op[1])
                logical_size = op[1]
            elif kind == "remove":
                t.remove(oid)
                logical_size = 0
            elif kind == "setxattr":
                t.setattr(oid, op[1], op[2])
            elif kind == "rmxattr":
                t.rmattr(oid, op[1])
            elif kind == "omap_set":
                t.omap_setkeys(oid, op[1])
            elif kind == "omap_rm":
                t.omap_rmkeys_op(oid, op[1])
            else:
                reply_fn(-95, None)  # EOPNOTSUPP
                return
        with self.lock:
            self.last_version += 1
            version = self.last_version
        # version + logical size ride as xattrs on every shard
        still_exists = not (len(msg.ops) == 1 and msg.ops[0][0] == "remove")
        if still_exists:
            t.setattr(oid, VERSION_ATTR, str(version).encode())
            t.setattr(oid, "_size", str(logical_size).encode())
        self.backend.submit_transaction(
            t, version, lambda: reply_fn(0, version))

    # -- recovery (primary-driven) -------------------------------------

    def start_recovery(self) -> None:
        """Ask every acting peer for its inventory; push what's missing."""
        if not self.is_primary():
            return
        shards = self.acting_shards()
        for shard, osd in shards.items():
            if osd == CRUSH_ITEM_NONE or osd == self.whoami:
                continue
            self.send_to_osd(osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=shard,
                op="request", map_epoch=self.map_epoch()))
        # also reconcile our own shard(s) synchronously
        my_inv = self._local_inventory(self.my_shard())
        self._reconcile_inventory(self.my_shard(), self.whoami, my_inv)

    def _local_inventory(self, shard: int) -> dict:
        cid = self.cid_of_shard(shard)
        inv = {}
        for oid in self.store.list_objects(cid):
            try:
                raw = self.store.getattr(cid, oid, VERSION_ATTR)
                inv[oid] = int(raw) if raw else 0
            except KeyError:
                inv[oid] = 0
        return inv

    def handle_scan(self, msg) -> None:
        if msg.op == "request":
            # a replica answers with its shard's inventory plus its
            # delete log, so a primary that was down during a delete
            # learns the object is a ghost instead of re-pushing it
            inv = self._local_inventory(
                msg.shard if self.pool.is_erasure() else -1)
            with self.lock:
                deleted = dict(self._deleted_log)
            self.send_to_osd(msg.from_osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=msg.shard,
                op="reply", objects=inv, deleted=deleted,
                map_epoch=self.map_epoch()))
            return
        if msg.op == "scrub_request":
            inv = self._scrub_inventory(
                msg.shard if self.pool.is_erasure() else -1)
            self.send_to_osd(msg.from_osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=msg.shard,
                op="scrub_reply", objects=inv,
                map_epoch=self.map_epoch()))
            return
        if msg.op == "scrub_reply":
            self._handle_scrub_reply(msg.from_osd, msg.shard,
                                     msg.objects)
            return
        # primary side: compare against authoritative inventory
        self._reconcile_inventory(msg.shard, msg.from_osd, msg.objects,
                                  getattr(msg, "deleted", {}) or {})

    # -- scrub (PG_STATE_SCRUBBING; PrimaryLogPG scrub + repair) --------

    def _scrub_inventory(self, shard: int) -> dict:
        """oid -> (version, crc32(data), size) for one shard."""
        import zlib
        cid = self.cid_of_shard(shard)
        inv = {}
        for oid in self.store.list_objects(cid):
            try:
                data = self.store.read(cid, oid)
                raw = self.store.getattr(cid, oid, VERSION_ATTR)
                inv[oid] = (int(raw) if raw else 0,
                            zlib.crc32(data), len(data))
            except (KeyError, OSError):
                inv[oid] = (-1, 0, 0)   # unreadable shard: scrub error
        return inv

    def scrub(self, seq: int | None = None,
              deep: bool = False) -> dict | None:
        """Primary-driven scrub: collect per-object (version, crc, size)
        from every acting peer, compare against the local copy, and
        push repairs for mismatches. Returns immediately; results land
        in self.scrub_stats once all replies arrive.

        seq is the ticket minted by OSDDaemon.scrub_pg (None = direct
        call: mint one here); a superseded ticket aborts silently.

        deep=True on an EC pool additionally verifies every shard's
        stored crc against the write-time hinfo record and rebuilds
        divergent shards from the survivors (decode on the device) —
        the integrity check a shallow EC scrub cannot do."""
        if not self.is_primary():
            return None
        shards = self.acting_shards()
        with self.lock:
            if seq is None:
                self._scrub_seq = getattr(self, "_scrub_seq", 0) + 1
                seq = self._scrub_seq
            elif seq != getattr(self, "_scrub_seq", 0):
                return None  # a newer scrub_pg superseded this ticket
            self._scrub_deep = deep
            self._scrub_waiting = {
                osd for shard, osd in shards.items()
                if osd not in (CRUSH_ITEM_NONE, self.whoami)}
            self._scrub_replies = {}
            self.scrub_stats = {"state": "scrubbing", "errors": 0,
                                "repaired": 0, "objects": 0}
        self._send_scrub_requests(shards)
        if not self._scrub_waiting:
            self._finish_scrub()
        else:
            # one-shot messages wedge on lossy links: retransmit to
            # laggard peers a few times, then give up loudly
            self.daemon.timer.add_event_after(
                1.0, self._scrub_retry, seq, 0)
        return self.scrub_stats

    def _send_scrub_requests(self, shards, only: set | None = None):
        for shard, osd in shards.items():
            if osd in (CRUSH_ITEM_NONE, self.whoami):
                continue
            if only is not None and osd not in only:
                continue
            self.send_to_osd(osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=shard,
                op="scrub_request", map_epoch=self.map_epoch()))

    def _scrub_retry(self, seq: int, attempt: int) -> None:
        with self.lock:
            if seq != getattr(self, "_scrub_seq", 0) \
                    or not self._scrub_waiting:
                return  # this scrub finished or was superseded
            waiting = set(self._scrub_waiting)
            if attempt >= 5:
                self._scrub_waiting = set()
                self.scrub_stats = {"state": "failed", "errors": 0,
                                    "repaired": 0, "objects": 0,
                                    "unreachable": sorted(waiting)}
                return
        self._send_scrub_requests(self.acting_shards(), only=waiting)
        self.daemon.timer.add_event_after(
            1.0, self._scrub_retry, seq, attempt + 1)

    def _handle_scrub_reply(self, peer_osd: int, shard: int,
                            inv: dict) -> None:
        with self.lock:
            if peer_osd not in getattr(self, "_scrub_waiting", set()):
                return
            self._scrub_waiting.discard(peer_osd)
            self._scrub_replies[(peer_osd, shard)] = inv
            done = not self._scrub_waiting
        if done:
            self._finish_scrub()

    def _finish_scrub(self) -> None:
        """Compare every replica's inventory to the primary's copy.

        Replicated pools only compare like-for-like copies; EC shards
        hold different bytes per shard, so EC scrub checks only version
        presence (deep EC parity verification = decode check, a later
        round). Authoritative copy = highest version, primary wins
        ties; mismatches are repaired by pushing it."""
        with self.lock:
            seq = getattr(self, "_scrub_seq", 0)
            deep = getattr(self, "_scrub_deep", False)
            replies = {k: dict(v)
                       for k, v in self._scrub_replies.items()}
        local = self._scrub_inventory(
            self.my_shard() if self.pool.is_erasure() else -1)
        errors = repaired = 0
        shallow_repaired: set = set()   # (peer_osd, shard, oid)
        replicated = not self.pool.is_erasure()
        for (peer_osd, shard), inv in replies.items():
            for oid in set(local) | set(inv):
                mine = local.get(oid)
                theirs = inv.get(oid)
                if mine == theirs:
                    continue
                if not replicated:
                    # EC: only flag version divergence
                    if mine is not None and theirs is not None \
                            and mine[0] == theirs[0]:
                        continue
                errors += 1
                if mine is not None and (
                        theirs is None or theirs[0] <= mine[0]):
                    self._push_object(oid, shard, peer_osd, force=True)
                    shallow_repaired.add((peer_osd, shard, oid))
                    repaired += 1
        if not replicated and deep:
            # the deep pass reconstructs objects through the normal EC
            # read path, whose sub-read replies are served by THIS PG's
            # shard worker — run it on its own thread so waiting for
            # them cannot deadlock the worker
            def deep_worker(base_err=errors, base_rep=repaired,
                            nobj=len(local)):
                d_err, d_rep = self._deep_scrub_ec(
                    local, replies, shallow_repaired)
                err, rep = base_err + d_err, base_rep + d_rep
                with self.lock:
                    if seq != getattr(self, "_scrub_seq", 0):
                        return  # a newer scrub superseded this one
                    self.scrub_stats = {
                        "state": "clean" if err == rep
                        else "inconsistent",
                        "errors": err, "repaired": rep,
                        "objects": nobj, "deep": True}

            threading.Thread(target=deep_worker, name="deep-scrub",
                             daemon=True).start()
            return
        with self.lock:
            if seq != getattr(self, "_scrub_seq", 0):
                return  # superseded mid-finish: don't clobber stats
            stats = {
                "state": "clean" if errors == repaired
                else "inconsistent",
                "errors": errors, "repaired": repaired,
                "objects": len(local)}
            if deep:
                # for replicated pools the shallow crc comparison IS
                # the deep check (all copies hold identical bytes);
                # mark completion either way so pollers keying on the
                # 'deep' flag terminate
                stats["deep"] = True
            self.scrub_stats = stats

    def _deep_scrub_ec(self, local_inv: dict, replies: dict,
                       already_repaired: set) -> tuple[int, int]:
        """EC shard verification against the write-time hinfo crcs.

        Ground truth is the per-shard cumulative crc recorded at encode
        time (ECUtil.HashInfo) — NOT a reconstruction, which would
        trust whichever shards it happened to read and could launder a
        corrupt data shard into "authoritative" bytes. A divergent
        shard is rebuilt from the OTHER shards (recover_object excludes
        the target), the rebuilt bytes are re-verified against the
        hinfo crc, and only then force-pushed.
        """
        import zlib

        errors = repaired = 0
        shards = self.acting_shards()
        my_shard = self.my_shard()
        my_inv = {my_shard: local_inv}   # _finish_scrub computed this
        for s in shards:
            if shards[s] == self.whoami and s not in my_inv:
                my_inv[s] = self._scrub_inventory(s)
        for oid, (version, _, _) in sorted(local_inv.items()):
            h = self.backend.get_hinfo(oid)
            if not h.has_chunk_hash() or h.get_total_chunk_size() == 0:
                continue
            for shard, osd in shards.items():
                if osd == CRUSH_ITEM_NONE:
                    continue
                if (osd, shard, oid) in already_repaired:
                    continue   # the shallow pass just fixed this copy
                want_crc = h.get_chunk_hash(shard)
                if osd == self.whoami:
                    have = my_inv.get(shard, {}).get(oid)
                else:
                    have = replies.get((osd, shard), {}).get(oid)
                if have is not None and have[1] == want_crc:
                    continue
                errors += 1
                done = threading.Event()
                got: list = [None]

                def on_done(data, _g=got, _d=done):
                    _g[0] = data
                    _d.set()

                self.backend.recover_object(oid, shard, on_done)
                if not done.wait(10.0) or got[0] is None:
                    continue    # unrepairable now: stays inconsistent
                rebuilt = bytes(got[0])
                if (zlib.crc32(rebuilt) & 0xFFFFFFFF) != want_crc:
                    continue    # survivors are bad too: do NOT launder
                attrs, omap = self._gather_push_meta(oid)
                attrs.setdefault(VERSION_ATTR, str(version).encode())
                push = MOSDPGPush(
                    pgid=self.pgid, from_osd=self.whoami, shard=shard,
                    oid=oid, data=rebuilt, attrs=attrs, omap=omap,
                    version=version, map_epoch=self.map_epoch(),
                    force=True)
                if osd == self.whoami:
                    self.handle_push(push)
                else:
                    self.send_to_osd(osd, push)
                repaired += 1
        return errors, repaired

    def _authoritative_inventory(self) -> dict:
        """Union of all local shard inventories (primary's knowledge)."""
        out = {}
        if self.pool.is_erasure():
            for shard in range(self.pool.size):
                for oid, v in self._local_inventory(shard).items():
                    out[oid] = max(out.get(oid, 0), v)
        for oid, v in self._local_inventory(-1).items():
            out[oid] = max(out.get(oid, 0), v)
        return out

    def _reconcile_inventory(self, shard: int, peer_osd: int,
                             peer_inv: dict,
                             peer_deleted: dict | None = None) -> None:
        peer_deleted = peer_deleted or {}
        want = self._authoritative_inventory()
        missing = [oid for oid, v in want.items()
                   if peer_inv.get(oid, -1) < v]
        for oid in missing:
            del_v = peer_deleted.get(oid, -1)
            if del_v >= want.get(oid, -1):
                # the peer deleted this at/after our version while we
                # were away: our copy is the ghost — adopt the delete
                # locally instead of resurrecting it onto the peer
                with self.lock:
                    if del_v > self._deleted_log.get(oid, -1):
                        self._deleted_log.pop(oid, None)
                        self._deleted_log[oid] = del_v
                txn = Transaction()
                if self.pool.is_erasure():
                    for s in range(self.pool.size):
                        txn.remove(self.cid_of_shard(s), oid)
                else:
                    txn.remove(self.cid_of_shard(-1), oid)
                self.store.queue_transaction(txn)
                continue
            self._push_object(oid, shard, peer_osd)
        if peer_osd == self.whoami:
            return
        # The peer may be AHEAD of us: a revived primary that missed
        # writes must pull them before serving authoritatively, or
        # acked data reads as lost (the peering GetLog/GetMissing
        # role, collapsed onto version xattrs). Deletes that happened
        # while we were down are indistinguishable from new objects
        # without divergent-log handling — resurrection is the known
        # limitation here, data loss is not.
        behind = [oid for oid, v in peer_inv.items()
                  if want.get(oid, -1) < v]
        my_shard = self.my_shard() if self.pool.is_erasure() else -1
        now = _time.monotonic()
        for oid in behind:
            # the divergence oracle: if OUR log shows the object deleted
            # at or after the peer's version, the peer holds a ghost —
            # propagate the delete instead of resurrecting it
            with self.lock:
                del_v = self._deleted_log.get(oid, -1)
            if del_v >= peer_inv[oid]:
                self.send_to_osd(peer_osd, MOSDPGPush(
                    pgid=self.pgid, from_osd=self.whoami, shard=shard,
                    oid=oid, version=del_v,
                    map_epoch=self.map_epoch(), delete=True))
                continue
            # in-flight pull tracking: repeated scan replies during
            # churn must not multiply EC reconstructions of the same
            # object; re-pull only after a timeout (lost push)
            if now - self._pulling.get(oid, -1e9) < 5.0:
                continue
            self._pulling[oid] = now
            self.send_to_osd(peer_osd, MOSDPGPull(
                pgid=self.pgid, from_osd=self.whoami, shard=my_shard,
                oid=oid, map_epoch=self.map_epoch()))
        if peer_inv:
            maxv = max(peer_inv.values())
            with self.lock:
                # never mint versions below what the cluster has seen
                if maxv > self.last_version:
                    self.last_version = maxv

    def handle_pull(self, msg) -> None:
        """A (usually freshly revived) primary asks for our newer copy
        of an object: push it to the requester's shard."""
        self._push_object(msg.oid, msg.shard, msg.from_osd)

    def _gather_push_meta(self, oid) -> tuple[dict, dict]:
        """(attrs, omap) from our local shard for a recovery/repair
        push — handle_push removes+rewrites the target, so the push
        must carry the full metadata set or the target loses it."""
        src_cid = self.cid_of_shard(
            self.my_shard() if self.pool.is_erasure() else -1)
        attrs: dict = {}
        for name in (VERSION_ATTR, "_size", "hinfo_key"):
            try:
                val = self.store.getattr(src_cid, oid, name)
            except KeyError:
                val = None
            if val is not None:
                attrs[name] = val
        try:
            omap = self.store.omap_get(src_cid, oid)
        except KeyError:
            omap = {}
        return attrs, omap

    def _push_object(self, oid, shard: int, peer_osd: int,
                     force: bool = False) -> None:
        attrs, omap = self._gather_push_meta(oid)

        def on_data(data):
            if data is None:
                return
            version = int(attrs.get(VERSION_ATTR, b"0") or 0)
            msg = MOSDPGPush(
                pgid=self.pgid, from_osd=self.whoami, shard=shard,
                oid=oid, data=data, attrs=attrs, omap=omap,
                version=version, map_epoch=self.map_epoch(),
                force=force)
            if peer_osd == self.whoami:
                self.handle_push(msg)
            else:
                self.send_to_osd(peer_osd, msg)

        self.backend.recover_object(oid, shard, on_data)

    def handle_push(self, msg) -> None:
        """Apply a recovery push to the local shard store."""
        cid = self.cid_of_shard(
            msg.shard if self.pool.is_erasure() else -1)
        # never let an in-flight push of an older version clobber a
        # fresher local copy (an acked client write may have landed
        # while the push was in transit)
        try:
            raw = self.store.getattr(cid, msg.oid, VERSION_ATTR)
            local_v = int(raw) if raw else 0
        except KeyError:
            local_v = -1
        # only a strictly newer push may replace an existing copy; a
        # versionless push (source object vanished mid-recovery) must
        # never clobber versioned local data
        self._pulling.pop(msg.oid, None)
        if msg.delete:
            # divergent-delete propagation: drop our ghost copy unless
            # we hold a strictly newer (recreated) version — and record
            # the delete so that if WE later become primary we can
            # propagate it instead of pulling the ghost back
            with self.lock:
                if msg.version > self._deleted_log.get(msg.oid, -1):
                    self._deleted_log.pop(msg.oid, None)
                    self._deleted_log[msg.oid] = msg.version
            if local_v >= 0 and local_v <= msg.version:
                txn = Transaction()
                txn.remove(cid, msg.oid)
                self.store.queue_transaction(txn)
            return
        # scrub repairs (force) may overwrite SAME-version bitrot; no
        # push — forced or not — may ever roll back a strictly newer
        # (acked) local copy
        if local_v >= 0 and (local_v > msg.version
                             or (local_v == msg.version
                                 and not msg.force)):
            return
        txn = Transaction()
        txn.remove(cid, msg.oid)
        txn.touch(cid, msg.oid)
        if msg.data:
            txn.write(cid, msg.oid, 0, msg.data)
        for name, val in msg.attrs.items():
            txn.setattr(cid, msg.oid, name, val)
        if msg.omap:
            txn.omap_setkeys(cid, msg.oid, msg.omap)
        self.store.queue_transaction(txn)
