"""Placement group: op execution, logging, peering, recovery drive.

Role of the reference's PG/PrimaryLogPG (src/osd/PG.{h,cc},
PrimaryLogPG.cc): a PG executes client ops in order through its backend
(do_op -> execute_ctx -> submit_transaction), maintains a durable
per-PG op log (ceph_tpu.osd.pg_log; entries stamped with (epoch,
version) eversions), and converges replicas through the peering rounds
of the reference statechart (PG.h:1811):

  GetInfo      on an interval change the primary queries every up/
               acting peer for its pg info (MOSDPGQuery what=info);
  GetLog       the peer with the highest last_update is authoritative;
               if that is not us, we pull its log delta and MERGE —
               divergent entries (dead-interval writes) are undone,
               newer authoritative entries become `missing`
               (PGLog.merge; ecbackend.rst:149-174 roll-forward);
  GetMissing   activation sends every replica the log segment it
               lacks; replicas merge, report their missing sets, and
               the primary pushes exactly those objects — no inventory
               scan when logs overlap. Scan-based backfill remains the
               fallback for peers whose logs do not overlap (the
               reference's backfill lane).

Writes are gated on activation (active_for_write), so a new primary
cannot mint entries on a stale chain that a later merge would rewind.

Collections: one per (pg, shard) — EC shard s lives in cid
("pg", str(pgid), s) on its host OSD; replicated uses shard -1
(mirroring ghobject shard_id_t namespacing).
"""

from __future__ import annotations

import logging
import threading
import time as _time

from .. import encoding
from ..common.lockdep import make_rlock
from ..msg.message import (MBackfillReserve, MOSDPGLog, MOSDPGNotify,
                           MOSDPGPull, MOSDPGPush, MOSDPGQuery,
                           MOSDPGScan, MWatchNotify)
from ..store.object_store import Transaction
from .ec_backend import ECBackend
from .osd_map import CRUSH_ITEM_NONE, POOL_TYPE_ERASURE
from .pg_log import PGLog, entry_from_tuple
from .pg_transaction import PGTransaction
from .replicated_backend import ReplicatedBackend

__all__ = ["PG"]

VERSION_ATTR = "_v"
META_OID = "__pg_meta__"
SNAPSET_ATTR = "_ss"
WHITEOUT_ATTR = "_whiteout"

# reservation priorities (the reference's OSD_RECOVERY_PRIORITY
# ladder, collapsed to two rungs): degraded-object recovery preempts
# routine backfill in the AsyncReservers, never the other way around
_RESV_PRIO = {"recovery": 180, "backfill": 90, "peering": 250}


def host_crc32(data) -> int:
    """Host-side shard hashing for scrub inventories — the fallback
    when an object is not HBM-resident with device digests.  Module-
    level (not inlined) so tests can assert the fused scrub-from-digest
    path never hashes a byte on the host."""
    import zlib
    return zlib.crc32(data) & 0xFFFFFFFF


def clone_name(oid, cloneid: int) -> str:
    """Clone objects live beside the head as '<oid>@<cloneid>'
    (the ghobject snap id at framework scale)."""
    return "%s@%d" % (oid, cloneid)


def is_clone_oid(oid) -> bool:
    return isinstance(oid, str) and "@" in oid


def is_user_xattr(name: str) -> bool:
    """Is this xattr CLIENT-visible? Internal bookkeeping attrs are
    underscore-prefixed, and the EC hinfo is filtered by name exactly
    like the reference (PrimaryLogPG GETXATTRS strips
    ECUtil::get_hinfo_key()). One definition — getxattrs, copy_get,
    resetxattrs, and the tier flush all share it."""
    return not name.startswith("_") and name != "hinfo_key"


def user_xattrs(attrs: dict) -> dict:
    return {k: v for k, v in attrs.items() if is_user_xattr(k)}


class PG:
    def __init__(self, daemon, pgid, pool):
        self.daemon = daemon
        self.pgid = pgid
        self.pool = pool
        self.whoami = daemon.whoami
        self.store = daemon.store
        self.lock = make_rlock("pg:%s" % (pgid,))
        self.acting: list[int] = []
        self.acting_primary = -1
        self.up: list[int] = []
        self.interval = 0
        self.last_version = 0
        self.pg_log = PGLog()
        self.waiting_for_active: list = []
        self._pulling: dict = {}   # oid -> pull sent at (monotonic)
        self._deleted_log: dict = {}   # oid -> version it was deleted at
        self.scrub_stats: dict = {"state": "never"}
        # unrepaired errors from the LAST completed scrub: reported to
        # the mon in pg stats (MPGStats) and the input behind the
        # OSD_SCRUB_ERRORS health check; cleared by a repairing scrub
        self.scrub_errors = 0
        self._scrub_waiting: set = set()
        self._scrub_replies: dict = {}
        self._repairing: set = set()   # (oid, shard) read-repairs live
        # peering (GetInfo/GetLog/GetMissing)
        self.peer_state = "idle"      # idle|peering|active|replica
        self._peer_seq = 0
        self._peer_infos: dict = {}   # osd -> info dict
        self._peer_wait: set = set()
        self.missing: dict = {}       # oid -> version we need
        self._missing_src: dict = {}  # oid -> osd holding it
        self._missing_waiters: dict = {}   # oid -> [continuations]
        # primary-side map of PEERS' missing objects (the reference's
        # peer_missing / MissingLoc): a shard OSD that reported an
        # object missing serves STALE bytes until its recovery push is
        # acked — EC reads must reconstruct around it, not from it
        self.peer_missing: dict = {}  # oid -> set(osd)
        # backfill lane bookkeeping (pg_stat_t misplaced role): shards
        # being copied to a NEW acting member after a remap — data is
        # still fully readable elsewhere, so these count as misplaced,
        # not degraded, and deliberately do NOT feed the EC
        # read-routing that peer_missing drives
        self.backfilling: dict = {}   # oid -> set(osd)
        self._push_retrying: set = set()   # (oid, peer) retry chains
        # recovery/backfill reservation state machine (the reference's
        # PG recovery-reservation states, common/reserver.py slots):
        # per lane, idle -> local_wait -> remote_wait -> granted, with
        # "toofull" parking a fullness-rejected round.  Pushes queue in
        # _resv_pending while ungranted and drain onto the recovery op
        # class once the local slot AND every replica's remote slot are
        # held.  _resv_remote_keys is the REPLICA side: primaries whose
        # requests we hold/queue remote slots for (cancelled on
        # interval change so a dead primary cannot leak our slots).
        self._resv_state = {"recovery": "idle", "backfill": "idle"}
        self._resv_pending = {"recovery": [], "backfill": []}
        self._resv_want = {"recovery": set(), "backfill": set()}
        self._resv_have = {"recovery": set(), "backfill": set()}
        self._resv_remote_keys: set = set()   # (lane, primary_osd)
        # reqid -> version, rebuilt from the log: the failover-safe
        # client-retransmit dedup (pg_log_entry_t::reqid role)
        from ..common.bounded import BoundedDict
        self._reqids: BoundedDict = BoundedDict()
        self._trimmed_snaps: set = set()
        # EC mutation serialization per object (ObjectContext rw-lock
        # role): the async snapshot pre-read window must not interleave
        # with another write to the same object
        self._obj_gate: dict = {}
        # watch/notify (PrimaryLogPG watchers; volatile on the primary,
        # clients re-watch after a primary change like the Objecter's
        # linger resend)
        self.watchers: dict = {}      # oid -> {cookie: client addr}
        self._notifies: dict = {}     # notify_id -> state
        self._notify_seq = 0
        self._tier_state = None       # PGTier, created on first use
        if pool.is_erasure():
            from .. import registry
            profile = daemon.ec_profile_for(pool)
            codec = registry.factory(profile["plugin"], dict(profile))
            self.backend = ECBackend(self, codec, pool.stripe_width)
        else:
            self.backend = ReplicatedBackend(self)
        self._ensure_collections()
        self._load_log()
        # a (re)started OSD must never mint versions below what its own
        # store has seen, or recovery judges stale peer copies "newer"
        # and clobbers acked writes
        for shard in ([-1] if not pool.is_erasure()
                      else list(range(pool.size)) + [-1]):
            for v in self._local_inventory(shard).values():
                if v > self.last_version:
                    self.last_version = v
        self.last_version = max(self.last_version,
                                self.pg_log.head[1])

    # -- identity / listener interface for backends --------------------

    def cid_of_shard(self, shard: int):
        return ("pg", str(self.pgid), shard)

    def my_shard(self) -> int:
        """This OSD's shard in the acting set (-1 for replicated)."""
        if not self.pool.is_erasure():
            return -1
        with self.lock:
            for i, osd in enumerate(self.acting):
                if osd == self.whoami:
                    return i
        return -1

    def acting_osds(self) -> list:
        with self.lock:
            return list(self.acting)

    def acting_shards(self) -> dict:
        """shard -> osd (CRUSH_ITEM_NONE holes preserved for EC)."""
        with self.lock:
            return {i: osd for i, osd in enumerate(self.acting)}

    def is_primary(self) -> bool:
        with self.lock:
            return self.acting_primary == self.whoami

    def map_epoch(self) -> int:
        return self.daemon.map_epoch()

    def send_to_osd(self, osd: int, msg) -> None:
        self.daemon.send_to_osd_cluster(osd, msg)

    def local_read_shard(self, shard: int, oid, off: int,
                         length: int) -> bytes:
        if shard != -1 and self.pool.is_erasure():
            # replicas serve THEIR shard; the cid names it explicitly
            return self.store.read(self.cid_of_shard(shard), oid, off,
                                   length)
        return self.store.read(self.cid_of_shard(-1), oid, off, length)

    def local_getattr(self, oid, name):
        shard = self.my_shard()
        try:
            return self.store.getattr(self.cid_of_shard(shard), oid, name)
        except KeyError:
            return None

    PG_LOG_CAP = 5000

    def mint_log_entries(self, op_map, at_version: int,
                         reqid: tuple = ("", 0)) -> list:
        """Wire-form entries for a write being submitted: (epoch,
        version, oid, kind, prior, session, tid). The epoch half of
        the eversion lets a later merge tell two same-numbered forks
        apart; the reqid rides REPLICATED so any future primary can
        dedup a client retransmit (exactly-once across failover)."""
        epoch = self.map_epoch()
        out = []
        for oid, obj_op in op_map.items():
            kind = "delete" if obj_op.is_delete() else "modify"
            prior = self._object_version(oid)
            out.append((epoch, at_version, oid, kind, prior,
                        reqid[0], reqid[1]))
        return out

    def _object_version(self, oid) -> int:
        raw = self.local_getattr(oid, VERSION_ATTR)
        try:
            return int(raw) if raw else 0
        except ValueError:
            return 0

    def log_operation(self, log_entries, at_version, shard,
                      txn=None) -> None:
        """Record entries in the in-memory log and make them durable.
        With `txn` (the backend's store transaction for this write)
        the log omap keys ride THE SAME transaction as the data — one
        commit, atomic, like the reference writing pg log keys in the
        op's ObjectStore::Transaction."""
        entries = [entry_from_tuple(t) for t in log_entries]
        dropped: list = []
        with self.lock:
            for entry in entries:
                dropped.extend(self.pg_log.append(entry))
                self.missing.pop(entry.oid, None)
                if entry.reqid[0]:
                    self._reqids[tuple(entry.reqid)] = entry.version
                v, oid, kind = entry.version, entry.oid, entry.kind
                if kind == "delete":
                    # divergence oracle for the scan/backfill lane:
                    # "oid was deleted at version v" (LRU re-insert)
                    if v > self._deleted_log.get(oid, -1):
                        self._deleted_log.pop(oid, None)
                        self._deleted_log[oid] = v
                elif v > self._deleted_log.get(oid, -1):
                    self._deleted_log.pop(oid, None)
            while len(self._deleted_log) > self.PG_LOG_CAP:
                self._deleted_log.pop(next(iter(self._deleted_log)))
            self.last_version = max(self.last_version, at_version)
        if txn is not None:
            cid = self._meta_cid()
            txn.touch(cid, META_OID)
            kv = {self._log_key(e): encoding.encode_any(
                (e.epoch, e.version, e.oid, e.kind, e.prior_version))
                for e in entries}
            if kv:
                txn.omap_setkeys(cid, META_OID, kv)
            if dropped:
                # the durable omap trims with the in-memory log, or it
                # (and the log reloaded at restart) grows forever
                txn.omap_rmkeys(cid, META_OID,
                                [self._log_key(e) for e in dropped])
        else:
            self._persist_log_delta(entries, dropped)

    # -- durable log (meta object omap, the reference's pg log omap) ---

    def _meta_cid(self):
        return self.cid_of_shard(-1)

    @staticmethod
    def _log_key(entry) -> str:
        return "log:%016d.%016d" % (entry.epoch, entry.version)

    def _persist_log_delta(self, entries, dropped=()) -> None:
        txn = Transaction()
        cid = self._meta_cid()
        txn.touch(cid, META_OID)
        kv = {self._log_key(e): encoding.encode_any(
            (e.epoch, e.version, e.oid, e.kind, e.prior_version))
            for e in entries}
        if kv:
            txn.omap_setkeys(cid, META_OID, kv)
        if dropped:
            txn.omap_rmkeys(cid, META_OID,
                            [self._log_key(e) for e in dropped])
        self.store.queue_transaction(txn)

    def _persist_log_full(self) -> None:
        """Rewrite the whole durable log (after a merge rewound it)."""
        txn = Transaction()
        cid = self._meta_cid()
        txn.remove(cid, META_OID)
        txn.touch(cid, META_OID)
        with self.lock:
            rows = self.pg_log.dump()
        kv = {"log:%016d.%016d" % (r[0], r[1]): encoding.encode_any(r)
              for r in rows}
        if kv:
            txn.omap_setkeys(cid, META_OID, kv)
        self.store.queue_transaction(txn)

    def _rebuild_reqids(self) -> None:
        with self.lock:
            self._reqids.clear()
            for e in self.pg_log.entries:
                if e.reqid[0]:
                    self._reqids[tuple(e.reqid)] = e.version

    def _load_log(self) -> None:
        try:
            omap = self.store.omap_get(self._meta_cid(), META_OID)
        except KeyError:
            return
        rows = []
        for key, raw in omap.items():
            if isinstance(key, str) and key.startswith("log:"):
                try:
                    rows.append(encoding.decode_any(raw))
                except encoding.DecodeError:
                    continue
        if rows:
            rows.sort(key=lambda r: (r[0], r[1]))
            self.pg_log.load(rows)
            self._rebuild_reqids()

    def _ensure_collections(self) -> None:
        txn = Transaction()
        if self.pool.is_erasure():
            for shard in range(self.pool.size):
                txn.create_collection(self.cid_of_shard(shard))
        txn.create_collection(self.cid_of_shard(-1))
        self.store.queue_transaction(txn)

    # -- peering-lite --------------------------------------------------

    def on_map_change(self) -> None:
        m = self.daemon.osdmap
        newpool = m.pools.get(self.pgid.pool)
        if newpool is not None and newpool is not self.pool:
            # pool metadata (snap_seq, snaps, removed_snaps) rides the
            # map; trim clones for newly removed snaps
            fresh = [s for s in newpool.removed_snaps
                     if s not in self._trimmed_snaps]
            self.pool = newpool
            if fresh:
                self._trimmed_snaps.update(fresh)
                # trim runs as its own snaptrim-class work item: under
                # mclock it is paced by the snaptrim rates instead of
                # riding the map-change op's class
                self.daemon.op_wq.queue(self.pgid, self.trim_snaps,
                                        fresh, klass="snaptrim",
                                        priority=1)
        up, upp, acting, actp = m.pg_to_up_acting_osds(self.pgid)
        with self.lock:
            changed = acting != self.acting or actp != self.acting_primary
            self.up = up
            self.acting = acting
            self.acting_primary = actp
            if changed:
                self.interval += 1
                # a new interval invalidates the old activation: the
                # primary re-peers, replicas wait for its log
                self.peer_state = ("peering" if actp == self.whoami
                                   else "replica")
            elif self.peer_state == "idle":
                self.peer_state = ("peering" if actp == self.whoami
                                   else "replica")
                changed = True     # first sight of our role: peer once
            if changed and actp != self.whoami:
                # primary-side recovery bookkeeping is meaningless on a
                # replica; keeping it would wedge cleanliness checks
                # and steer a future primary's reads forever
                self.peer_missing.clear()
            if changed and self.whoami not in \
                    set(self.acting) | set(self.up):
                # we are a STRAY for this PG now: nobody will ever push
                # our missing objects; drop the bookkeeping (and any
                # parked ops — the client retargets by map)
                self.missing.clear()
                self._missing_src.clear()
                self._missing_waiters.clear()
        if changed:
            # a new interval invalidates every reservation this PG
            # holds or waits on, in BOTH roles: the primary's round
            # restarts against the new acting set, and remote slots we
            # granted a (possibly dead) primary must not leak
            self._release_reservations()
            # a new interval invalidates this PG's HBM residency: the
            # resident copies were the OLD primary's view, and another
            # primary may have written while we were not it
            tier = getattr(self.daemon, "hbm_tier", None)
            if tier is not None:
                tier.drop_prefix(str(self.pgid))
        if changed and self.is_primary():
            self.daemon.queue_recovery(self)
        if not self.is_primary():
            # replicas don't gate anything locally; release waiters
            with self.lock:
                waiting, self.waiting_for_active = \
                    self.waiting_for_active, []
            for fn in waiting:
                fn()

    def active_for_write(self) -> bool:
        with self.lock:
            alive = sum(1 for o in self.acting if o != CRUSH_ITEM_NONE)
            return alive >= self.pool.min_size and self.is_primary() \
                and self.peer_state == "active"

    def active_for_read(self) -> bool:
        with self.lock:
            if self.peer_state != "active":
                return False
            alive = sum(1 for o in self.acting if o != CRUSH_ITEM_NONE)
            if self.pool.is_erasure():
                k = self.backend.codec.get_data_chunk_count()
                return alive >= k and self.is_primary()
            return self.is_primary()

    # -- cache tiering -------------------------------------------------

    def _tier(self):
        """Per-PG cache-tier state (osd/tiering.py), lazily attached —
        a pool becomes a tier via a map change after the PG exists.
        Creation is locked: the agent timer thread and the op-shard
        worker race here, and two PGTier instances would split the
        atime/hit-set/inflight state between them."""
        with self.lock:
            if self._tier_state is None:
                from .tiering import PGTier
                self._tier_state = PGTier(self)
            return self._tier_state

    def submit_internal_write(self, oid, t: PGTransaction,
                              logical_size, on_commit,
                              deleting: bool = False) -> bool:
        """Apply an OSD-internal mutation (promote install, dirty
        clear, evict, hit-set archive) through the normal replicated
        backend so replicas and the PG log stay consistent — the tier
        machinery must never write the store behind the log's back.

        Returns False WITHOUT submitting when this daemon is no longer
        the active primary: deferred tier work (an agent pass queued
        seconds ago) must not mint versions on a demoted primary's
        stale chain — a zombie agent could otherwise delete an object
        the NEW primary just rewrote."""
        with self.lock:
            if not self.is_primary() or self.peer_state != "active":
                return False
            self.last_version += 1
            version = self.last_version
        if not deleting:
            t.setattr(oid, VERSION_ATTR, str(version).encode())
            if logical_size is not None:
                t.setattr(oid, "_size", str(logical_size).encode())
        self.backend.submit_transaction(t, version, on_commit)
        return True

    # -- client op execution (PrimaryLogPG::do_op collapsed) -----------

    def do_op(self, msg, reply_fn) -> None:
        # per-principal attribution (osd/perf_query.py): wrap the
        # reply ONCE per op — do_op re-enters through missing-object
        # parking and waiting_for_active with the same msg+reply_fn,
        # and a second wrap would double-count the op
        pq = getattr(self.daemon, "perf_query", None)
        if pq is not None and pq.active \
                and not getattr(msg, "_pq_wrapped", False):
            msg._pq_wrapped = True
            reply_fn = pq.wrap_reply(
                msg, reply_fn,
                getattr(self.pool, "name", str(self.pgid.pool)),
                self.pgid)
        if not self.is_primary():
            reply_fn(-11, None)  # EAGAIN: wrong primary / not peered
            return
        # a retransmit of a write some past primary already committed
        # (the reqid rides the replicated log) replays its outcome —
        # the exactly-once guarantee must survive failover, not just
        # live in one daemon's memory
        session = getattr(msg, "session", "")
        if session:
            with self.lock:
                done_v = self._reqids.get((session, msg.tid))
            if done_v is not None:
                reply_fn(0, done_v)
                return
        # an object we know we're missing must be recovered before any
        # op touches it — serving the local copy would expose stale
        # bytes for an acked write (PrimaryLogPG wait_for_missing).
        # Register-and-return under ONE lock hold: a second check after
        # registering would race a concurrent push into running the op
        # twice (once via the waiter, once here).
        parked = False
        repull = None
        with self.lock:
            if msg.oid in self.missing:
                parked = True
                self._missing_waiters.setdefault(msg.oid, []).append(
                    lambda: self.do_op(msg, reply_fn))
                now = _time.monotonic()
                if now - self._pulling.get(msg.oid, -1e9) > 2.0:
                    self._pulling[msg.oid] = now
                    repull = self._missing_src.get(msg.oid)
        if parked:
            if repull is not None:
                self.send_to_osd(repull, MOSDPGPull(
                    pgid=self.pgid, from_osd=self.whoami,
                    shard=(self.my_shard() if self.pool.is_erasure()
                           else -1),
                    oid=msg.oid, map_epoch=self.map_epoch()))
            return
        # cache-tier interposition (PrimaryLogPG::maybe_handle_cache):
        # a tier-pool PG may promote, proxy, or answer the op itself —
        # unless the client pinned the op to this pool (IGNORE_CACHE).
        # The explicit cache control ops are tier ops by definition and
        # ignore the flag.
        from ..msg.message import OSD_FLAG_IGNORE_CACHE
        if self.pool.is_tier() and self.pool.cache_mode != "none" \
                and self.active_for_read():
            op0 = msg.ops[0][0] if msg.ops else ""
            if (not (getattr(msg, "flags", 0) & OSD_FLAG_IGNORE_CACHE)
                    or op0 in ("cache_flush", "cache_try_flush",
                               "cache_evict")):
                if self._tier().maybe_handle(msg, reply_fn):
                    return
        if any(op[0] == "call" for op in msg.ops):
            self._do_call_op(msg, reply_fn)
            return
        if msg.ops and msg.ops[0][0] in ("watch", "unwatch", "notify"):
            self._do_watch_ops(msg, reply_fn)
            return
        from ..msg.message import OSD_READ_OPS
        reads = [op for op in msg.ops if op[0] in OSD_READ_OPS]
        if reads and len(reads) == len(msg.ops):
            self._do_read_ops(msg, reply_fn)
            return
        if not self.active_for_write():
            # hold until peered enough (waiting_for_active)
            with self.lock:
                self.waiting_for_active.append(
                    lambda: self.do_op(msg, reply_fn))
            return
        self._do_write_ops(msg, reply_fn)

    def _do_call_op(self, msg, reply_fn) -> None:
        """Object-class exec (PrimaryLogPG do_osd_ops CEPH_OSD_OP_CALL).

        Classes need synchronous local reads, which EC pools cannot
        serve (objects_read_sync -EOPNOTSUPP, ecbackend.rst:79-83) —
        so, like the reference, cls is refused on erasure pools.
        """
        from .objclass import CLS_METHOD_WR, ClassHandler, MethodContext
        if self.pool.is_erasure():
            reply_fn(-95, None)  # EOPNOTSUPP
            return
        if len(msg.ops) != 1:
            # mixing exec with other ops in one message would silently
            # drop the rest; reject the vector outright
            reply_fn(-22, None)  # EINVAL
            return
        _, cls_name, method_name, indata = msg.ops[0]
        method = ClassHandler.instance().get_method(cls_name, method_name)
        if method is None:
            reply_fn(-95, None)  # unknown class/method (reference: same)
            return
        if method.flags & CLS_METHOD_WR and not self.active_for_write():
            with self.lock:
                self.waiting_for_active.append(
                    lambda: self.do_op(msg, reply_fn))
            return
        hctx = MethodContext(self, msg.oid)
        try:
            ret, out = method.fn(hctx, indata)
        except Exception:
            reply_fn(-5, None)
            return
        if ret != 0 or not hctx.wrote:
            reply_fn(ret, out)
            return
        if not method.flags & CLS_METHOD_WR:
            reply_fn(-1, None)  # EPERM: RD-only method tried to write
            return
        with self.lock:
            self.last_version += 1
            version = self.last_version
        if not hctx.removed:  # a version xattr would resurrect the object
            hctx.txn.setattr(msg.oid, VERSION_ATTR, str(version).encode())
        self.backend.submit_transaction(
            hctx.txn, version, lambda: reply_fn(ret, out))

    # -- watch / notify (PrimaryLogPG do_osd_op_watch + do_notify) -----

    def _do_watch_ops(self, msg, reply_fn) -> None:
        op = msg.ops[0]
        kind = op[0]
        oid = msg.oid
        if kind == "watch":
            cookie = op[1]
            addr = tuple(msg.from_addr) if msg.from_addr else None
            if addr is None:
                reply_fn(-22, None)
                return
            with self.lock:
                self.watchers.setdefault(oid, {})[cookie] = addr
            reply_fn(0, None)
            return
        if kind == "unwatch":
            with self.lock:
                self.watchers.get(oid, {}).pop(op[1], None)
            reply_fn(0, None)
            return
        # notify: fan out to every watcher, complete when all ack or
        # the timeout fires (Objecter notify linger semantics)
        payload = op[1] if len(op) > 1 else b""
        timeout = op[2] if len(op) > 2 else 3.0
        with self.lock:
            watchers = dict(self.watchers.get(oid, {}))
            self._notify_seq += 1
            notify_id = (self.whoami << 32) | self._notify_seq
        if not watchers:
            reply_fn(0, {"replies": {}, "timed_out": []})
            return
        state = {"waiting": set(watchers), "replies": {},
                 "reply_fn": reply_fn}
        with self.lock:
            self._notifies[notify_id] = state
        for cookie, addr in watchers.items():
            self.daemon.send_to_client(addr, MWatchNotify(
                pgid=self.pgid, oid=oid, cookie=cookie,
                notify_id=notify_id, payload=payload,
                from_osd=self.whoami))
        self.daemon.timer.add_event_after(
            timeout or 3.0, self._notify_timeout, notify_id)

    def handle_notify_ack(self, msg) -> None:
        with self.lock:
            state = self._notifies.get(msg.notify_id)
            if state is None:
                return
            state["waiting"].discard(msg.cookie)
            state["replies"][msg.cookie] = msg.reply
            done = not state["waiting"]
            if done:
                self._notifies.pop(msg.notify_id, None)
        if done:
            state["reply_fn"](0, {"replies": state["replies"],
                                  "timed_out": []})

    def _notify_timeout(self, notify_id: int) -> None:
        with self.lock:
            state = self._notifies.pop(notify_id, None)
        if state is not None:
            state["reply_fn"](0, {"replies": state["replies"],
                                  "timed_out": sorted(state["waiting"])})

    def _do_read_ops(self, msg, reply_fn) -> None:
        if not self.active_for_read():
            with self.lock:
                self.waiting_for_active.append(
                    lambda: self.do_op(msg, reply_fn))
            return
        op = msg.ops[0]
        kind = op[0]
        oid = msg.oid
        snap = getattr(msg, "snap", 0)
        if kind == "list_snaps":
            ss = self._load_snapset(oid)
            head_alive = (self._object_size(oid) is not None
                          and not self._is_whiteout(oid))
            reply_fn(0, {
                "seq": ss["seq"],
                "clones": [{"id": c, "snaps": ss["snaps"].get(c, []),
                            "size": ss["sizes"].get(c, 0)}
                           for c in sorted(ss["clones"])],
                "head_exists": head_alive})
            return
        if snap:
            resolved = self._resolve_snap(oid, snap)
            if resolved is None or (
                    resolved == oid and (self._is_whiteout(oid)
                                         or self._object_size(oid)
                                         is None)):
                reply_fn(-2, None)   # did not exist at that snap
                return
            oid = resolved
        elif self._is_whiteout(oid) and kind in ("read", "stat",
                                                 "getxattr",
                                                 "getxattrs",
                                                 "omap_get"):
            reply_fn(-2, None)       # tombstone reads as absent
            return
        if kind == "stat":
            size = self._object_size(oid)
            if size is None:
                reply_fn(-2, None)
            else:
                reply_fn(0, {"size": size})
            return
        if kind == "getxattr":
            cid = self.cid_of_shard(self.my_shard())
            try:
                reply_fn(0, self.store.getattr(cid, oid, op[1]))
            except KeyError:
                reply_fn(-2, None)
            return
        if kind == "getxattrs":
            # CEPH_OSD_OP_GETXATTRS: every USER xattr
            cid = self.cid_of_shard(self.my_shard())
            try:
                attrs = self.store.getattrs(cid, oid)
            except KeyError:
                reply_fn(-2, None)
                return
            reply_fn(0, user_xattrs(attrs))
            return
        if kind == "omap_get":
            cid = self.cid_of_shard(self.my_shard())
            try:
                reply_fn(0, self.store.omap_get(cid, oid))
            except KeyError:
                reply_fn(-2, None)
            return
        if kind == "copy_get":
            self._do_copy_get(oid, reply_fn)
            return
        if kind == "list":
            from .tiering import HITSET_PREFIX
            cid = self.cid_of_shard(self.my_shard())
            reply_fn(0, [o for o in self.store.list_objects(cid)
                         if o != META_OID and not is_clone_oid(o)
                         and not (isinstance(o, str)
                                  and o.startswith(HITSET_PREFIX))])
            return
        # read (off, len)
        size = self._object_size(oid)
        if size is None:
            reply_fn(-2, None)
            return
        off, length = op[1], op[2]
        # clamp to the LOGICAL size: the EC backend's hinfo only knows
        # padded chunk-stream bounds (object_info_t.size analog)
        if length == 0:
            length = max(0, size - off)
        else:
            length = max(0, min(length, size - off))
        if length == 0:
            reply_fn(0, b"")
            return
        self._ec_read_with_retry(oid, off, length, reply_fn,
                                 trace=getattr(msg, "trace", None))

    def _do_copy_get(self, oid, reply_fn, tries: int = 0) -> None:
        """CEPH_OSD_OP_COPY_GET (the promote/copy-from fetch,
        src/osd/PrimaryLogPG.cc do_osd_ops COPY_GET): one op returning
        a CONSISTENT (data, user xattrs, omap, version) snapshot.
        Replicated pools read inline on the op-shard worker (writes
        serialize there, so the view is atomic); EC pools read data
        asynchronously, so the version is re-checked afterward and the
        fetch retried if a write landed in between."""
        size = self._object_size(oid)
        if size is None or self._is_whiteout(oid):
            reply_fn(-2, None)
            return
        v0 = self._object_version(oid)
        cid = self.cid_of_shard(self.my_shard())
        try:
            attrs = user_xattrs(self.store.getattrs(cid, oid))
        except KeyError:
            attrs = {}
        try:
            omap = dict(self.store.omap_get(cid, oid))
        except KeyError:
            omap = {}
        # the object's recent client reqids ride along (the reference
        # COPY_GET's reqids field): after a promote, the cache PG can
        # recognize a retransmit of a write the BASE pool already
        # applied — without this, a lost reply + resend across a tier
        # transition double-applies non-idempotent ops
        with self.lock:
            reqids = [(list(e.reqid), e.version)
                      for e in self.pg_log.entries
                      if e.oid == oid and e.reqid[0]]

        def finish(data):
            if data is None:
                reply_fn(-5, None)
                return
            if self._object_version(oid) != v0:
                if tries < 5:       # a write raced the async read
                    self._do_copy_get(oid, reply_fn, tries + 1)
                else:
                    reply_fn(-11, None)   # EAGAIN: hot object
                return
            reply_fn(0, {"data": bytes(data), "attrs": attrs,
                         "omap": omap, "version": v0,
                         "reqids": reqids})

        if size == 0:
            finish(b"")
        elif self.pool.is_erasure():
            self.backend.objects_read(oid, 0, size, finish)
        else:
            try:
                finish(self.store.read(self._head_cid(), oid))
            except KeyError:
                reply_fn(-2, None)

    def _ec_read_with_retry(self, oid, off, length, reply_fn,
                            attempt: int = 0, trace=None) -> None:
        """Reconstruction shortages are usually TRANSIENT (a shard
        mid-recovery is excluded from reads until its push commits):
        retry briefly before failing, like the reference holds ops on
        degraded objects instead of erroring (wait_for_degraded)."""
        def on_data(data):
            if data is not None:
                reply_fn(0, data)
            elif attempt < 20:
                self.daemon.timer.add_event_after(
                    0.5, self._ec_read_with_retry, oid, off, length,
                    reply_fn, attempt + 1, trace)
            else:
                reply_fn(-5, None)
        self.backend.objects_read(oid, off, length, on_data,
                                  trace=trace)

    def _object_size(self, oid):
        if self.pool.is_erasure():
            h = self.backend.get_hinfo(oid)
            if h.get_total_chunk_size() == 0:
                # distinguish empty object from absent
                st = self.store.stat(self.cid_of_shard(self.my_shard()),
                                     oid)
                return 0 if st is not None else None
            # logical size tracked via size xattr for exactness
            raw = self.local_getattr(oid, "_size")
            if raw is not None:
                return int(raw)
            return h.get_total_logical_size(self.backend.sinfo)
        st = self.store.stat(self.cid_of_shard(-1), oid)
        return st["size"] if st is not None else None

    # -- snapshots (PrimaryLogPG make_writeable / snapset machinery) ---

    def _load_snapset(self, oid) -> dict:
        raw = self.local_getattr(oid, SNAPSET_ATTR)
        if raw:
            try:
                return encoding.decode_any(raw)
            except encoding.DecodeError:
                pass
        return {"seq": 0, "clones": [], "snaps": {}, "sizes": {}}

    def _is_whiteout(self, oid) -> bool:
        return self.local_getattr(oid, WHITEOUT_ATTR) is not None

    def _head_cid(self):
        return self.cid_of_shard(-1)

    def _snap_capture_needed(self, oid, snapc) -> bool:
        """Will make_writeable need the head's BYTES? (EC pools must
        pre-read them through the backend before planning the write.)"""
        if not snapc or not snapc[0]:
            return False
        if self._object_size(oid) is None or self._is_whiteout(oid):
            return False
        ss = self._load_snapset(oid)
        seq, snaps = snapc[0], list(snapc[1] or ())
        return bool([s for s in snaps if s > ss["seq"]]) \
            and seq > ss["seq"]

    def make_writeable(self, t: PGTransaction, oid, snapc,
                       head_data: bytes | None = None) -> None:
        """Before the first mutation of a write whose SnapContext names
        snaps newer than the newest clone, preserve the current head as
        a clone covering them (PrimaryLogPG::make_writeable,
        PrimaryLogPG.cc around :3151 execute_ctx). The clone is emitted
        as captured bytes (not a store-level clone op) so it is
        pre-mutation by construction and replicas apply it
        deterministically — and on EC pools the captured clone encodes
        through the normal write path like any object (head_data is the
        pre-read logical content the caller gathered via the backend).

        Returns the in-flight snapset (so later ops in the SAME
        transaction see the new clone), or None when nothing was
        preserved."""
        if not snapc or not snapc[0]:
            return None
        seq, snaps = snapc[0], list(snapc[1] or ())
        size = self._object_size(oid)
        if size is None or self._is_whiteout(oid):
            # the object is being BORN under this SnapContext: stamp
            # the snapset seq so snap reads older than its birth
            # resolve to "did not exist" (object_info/snapset seq
            # semantics), keeping any clones a prior life left behind
            ss = self._load_snapset(oid)
            if seq > ss["seq"]:
                ss["seq"] = seq
                t.setattr(oid, SNAPSET_ATTR, encoding.encode_any(ss))
                return ss
            return None            # no head to preserve
        if not self._snap_capture_needed(oid, snapc):
            return None            # the ONE capture predicate
        ss = self._load_snapset(oid)
        new_snaps = sorted(s for s in snaps if s > ss["seq"])
        if self.pool.is_erasure() and head_data is None:
            # the pre-read didn't arrive (predicate/state drift): skip
            # the clone rather than read the dataless EC head cid
            return None
        cname = clone_name(oid, seq)
        if head_data is not None:
            data = head_data
            cid = self.cid_of_shard(self.my_shard())
        else:
            cid = self._head_cid()
            data = self.store.read(cid, oid)
        t.create(cname)
        if data:
            t.write(cname, 0, data)
        t.setattr(cname, VERSION_ATTR,
                  str(self._object_version(oid)).encode())
        t.setattr(cname, "_size", str(size).encode())
        try:
            omap = self.store.omap_get(cid, oid)
        except KeyError:
            omap = {}
        if omap:
            t.omap_setkeys(cname, omap)
        ss["clones"].append(seq)
        ss["clones"].sort()
        ss["snaps"][seq] = new_snaps
        ss["sizes"][seq] = size
        ss["seq"] = seq
        t.setattr(oid, SNAPSET_ATTR, encoding.encode_any(ss))
        return ss

    def _resolve_snap(self, oid, snap: int):
        """Which stored object serves reads at snap id `snap`?
        Clone c covers snaps in (previous clone, c]; newer than the
        newest clone reads from head — unless the head was born after
        the snap (snapset seq > snap with no covering clone), which is
        'did not exist then': None."""
        ss = self._load_snapset(oid)
        for c in sorted(ss["clones"]):
            if c >= snap:
                covered = ss["snaps"].get(c, [])
                if covered and snap < min(covered):
                    # the clone's coverage starts after `snap`: the
                    # object was born between them — did not exist
                    return None
                return clone_name(oid, c)
        if ss["seq"] >= snap:
            # no covering clone and the head's (re)birth context
            # already included `snap`: the object did not exist then
            # (a write under snapc seq S postdates every snap <= S)
            return None
        return oid                  # head

    def trim_snaps(self, removed: list) -> None:
        """Drop removed snaps from clone coverage; clones covering
        nothing are deleted (snap trimming; each OSD trims its own
        store deterministically from the map's removed_snaps). EC
        shard collections trim independently — the snapset xattr is
        replicated to every shard."""
        if not removed:
            return
        removed = set(removed)
        cids = ([self._head_cid()] if not self.pool.is_erasure()
                else [self.cid_of_shard(s)
                      for s in range(self.pool.size)])
        for cid in cids:
            self._trim_snaps_cid(cid, removed)

    def _trim_snaps_cid(self, cid, removed: set) -> None:
        for oid in list(self.store.list_objects(cid)):
            if is_clone_oid(oid) or oid == META_OID:
                continue
            raw = None
            try:
                raw = self.store.getattr(cid, oid, SNAPSET_ATTR)
            except KeyError:
                continue
            if not raw:
                continue
            try:
                ss = encoding.decode_any(raw)
            except encoding.DecodeError:
                continue
            dirty = False
            txn = Transaction()
            for c in list(ss["clones"]):
                keep = [s for s in ss["snaps"].get(c, [])
                        if s not in removed]
                if keep != ss["snaps"].get(c, []):
                    dirty = True
                if keep:
                    ss["snaps"][c] = keep
                else:
                    ss["clones"].remove(c)
                    ss["snaps"].pop(c, None)
                    ss["sizes"].pop(c, None)
                    txn.remove(cid, clone_name(oid, c))
            if dirty:
                try:
                    wout = self.store.getattr(
                        cid, oid, WHITEOUT_ATTR) is not None
                except KeyError:
                    wout = False
                if not ss["clones"] and wout:
                    # nothing references the whiteout anymore
                    txn.remove(cid, oid)
                else:
                    txn.setattr(cid, oid, SNAPSET_ATTR,
                                encoding.encode_any(ss))
                self.store.queue_transaction(txn)

    def _do_write_ops(self, msg, reply_fn) -> None:
        """EC pools read asynchronously, so snapshot captures (COW of
        the pre-write head, rollback source content) pre-read through
        the backend before the write is planned; replicated pools read
        their local store inline."""
        snapc = getattr(msg, "snapc", (0, ()))
        mutates = any(op[0] in ("write", "writefull", "append", "zero",
                                "truncate", "remove", "rollback")
                      for op in msg.ops)
        if not (self.pool.is_erasure() and mutates):
            self._plan_write_ops(msg, reply_fn, {})
            return
        # EC: mutations on one object run one at a time so the async
        # pre-read can never capture a head another in-flight write is
        # changing (the EC backend pipeline then keeps submit order)
        from collections import deque

        def run():
            self._ec_write_with_prereads(msg, reply_fn)

        with self.lock:
            q = self._obj_gate.setdefault(msg.oid, deque())
            q.append(run)
            if len(q) > 1:
                return             # a predecessor will run us
        run()

    def _release_obj_gate(self, oid) -> None:
        nxt = None
        with self.lock:
            q = self._obj_gate.get(oid)
            if q:
                q.popleft()
                if q:
                    nxt = q[0]
                else:
                    self._obj_gate.pop(oid, None)
        if nxt is not None:
            nxt()

    def _ec_write_with_prereads(self, msg, reply_fn) -> None:
        snapc = getattr(msg, "snapc", (0, ()))
        needs: list = []
        if self._snap_capture_needed(msg.oid, snapc):
            needs.append(msg.oid)
        for op in msg.ops:
            if op[0] == "rollback":
                src_oid = self._resolve_snap(msg.oid, op[1])
                if src_oid not in (None, msg.oid):
                    needs.append(src_oid)

        # The gate must stay held until the write COMMITS, not merely
        # until it is planned/submitted: the snapset update rides the
        # async shard transactions, so a successor entering the gate
        # pre-commit would read a stale snapset and capture a second
        # clone from a post-write head (PrimaryLogPG holds the
        # ObjectContext rw-lock across make_writeable -> commit the
        # same way, PrimaryLogPG.cc:5197-5311).
        released = [False]

        def release_once():
            with self.lock:
                if released[0]:
                    return
                released[0] = True
            self._release_obj_gate(msg.oid)

        def finish(result, data):
            try:
                reply_fn(result, data)
            finally:
                release_once()

        def plan(pre):
            try:
                self._plan_write_ops(msg, finish, pre)
            except Exception:
                # fail the op rather than unwind into the backend's
                # read-completion / timer context (finish releases the
                # gate); the client sees EIO instead of a 30s timeout
                logging.getLogger("ceph_tpu.osd").exception(
                    "EC write planning failed for %r", msg.oid)
                finish(-5, None)

        if not needs:
            plan({})
            return
        pre: dict = {}

        def read_next(i: int, attempt: int = 0) -> None:
            if i == len(needs):
                plan(pre)
                return
            roid = needs[i]
            size = self._object_size(roid)
            if size is None:
                finish(-2, None)   # pre-read source vanished
                return

            def on_data(data, roid=roid, i=i):
                if data is None:
                    # degraded below k / reconstruction failed: b""
                    # here would snapshot or roll back to EMPTY content
                    # and ack it. Usually TRANSIENT (shard mid-recovery
                    # excluded from reads): retry briefly, then error
                    if attempt < 10:
                        self.daemon.timer.add_event_after(
                            0.5, read_next, i, attempt + 1)
                    else:
                        finish(-5, None)
                    return
                pre[roid] = bytes(data)
                read_next(i + 1)

            if size == 0:
                on_data(b"")
            else:
                self.backend.objects_read(
                    roid, 0, size, on_data,
                    trace=getattr(msg, "trace", None))

        read_next(0)

    def _plan_write_ops(self, msg, reply_fn, pre: dict) -> None:
        t = PGTransaction()
        oid = msg.oid
        snapc = getattr(msg, "snapc", (0, ()))
        mutates = any(op[0] in ("write", "writefull", "append", "zero",
                                "truncate", "remove", "rollback")
                      for op in msg.ops)
        ss_inflight = None
        if mutates:
            ss_inflight = self.make_writeable(t, oid, snapc,
                                              head_data=pre.get(oid))
        if self._is_whiteout(oid):
            # recreating over a whiteout: clear the tombstone, keep ss
            if any(op[0] in ("create", "write", "writefull", "append")
                   for op in msg.ops):
                t.rmattr(oid, WHITEOUT_ATTR)
        logical_size = self._object_size(oid) or 0
        for op in msg.ops:
            kind = op[0]
            if kind == "create":
                t.create(oid)
            elif kind == "write":
                t.write(oid, op[1], op[2])
                logical_size = max(logical_size, op[1] + len(op[2]))
            elif kind == "writefull":
                # CEPH_OSD_OP_WRITEFULL replaces the DATA only: xattrs
                # (snapset!) and omap persist (do_osd_ops WRITEFULL is
                # truncate+write, not delete+create — a remove here
                # would wipe the head's snapset whenever a later writer
                # needs no capture, losing every existing clone).
                # Earlier data ops in the SAME transaction are
                # superseded wholesale — including a whiteout marker a
                # preceding remove queued (the object is being reborn).
                t.reset_data(oid)
                t.drop_attr_update(oid, WHITEOUT_ATTR)
                if self._object_size(oid) is not None:
                    t.truncate(oid, 0)
                t.create(oid)
                t.write(oid, 0, op[1])
                logical_size = len(op[1])
            elif kind == "append":
                t.write(oid, logical_size, op[1])
                logical_size += len(op[1])
            elif kind == "zero":
                t.zero(oid, op[1], op[2])
            elif kind == "truncate":
                t.truncate(oid, op[1])
                logical_size = op[1]
            elif kind == "remove":
                ss = ss_inflight or self._load_snapset(oid)
                if ss["clones"] or self.pool.is_tier():
                    # live clones still reference the snapset — and a
                    # cache tier must REMEMBER deletions so the flush
                    # propagates them to the base pool: leave a
                    # whiteout tombstone instead of erasing it
                    # (PrimaryLogPG whiteout semantics)
                    t.truncate(oid, 0)
                    t.setattr(oid, WHITEOUT_ATTR, b"1")
                else:
                    t.remove(oid)
                logical_size = 0
            elif kind == "rollback":
                # CEPH_OSD_OP_ROLLBACK: head becomes the clone that
                # serves snap op[1]; rolling back to head is a no-op
                src = self._resolve_snap(oid, op[1])
                if src is None:
                    # the object did not exist at that snap: rollback
                    # means delete (whiteout if clones remain)
                    ss = ss_inflight or self._load_snapset(oid)
                    if ss["clones"]:
                        t.truncate(oid, 0)
                        t.setattr(oid, WHITEOUT_ATTR, b"1")
                    else:
                        t.remove(oid)
                    logical_size = 0
                elif src != oid:
                    if src in pre:
                        data = pre[src]     # EC: pre-read via backend
                    else:
                        cid = self._head_cid()
                        try:
                            data = self.store.read(cid, src)
                        except KeyError:
                            reply_fn(-2, None)
                            return
                    ss = ss_inflight or self._load_snapset(oid)
                    t.remove(oid)
                    t.create(oid)
                    if data:
                        t.write(oid, 0, data)
                    t.setattr(oid, SNAPSET_ATTR,
                              encoding.encode_any(ss))
                    logical_size = len(data)
                elif self._is_whiteout(oid) or \
                        self._object_size(oid) is None:
                    reply_fn(-2, None)
                    return
            elif kind == "setxattr":
                t.setattr(oid, op[1], op[2])
            elif kind == "rmxattr":
                t.rmattr(oid, op[1])
            elif kind == "resetxattrs":
                # drop every USER xattr — persisted AND ones queued
                # earlier in this same op vector (the metadata-
                # replacement leg of a tier flush: copy-from
                # semantics, the base must not keep attrs the cache
                # deleted)
                cid = self.cid_of_shard(self.my_shard())
                try:
                    names = set(self.store.getattrs(cid, oid))
                except KeyError:
                    names = set()
                pending = t.op_map.get(oid)
                if pending is not None:
                    names.update(k for k, v in
                                 pending.attr_updates.items()
                                 if v is not None)
                for name in names:
                    if is_user_xattr(name):
                        t.rmattr(oid, name)
            elif kind == "omap_set":
                t.omap_setkeys(oid, op[1])
            elif kind == "omap_rm":
                t.omap_rmkeys_op(oid, op[1])
            elif kind == "omap_clear":
                # CEPH_OSD_OP_OMAPCLEAR: persisted keys AND any queued
                # by an earlier omap_set in this op vector
                cid = self.cid_of_shard(self.my_shard())
                try:
                    keys = set(self.store.omap_get(cid, oid))
                except KeyError:
                    keys = set()
                pending = t.op_map.get(oid)
                if pending is not None:
                    keys.update(pending.omap_updates)
                if keys:
                    t.omap_rmkeys_op(oid, sorted(keys))
            else:
                reply_fn(-95, None)  # EOPNOTSUPP
                return
        with self.lock:
            self.last_version += 1
            version = self.last_version
        # version + logical size ride as xattrs on every shard; a
        # whiteout tombstone still exists physically and keeps them
        head_op = t.op_map.get(oid)
        still_exists = head_op is None or not head_op.is_delete()
        if still_exists:
            t.setattr(oid, VERSION_ATTR, str(version).encode())
            t.setattr(oid, "_size", str(logical_size).encode())
            if self.pool.is_tier() and \
                    self.pool.cache_mode in ("writeback", "readproxy"):
                # cache-tier dirty bit (object_info_t FLAG_DIRTY): the
                # agent flushes this object back to the base pool.
                # EVERY write message dirties — metadata-only ops
                # (rmxattr, omap_rm) included, or a deleted attr would
                # never flush and would resurrect from the base copy
                from .tiering import DIRTY_ATTR
                t.setattr(oid, DIRTY_ATTR, b"1")
                self._tier().dirty_at.setdefault(oid, _time.monotonic())
        self.backend.submit_transaction(
            t, version, lambda: reply_fn(0, version),
            reqid=(getattr(msg, "session", ""), msg.tid),
            trace=getattr(msg, "trace", None))

    # -- peering: GetInfo / GetLog / GetMissing ------------------------

    def start_recovery(self) -> None:
        """Entry point from the recovery queue: run the peering rounds
        (log-based convergence), then scan-backfill any peer whose log
        does not overlap.

        Peering storm control (ISSUE 19): when the daemon's peering
        gate is on, peering itself queues for a slot on the "peering"
        AsyncReserver — a map-churn burst re-peers at most
        osd_peering_max_active PGs concurrently instead of flooding
        the op queue with a thousand simultaneous info exchanges."""
        if not self.is_primary():
            return
        res = self._peering_reserver()
        if res is None:
            self.start_peering()
            return
        with self.lock:
            # the grant callback re-reads this, so a newer interval's
            # start_recovery retargets an already-queued request
            # (request_reservation ignores the duplicate item)
            self._peering_want = self.interval
        self._peering_slot = True
        res.request_reservation(str(self.pgid),
                                self._peering_granted,
                                _RESV_PRIO["peering"])

    def _peering_reserver(self):
        """The daemon's peering-slot reserver, or None when the gate
        is off (osd_peering_max_active=0) or the PG runs against a
        stub daemon — None short-circuits to ungated peering."""
        if not getattr(self.daemon, "peering_gate", False):
            return None
        reservers = self._reservers()
        if reservers is None:
            return None
        return reservers.get("peering")

    def _peering_granted(self) -> None:
        """Slot granted: run peering on the op queue's recovery class,
        never inline — the grant callback fires on whatever thread
        released the previous holder's slot."""
        queue = getattr(self.daemon, "op_wq", None)
        if queue is None:
            self._run_gated_peering()
            return
        queue.queue(self.pgid, self._run_gated_peering,
                    klass="recovery",
                    priority=getattr(self.daemon,
                                     "recovery_op_priority", 5))

    def _run_gated_peering(self) -> None:
        with self.lock:
            stale = (getattr(self, "_peering_want", -1)
                     != self.interval
                     or self.acting_primary != self.whoami)
        if stale:
            # the interval moved while we queued: the map change that
            # moved it already re-queued recovery, so just give the
            # slot back
            self._release_peering_slot()
            return
        self.start_peering()

    def _release_peering_slot(self) -> None:
        res = self._peering_reserver()
        if res is None or not getattr(self, "_peering_slot", False):
            return
        self._peering_slot = False
        res.cancel_reservation(str(self.pgid))

    def _my_info(self) -> dict:
        with self.lock:
            return {"osd": self.whoami,
                    "last_update": list(self.pg_log.head),
                    "log_tail": list(self.pg_log.tail)}

    def osds_missing_object(self, oid) -> set:
        """OSDs whose shard of `oid` is known-stale (their recovery
        push has not committed): reads must reconstruct around them."""
        with self.lock:
            bad = set(self.peer_missing.get(oid, ()))
            if oid in self.missing:
                bad.add(self.whoami)
            return bad

    def start_peering(self) -> None:
        with self.lock:
            self.peer_state = "peering"
            self._peer_seq += 1
            seq = self._peer_seq
            # wall-clock start for the ceph_pg_peering_seconds lane
            self._peer_t0 = _time.monotonic()
            self._peer_infos = {self.whoami: self._my_info()}
            # a new interval recomputes who is missing what: replicas
            # re-report after activation (handle_log missing notify)
            self.peer_missing.clear()
            self.backfilling.clear()
            targets = {osd for osd in set(self.up) | set(self.acting)
                       if osd not in (CRUSH_ITEM_NONE, self.whoami)}
            self._peer_wait = set(targets)
        if not targets:
            self._choose_authoritative(seq)
            return
        for osd in targets:
            self.send_to_osd(osd, MOSDPGQuery(
                pgid=self.pgid, from_osd=self.whoami, what="info",
                map_epoch=self.map_epoch()))
        # peers that never answer must not wedge the PG: after the
        # grace, proceed with whoever responded (they re-peer via a
        # later map change / backfill when they return)
        self.daemon.timer.add_event_after(
            0.5, self._peering_retry, seq, 0)

    def _peer_quorum(self) -> int:
        """How many infos (self included) we must hold before
        activating: enough that the responder set provably intersects
        ANY set that could have acked a write in a prior interval (the
        role of the reference's prior-interval maybe_went_rw gate).
        An ack set has >= min_size members out of `size`, so
        intersection needs responders > size - min_size, i.e.
        size - min_size + 1 — for size=3/min_size=2 that is 2; for
        size=2/min_size=1 it is 2 (both, the price of min_size=1).
        EC additionally needs k responders to reconstruct anything."""
        need = self.pool.size - min(self.pool.min_size,
                                    self.pool.size) + 1
        if self.pool.is_erasure():
            need = max(need, self.backend.codec.get_data_chunk_count())
        return min(need, self.pool.size)

    def _peering_retry(self, seq: int, attempt: int) -> None:
        with self.lock:
            if seq != self._peer_seq or self.peer_state != "peering":
                return
            waiting = set(self._peer_wait)
            if not waiting:
                return
            if attempt >= 2 and \
                    len(self._peer_infos) >= self._peer_quorum():
                # enough of the prior world answered: any acked write
                # is represented among the responders — proceed
                self._peer_wait = set()
                go = True
            else:
                go = False
        if go:
            self._choose_authoritative(seq)
            return
        # not safe to proceed (the acked state might live only on the
        # silent peers): keep asking — the PG stays inactive, exactly
        # like the reference's down/incomplete states, until enough
        # peers return or a map change restarts peering
        if attempt >= 2:
            # wedged on silent peers: give the peering slot back so an
            # incomplete PG can't pin the storm-control lane while it
            # waits (possibly forever) for the dead peers to return
            self._release_peering_slot()
        for osd in waiting:
            self.send_to_osd(osd, MOSDPGQuery(
                pgid=self.pgid, from_osd=self.whoami, what="info",
                map_epoch=self.map_epoch()))
        self.daemon.timer.add_event_after(
            0.5, self._peering_retry, seq, attempt + 1)

    def handle_query(self, msg) -> None:
        """Peer side of GetInfo/GetLog."""
        if msg.what == "info":
            self.send_to_osd(msg.from_osd, MOSDPGNotify(
                pgid=self.pgid, from_osd=self.whoami,
                info=self._my_info(), map_epoch=self.map_epoch()))
            return
        if msg.what == "log":
            since = tuple(msg.since)
            with self.lock:
                if self.pg_log.overlaps(since):
                    entries = [(e.epoch, e.version, e.oid, e.kind,
                                e.prior_version)
                               for e in self.pg_log.entries_since(since)]
                    contiguous = True
                else:
                    entries = self.pg_log.dump()
                    contiguous = False
                head = list(self.pg_log.head)
            self.send_to_osd(msg.from_osd, MOSDPGLog(
                pgid=self.pgid, from_osd=self.whoami, entries=entries,
                head=head, contiguous=contiguous,
                info=self._my_info(), map_epoch=self.map_epoch()))

    def handle_notify(self, msg) -> None:
        """Primary side: a peer's info (GetInfo reply) or its missing
        set (GetMissing leg, after it merged our activation log) —
        distinguished by the kind flag, because an EMPTY missing
        report must not masquerade as an info reply."""
        if getattr(msg, "kind", "info") == "recovered":
            # a peer applied its recovery push: its shard is clean
            # again and may serve reads
            with self.lock:
                for oid in msg.missing:
                    peers = self.peer_missing.get(oid)
                    if peers is not None:
                        peers.discard(msg.from_osd)
                        if not peers:
                            self.peer_missing.pop(oid, None)
                    backf = self.backfilling.get(oid)
                    if backf is not None:
                        backf.discard(msg.from_osd)
                        if not backf:
                            self.backfilling.pop(oid, None)
            # a drained lane gives its reservation slots back
            self._maybe_release_reservations()
            return
        if getattr(msg, "kind", "info") == "missing":
            shards = self.acting_shards()
            shard = next((s for s, o in shards.items()
                          if o == msg.from_osd), -1)
            if self.pool.is_erasure() and shard == -1:
                # a STRAY's report (the peer is no longer in the acting
                # set): it holds no shard to recover — ignore; the
                # stray clears its own state on its next map update
                return
            if not self.pool.is_erasure():
                if msg.from_osd not in set(self.acting) | set(self.up):
                    return
                shard = -1
            with self.lock:
                for oid in msg.missing:
                    self.peer_missing.setdefault(oid, set()).add(
                        msg.from_osd)
            for oid in msg.missing:
                self._push_object(oid, shard, msg.from_osd)
            return
        proceed = False
        with self.lock:
            if self.peer_state != "peering":
                return
            seq = self._peer_seq
            self._peer_infos[msg.from_osd] = dict(msg.info)
            self._peer_wait.discard(msg.from_osd)
            proceed = not self._peer_wait
        if proceed:
            self._choose_authoritative(seq)

    def _choose_authoritative(self, seq: int) -> None:
        """GetLog: the highest last_update owns history."""
        with self.lock:
            if seq != self._peer_seq or self.peer_state != "peering":
                return
            if len(self._peer_infos) < self._peer_quorum():
                return   # unsafe: acked state may be on silent peers
            infos = dict(self._peer_infos)
            my_head = self.pg_log.head
        best_osd, best_lu = self.whoami, my_head
        for osd, info in infos.items():
            lu = tuple(info.get("last_update", (0, 0)))
            if lu > best_lu:
                best_osd, best_lu = osd, lu
        if best_osd == self.whoami:
            self._activate(seq)
            return
        with self.lock:
            # only THIS peer's reply may serve as the authoritative
            # log for this round — a delayed MOSDPGLog from an old
            # interval must not short-circuit peering
            self._getlog_from = best_osd
        self.send_to_osd(best_osd, MOSDPGQuery(
            pgid=self.pgid, from_osd=self.whoami, what="log",
            since=tuple(my_head), map_epoch=self.map_epoch()))
        # the authoritative peer may die mid-GetLog: re-run the rounds
        # (if its extra entries were acked they live on another
        # responder too; if not, they were never acknowledged)
        self.daemon.timer.add_event_after(
            1.5, self._getlog_timeout, seq)

    def _getlog_timeout(self, seq: int) -> None:
        with self.lock:
            if seq != self._peer_seq or self.peer_state != "peering":
                return
        self.start_peering()

    def handle_log(self, msg) -> None:
        """A log segment arrived: on a peering primary this is the
        authoritative GetLog reply; on a replica it is the activation
        delta from the primary."""
        entries = [entry_from_tuple(r) for r in msg.entries]
        if self.is_primary():
            with self.lock:
                if self.peer_state != "peering":
                    return
                if msg.from_osd != getattr(self, "_getlog_from", None):
                    return   # not the authoritative reply we asked for
                self._getlog_from = None
                seq = self._peer_seq
                updates, divergent = self.pg_log.merge(
                    entries, tuple(msg.head))
                self.last_version = max(self.last_version,
                                        self.pg_log.head[1])
            self._persist_log_full()
            self._rebuild_reqids()
            self._apply_log_updates(updates, msg.from_osd, divergent)
            self._activate(seq)
            return
        # replica: merge, then report what we now know we're missing
        with self.lock:
            updates, divergent = self.pg_log.merge(entries,
                                                   tuple(msg.head))
            self.last_version = max(self.last_version,
                                    self.pg_log.head[1])
        if entries or updates or divergent:
            # a caught-up replica's empty activation delta (sent so it
            # re-reports missing) must not cost a full log rewrite
            self._persist_log_full()
            self._rebuild_reqids()
        self._apply_log_updates(updates, msg.from_osd, divergent,
                                pull=False)
        # report the FULL outstanding missing map, not just newly-
        # discovered entries: a report sent while the primary still saw
        # us as a stray was ignored, and re-activation may deliver no
        # new log entries — without the full set, those objects would
        # never be pushed
        with self.lock:
            need = set(self.missing)
        self.send_to_osd(msg.from_osd, MOSDPGNotify(
            pgid=self.pgid, from_osd=self.whoami, missing=sorted(need),
            kind="missing", map_epoch=self.map_epoch()))

    def _apply_log_updates(self, updates: dict, source_osd: int,
                           divergent: set = frozenset(),
                           pull: bool = True) -> set:
        """Act on a merge result: version 0 means the object must not
        exist here (divergent create / authoritative delete) — remove
        it; a positive version goes into `missing` and (on the
        primary) is pulled from the authoritative peer. A DIVERGENT
        local copy is dropped first: its version xattr was minted by a
        dead-interval fork and must never win a version comparison
        against the authoritative copy. Returns the set of oids still
        missing locally."""
        need: set = set()
        my_shard = self.my_shard() if self.pool.is_erasure() else -1
        for oid, version in sorted(updates.items()):
            if version == 0 or oid in divergent:
                txn = Transaction()
                if self.pool.is_erasure():
                    for s in range(self.pool.size):
                        txn.remove(self.cid_of_shard(s), oid)
                else:
                    txn.remove(self.cid_of_shard(-1), oid)
                self.store.queue_transaction(txn)
                with self.lock:
                    self.missing.pop(oid, None)
                if version == 0:
                    continue
            if self._object_version(oid) >= version:
                continue            # already have it (or newer)
            need.add(oid)
            with self.lock:
                self.missing[oid] = version
                self._missing_src[oid] = source_osd
            if pull and source_osd != self.whoami:
                self._pulling[oid] = _time.monotonic()
                self.send_to_osd(source_osd, MOSDPGPull(
                    pgid=self.pgid, from_osd=self.whoami,
                    shard=my_shard, oid=oid,
                    map_epoch=self.map_epoch()))
        return need

    def _activate(self, seq: int) -> None:
        """Activation: ship every known peer the log delta it lacks
        (replicas merge + report missing), fall back to scan backfill
        for non-overlapping peers, release held client ops."""
        with self.lock:
            if seq != self._peer_seq or self.peer_state != "peering":
                return
            self.peer_state = "active"
            infos = dict(self._peer_infos)
            waiting, self.waiting_for_active = \
                self.waiting_for_active, []
            head = self.pg_log.head
            t0 = getattr(self, "_peer_t0", None)
        # peering done: free the storm-control slot and feed the
        # duration histogram (ceph_pg_peering_seconds p99)
        self._release_peering_slot()
        note = getattr(self.daemon, "note_peering_done", None)
        if note is not None and t0 is not None:
            note(_time.monotonic() - t0)
        shards = self.acting_shards()
        backfill = []
        for osd, info in infos.items():
            if osd == self.whoami:
                continue
            peer_lu = tuple(info.get("last_update", (0, 0)))
            if peer_lu == head:
                # log-caught-up, but the peer may still hold a missing
                # map whose earlier report was dropped or ignored
                # (e.g. it arrived while our lagging map saw the peer
                # as a stray) — an EMPTY activation delta makes it
                # re-report its full outstanding set via handle_log
                self.send_to_osd(osd, MOSDPGLog(
                    pgid=self.pgid, from_osd=self.whoami, entries=[],
                    head=list(head), contiguous=True,
                    map_epoch=self.map_epoch()))
                continue
            with self.lock:
                overlaps = self.pg_log.overlaps(peer_lu)
                if overlaps:
                    entries = [(e.epoch, e.version, e.oid, e.kind,
                                e.prior_version)
                               for e in
                               self.pg_log.entries_since(peer_lu)]
                else:
                    # divergent or forked peer: ship the FULL log so
                    # its merge can find the common point and roll its
                    # dead-interval entries back (never the scan lane,
                    # which would resurrect them as "newer versions")
                    entries = self.pg_log.dump()
                    if peer_lu < self.pg_log.tail:
                        # pre-history peer: the log can't cover it all
                        backfill.append(osd)
            self.send_to_osd(osd, MOSDPGLog(
                pgid=self.pgid, from_osd=self.whoami,
                entries=entries, head=list(head),
                contiguous=overlaps, map_epoch=self.map_epoch()))
        # non-overlapping peers (or peers that never answered) converge
        # through the scan/backfill lane
        silent = [osd for s, osd in shards.items()
                  if osd not in (CRUSH_ITEM_NONE, self.whoami)
                  and osd not in infos]
        for osd in set(backfill + silent):
            shard = next((s for s, o in shards.items() if o == osd), -1)
            self.send_to_osd(osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=shard,
                op="request", map_epoch=self.map_epoch()))
        # reconcile our own shard(s) (objects only we lost)
        my_inv = self._local_inventory(self.my_shard())
        self._reconcile_inventory(self.my_shard(), self.whoami, my_inv)
        for fn in waiting:
            fn()

    def _local_inventory(self, shard: int) -> dict:
        cid = self.cid_of_shard(shard)
        inv = {}
        for oid in self.store.list_objects(cid):
            if oid == META_OID:
                # the durable-log object is per-OSD state: pushing it
                # would graft OUR log head onto a replica that has
                # none of the data behind it
                continue
            try:
                raw = self.store.getattr(cid, oid, VERSION_ATTR)
                inv[oid] = int(raw) if raw else 0
            except KeyError:
                inv[oid] = 0
        return inv

    def handle_scan(self, msg) -> None:
        if msg.op == "request":
            # a replica answers with its shard's inventory plus its
            # delete log, so a primary that was down during a delete
            # learns the object is a ghost instead of re-pushing it
            inv = self._local_inventory(
                msg.shard if self.pool.is_erasure() else -1)
            with self.lock:
                deleted = dict(self._deleted_log)
            self.send_to_osd(msg.from_osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=msg.shard,
                op="reply", objects=inv, deleted=deleted,
                map_epoch=self.map_epoch()))
            return
        if msg.op == "scrub_request":
            inv = self._scrub_inventory(
                msg.shard if self.pool.is_erasure() else -1)
            self.send_to_osd(msg.from_osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=msg.shard,
                op="scrub_reply", objects=inv,
                map_epoch=self.map_epoch()))
            return
        if msg.op == "scrub_reply":
            self._handle_scrub_reply(msg.from_osd, msg.shard,
                                     msg.objects)
            return
        # primary side: compare against authoritative inventory
        self._reconcile_inventory(msg.shard, msg.from_osd, msg.objects,
                                  getattr(msg, "deleted", {}) or {})

    # -- scrub (PG_STATE_SCRUBBING; PrimaryLogPG scrub + repair) --------

    def _scrub_inventory(self, shard: int) -> dict:
        """oid -> (version, crc32(data), size) for one shard.

        HBM-resident objects carrying fused-write device digests are
        verified with ZERO host hashing: the on-disk bytes are still
        read (silent disk bitrot must stay catchable — the write-time
        digest only says what the bytes SHOULD be), but their crc is
        computed on device (fused_transform.device_crc32) and the
        resident digest is the expected side, so the host never walks
        a crc loop for them.  Only non-resident objects fall back to
        host_crc32()."""
        cid = self.cid_of_shard(shard)
        tier = getattr(self.daemon, "hbm_tier", None)
        inv = {}
        for oid in self.store.list_objects(cid):
            if oid == META_OID:
                continue   # per-OSD durable log, not replicated data
            try:
                dig = None if tier is None or shard < 0 else \
                    self._digest_from_tier(tier, shard, oid)
                data = self.store.read(cid, oid)
                raw = self.store.getattr(cid, oid, VERSION_ATTR)
                if dig is not None:
                    from . import fused_transform
                    disk_crc = fused_transform.device_crc32(
                        data, device=getattr(self.daemon,
                                             "home_device", None))
                    inv[oid] = (int(raw) if raw else 0, disk_crc,
                                len(data))
                    continue
                inv[oid] = (int(raw) if raw else 0,
                            host_crc32(data), len(data))
            except (KeyError, OSError):
                inv[oid] = (-1, 0, 0)   # unreadable shard: scrub error
        return inv

    def _digest_from_tier(self, tier, shard: int, oid) -> int | None:
        """Device-computed crc for one resident shard, or None (not
        resident / adopted without digests / unknown shard row)."""
        try:
            key = (str(self.pgid), oid)
            row = tier.shard_digests(key)
            if row is None:
                return None
            codec = tier.codec_of(key)
            phys = shard
            if codec is not None:
                for i in range(codec.get_chunk_count()):
                    if codec.chunk_index(i) == shard:
                        phys = i
                        break
            if phys >= len(row):
                return None
            return int(row[phys])
        except Exception:
            return None

    def scrub(self, seq: int | None = None, deep: bool = False,
              repair: bool = False) -> dict | None:
        """Primary-driven scrub: collect per-object (version, crc, size)
        from every acting peer, compare against the local copy, and
        push repairs for mismatches. Returns immediately; results land
        in self.scrub_stats once all replies arrive.

        seq is the ticket minted by OSDDaemon.scrub_pg (None = direct
        call: mint one here); a superseded ticket aborts silently.

        deep=True on an EC pool additionally verifies every shard's
        stored crc against the write-time hinfo record and rebuilds
        divergent shards from the survivors (decode on the device) —
        the integrity check a shallow EC scrub cannot do.

        Whether flagged inconsistencies are actually REPAIRED is
        repair OR osd_scrub_auto_repair; with both off the scrub is
        detect-only, errors persist in self.scrub_errors, and the
        cluster raises OSD_SCRUB_ERRORS until a 'pg repair'
        (scrub_pg(..., repair=True)) rebuilds the bad copies."""
        if not self.is_primary():
            return None
        shards = self.acting_shards()
        with self.lock:
            if seq is None:
                self._scrub_seq = getattr(self, "_scrub_seq", 0) + 1
                seq = self._scrub_seq
            elif seq != getattr(self, "_scrub_seq", 0):
                return None  # a newer scrub_pg superseded this ticket
            self._scrub_deep = deep
            try:
                auto = self.daemon.ctx.conf.get_val(
                    "osd_scrub_auto_repair")
            except Exception:
                auto = True
            self._scrub_repair = repair or auto
            self._scrub_waiting = {
                osd for shard, osd in shards.items()
                if osd not in (CRUSH_ITEM_NONE, self.whoami)}
            self._scrub_replies = {}
            self.scrub_stats = {"state": "scrubbing", "errors": 0,
                                "repaired": 0, "objects": 0}
        self._send_scrub_requests(shards)
        if not self._scrub_waiting:
            self._finish_scrub()
        else:
            # one-shot messages wedge on lossy links: retransmit to
            # laggard peers a few times, then give up loudly
            self.daemon.timer.add_event_after(
                1.0, self._scrub_retry, seq, 0)
        return self.scrub_stats

    def _send_scrub_requests(self, shards, only: set | None = None):
        for shard, osd in shards.items():
            if osd in (CRUSH_ITEM_NONE, self.whoami):
                continue
            if only is not None and osd not in only:
                continue
            self.send_to_osd(osd, MOSDPGScan(
                pgid=self.pgid, from_osd=self.whoami, shard=shard,
                op="scrub_request", map_epoch=self.map_epoch()))

    def _scrub_retry(self, seq: int, attempt: int) -> None:
        with self.lock:
            if seq != getattr(self, "_scrub_seq", 0) \
                    or not self._scrub_waiting:
                return  # this scrub finished or was superseded
            waiting = set(self._scrub_waiting)
            if attempt >= 5:
                self._scrub_waiting = set()
                self.scrub_stats = {"state": "failed", "errors": 0,
                                    "repaired": 0, "objects": 0,
                                    "unreachable": sorted(waiting)}
                return
        self._send_scrub_requests(self.acting_shards(), only=waiting)
        self.daemon.timer.add_event_after(
            1.0, self._scrub_retry, seq, attempt + 1)

    def _handle_scrub_reply(self, peer_osd: int, shard: int,
                            inv: dict) -> None:
        with self.lock:
            if peer_osd not in getattr(self, "_scrub_waiting", set()):
                return
            self._scrub_waiting.discard(peer_osd)
            self._scrub_replies[(peer_osd, shard)] = inv
            done = not self._scrub_waiting
        if done:
            self._finish_scrub()

    def _finish_scrub(self) -> None:
        """Compare every replica's inventory to the primary's copy.

        Replicated pools only compare like-for-like copies; EC shards
        hold different bytes per shard, so EC scrub checks only version
        presence (deep EC parity verification = decode check, a later
        round). Authoritative copy = highest version, primary wins
        ties; mismatches are repaired by pushing it."""
        with self.lock:
            seq = getattr(self, "_scrub_seq", 0)
            deep = getattr(self, "_scrub_deep", False)
            repair = getattr(self, "_scrub_repair", True)
            replies = {k: dict(v)
                       for k, v in self._scrub_replies.items()}
        local = self._scrub_inventory(
            self.my_shard() if self.pool.is_erasure() else -1)
        errors = repaired = 0
        shallow_repaired: set = set()   # (peer_osd, shard, oid)
        replicated = not self.pool.is_erasure()
        for (peer_osd, shard), inv in replies.items():
            for oid in set(local) | set(inv):
                mine = local.get(oid)
                theirs = inv.get(oid)
                if mine == theirs:
                    continue
                if not replicated:
                    # EC: only flag version divergence
                    if mine is not None and theirs is not None \
                            and mine[0] == theirs[0]:
                        continue
                errors += 1
                if repair and mine is not None and (
                        theirs is None or theirs[0] <= mine[0]):
                    self._push_object(oid, shard, peer_osd, force=True)
                    shallow_repaired.add((peer_osd, shard, oid))
                    repaired += 1
        if not replicated and deep:
            # the deep pass reconstructs objects through the normal EC
            # read path, whose sub-read replies are served by THIS PG's
            # shard worker — run it on its own thread so waiting for
            # them cannot deadlock the worker
            def deep_worker(base_err=errors, base_rep=repaired,
                            nobj=len(local)):
                d_err, d_rep = self._deep_scrub_ec(
                    local, replies, shallow_repaired, repair)
                err, rep = base_err + d_err, base_rep + d_rep
                with self.lock:
                    if seq != getattr(self, "_scrub_seq", 0):
                        return  # a newer scrub superseded this one
                    self.scrub_stats = {
                        "state": "clean" if err == rep
                        else "inconsistent",
                        "errors": err, "repaired": rep,
                        "objects": nobj, "deep": True}
                self._scrub_epilogue(err, rep, deep=True)

            threading.Thread(target=deep_worker, name="deep-scrub",
                             daemon=True).start()
            return
        with self.lock:
            if seq != getattr(self, "_scrub_seq", 0):
                return  # superseded mid-finish: don't clobber stats
            stats = {
                "state": "clean" if errors == repaired
                else "inconsistent",
                "errors": errors, "repaired": repaired,
                "objects": len(local)}
            if deep:
                # for replicated pools the shallow crc comparison IS
                # the deep check (all copies hold identical bytes);
                # mark completion either way so pollers keying on the
                # 'deep' flag terminate
                stats["deep"] = True
            self.scrub_stats = stats
        self._scrub_epilogue(errors, repaired, deep=deep)

    def _scrub_epilogue(self, errors: int, repaired: int,
                        deep: bool = False) -> None:
        """Post-scrub accounting: persist the unrepaired count for the
        pg-stats report (OSD_SCRUB_ERRORS input) and tell the operator
        through the cluster log — the reference clogs scrub results
        from PG::scrub_finish the same way."""
        with self.lock:
            self.scrub_errors = max(0, errors - repaired)
        clog = getattr(self.daemon, "clog", None)
        if clog is None:
            return
        what = "deep-scrub" if deep else "scrub"
        if errors:
            clog.error("pg %s %s: %d errors, %d repaired%s"
                       % (self.pgid, what, errors, repaired,
                          "" if errors == repaired
                          else " — pg is INCONSISTENT, run pg repair"))

    def _deep_scrub_ec(self, local_inv: dict, replies: dict,
                       already_repaired: set,
                       repair: bool = True) -> tuple[int, int]:
        """EC shard verification against the write-time hinfo crcs.

        Ground truth is the per-shard cumulative crc recorded at encode
        time (ECUtil.HashInfo) — NOT a reconstruction, which would
        trust whichever shards it happened to read and could launder a
        corrupt data shard into "authoritative" bytes. A divergent
        shard is rebuilt from the OTHER shards (recover_object excludes
        the target), the rebuilt bytes are re-verified against the
        hinfo crc, and only then force-pushed.  repair=False counts
        errors without rebuilding (detect-only deep scrub).
        """
        import zlib

        errors = repaired = 0
        shards = self.acting_shards()
        my_shard = self.my_shard()
        my_inv = {my_shard: local_inv}   # _finish_scrub computed this
        for s in shards:
            if shards[s] == self.whoami and s not in my_inv:
                my_inv[s] = self._scrub_inventory(s)
        for oid, (version, _, _) in sorted(local_inv.items()):
            h = self.backend.get_hinfo(oid)
            if not h.has_chunk_hash() or h.get_total_chunk_size() == 0:
                continue
            for shard, osd in shards.items():
                if osd == CRUSH_ITEM_NONE:
                    continue
                if (osd, shard, oid) in already_repaired:
                    continue   # the shallow pass just fixed this copy
                want_crc = h.get_chunk_hash(shard)
                if osd == self.whoami:
                    have = my_inv.get(shard, {}).get(oid)
                else:
                    have = replies.get((osd, shard), {}).get(oid)
                if have is not None and have[1] == want_crc:
                    continue
                errors += 1
                if not repair:
                    continue    # detect-only pass: count, don't touch
                done = threading.Event()
                got: list = [None]

                def on_done(data, _g=got, _d=done):
                    _g[0] = data
                    _d.set()

                self.backend.recover_object(oid, shard, on_done)
                if not done.wait(10.0) or got[0] is None:
                    continue    # unrepairable now: stays inconsistent
                rebuilt = bytes(got[0])
                if (zlib.crc32(rebuilt) & 0xFFFFFFFF) != want_crc:
                    continue    # survivors are bad too: do NOT launder
                attrs, omap = self._gather_push_meta(oid)
                attrs.setdefault(VERSION_ATTR, str(version).encode())
                push = MOSDPGPush(
                    pgid=self.pgid, from_osd=self.whoami, shard=shard,
                    oid=oid, data=rebuilt, attrs=attrs, omap=omap,
                    version=version, map_epoch=self.map_epoch(),
                    force=True)
                if osd == self.whoami:
                    self.handle_push(push)
                else:
                    self.send_to_osd(osd, push)
                repaired += 1
                self.daemon.perf.inc("repaired")
        return errors, repaired

    def get_stats(self) -> dict:
        """Primary's per-PG stats row for the mon's MPGStats report:
        the HealthMonitor derives OSD_SCRUB_ERRORS and POOL_FULL from
        these.  bytes/objects are the PRIMARY SHARD's stored footprint
        (for EC that is ~1/k of logical bytes — a quota knob, not an
        accounting ledger)."""
        cid = self.cid_of_shard(
            self.my_shard() if self.pool.is_erasure() else -1)
        nobj = nbytes = 0
        try:
            for oid in self.store.list_objects(cid):
                if oid == META_OID:
                    continue
                st = self.store.stat(cid, oid)
                if st is not None:
                    nobj += 1
                    nbytes += st.get("size", 0)
        except Exception:
            pass
        with self.lock:
            # pg_stat_t degraded/misplaced: degraded = object copies
            # a current acting member is known to lack (our own
            # missing set + every peer's); misplaced = copies still
            # being backfilled onto a new acting member (fully
            # readable elsewhere). These ride MPGStats/MMgrReport
            # into the mgr's pg_summary and the progress module.
            degraded = (len(self.missing)
                        + sum(len(s)
                              for s in self.peer_missing.values()))
            misplaced = sum(len(s) for s in self.backfilling.values())
            # reservation visibility (recovery_wait/backfill_wait/
            # backfill_toofull PG states): suffixes on the ACTIVE state
            # only — "peering" stays exact for the progress module
            state = self.peer_state
            if state == "active":
                for lane in ("recovery", "backfill"):
                    s = self._resv_state[lane]
                    if s in ("local_wait", "remote_wait"):
                        state += "+%s_wait" % lane
                    elif s == "toofull":
                        state += "+%s_toofull" % lane
                    elif s == "granted":
                        state += ("+recovering" if lane == "recovery"
                                  else "+backfilling")
            return {"pool": self.pgid.pool, "state": state,
                    "objects": nobj, "bytes": nbytes,
                    "scrub_errors": self.scrub_errors,
                    "degraded_objects": degraded,
                    "misplaced_objects": misplaced}

    def repair_shard(self, oid, shard: int, peer_osd: int) -> None:
        """Read-path self-heal: a shard that served EIO or bad-crc
        bytes during a client read is rebuilt from the survivors and
        force-pushed back (the scrub-repair machinery, triggered by the
        read instead of a scrub pass).  Deduped per (oid, shard) so a
        burst of reads over one bad shard repairs it once."""
        key = (oid, shard)
        with self.lock:
            if self.acting_primary != self.whoami:
                return
            if key in self._repairing:
                return
            self._repairing.add(key)
        attrs, omap = self._gather_push_meta(oid)

        def on_data(data):
            with self.lock:
                self._repairing.discard(key)
            if data is None:
                return     # not enough survivors right now; a scrub
                           # or the next read retries
            if self.pool.is_erasure():
                # never launder: the rebuilt bytes must match the
                # write-time hinfo crc before they overwrite anything
                h = self.backend.get_hinfo(oid)
                if h.has_chunk_hash():
                    import zlib
                    if (zlib.crc32(bytes(data)) & 0xFFFFFFFF) != \
                            h.get_chunk_hash(shard):
                        return
            version = max(int(attrs.get(VERSION_ATTR, b"0") or 0),
                          self._log_version_of(oid))
            push = MOSDPGPush(
                pgid=self.pgid, from_osd=self.whoami, shard=shard,
                oid=oid, data=bytes(data), attrs=attrs, omap=omap,
                version=version, map_epoch=self.map_epoch(),
                force=True)
            if peer_osd == self.whoami:
                self.handle_push(push)
            else:
                self.send_to_osd(peer_osd, push)
            self.daemon.perf.inc("repaired")
            clog = getattr(self.daemon, "clog", None)
            if clog is not None:
                clog.info("pg %s: rewrote shard %d of %r on osd.%d "
                          "after read error" % (self.pgid, shard, oid,
                                                peer_osd))

        self.backend.recover_object(oid, shard, on_data)

    def _authoritative_inventory(self) -> dict:
        """Union of all local shard inventories (primary's knowledge)."""
        out = {}
        if self.pool.is_erasure():
            for shard in range(self.pool.size):
                for oid, v in self._local_inventory(shard).items():
                    out[oid] = max(out.get(oid, 0), v)
        for oid, v in self._local_inventory(-1).items():
            out[oid] = max(out.get(oid, 0), v)
        return out

    def _reconcile_inventory(self, shard: int, peer_osd: int,
                             peer_inv: dict,
                             peer_deleted: dict | None = None) -> None:
        peer_deleted = peer_deleted or {}
        want = self._authoritative_inventory()
        missing = [oid for oid, v in want.items()
                   if peer_inv.get(oid, -1) < v]
        for oid in missing:
            del_v = peer_deleted.get(oid, -1)
            if del_v >= want.get(oid, -1):
                # the peer deleted this at/after our version while we
                # were away: our copy is the ghost — adopt the delete
                # locally instead of resurrecting it onto the peer
                with self.lock:
                    if del_v > self._deleted_log.get(oid, -1):
                        self._deleted_log.pop(oid, None)
                        self._deleted_log[oid] = del_v
                txn = Transaction()
                if self.pool.is_erasure():
                    for s in range(self.pool.size):
                        txn.remove(self.cid_of_shard(s), oid)
                else:
                    txn.remove(self.cid_of_shard(-1), oid)
                self.store.queue_transaction(txn)
                continue
            # inventory reconcile = the backfill lane: the peer is a
            # (possibly new) acting member being brought up to the
            # authoritative set after a remap — its objects are
            # misplaced, not degraded
            with self.lock:
                self.backfilling.setdefault(oid, set()).add(peer_osd)
            self._push_object(oid, shard, peer_osd, lane="backfill")
        if peer_osd == self.whoami:
            return
        # The peer may be AHEAD of us: a revived primary that missed
        # writes must pull them before serving authoritatively, or
        # acked data reads as lost (the peering GetLog/GetMissing
        # role, collapsed onto version xattrs). Deletes that happened
        # while we were down are indistinguishable from new objects
        # without divergent-log handling — resurrection is the known
        # limitation here, data loss is not.
        behind = [oid for oid, v in peer_inv.items()
                  if want.get(oid, -1) < v]
        my_shard = self.my_shard() if self.pool.is_erasure() else -1
        now = _time.monotonic()
        for oid in behind:
            # the divergence oracle: if OUR log shows the object deleted
            # at or after the peer's version, the peer holds a ghost —
            # propagate the delete instead of resurrecting it
            with self.lock:
                del_v = self._deleted_log.get(oid, -1)
            if del_v >= peer_inv[oid]:
                self.send_to_osd(peer_osd, MOSDPGPush(
                    pgid=self.pgid, from_osd=self.whoami, shard=shard,
                    oid=oid, version=del_v,
                    map_epoch=self.map_epoch(), delete=True))
                continue
            # in-flight pull tracking: repeated scan replies during
            # churn must not multiply EC reconstructions of the same
            # object; re-pull only after a timeout (lost push)
            if now - self._pulling.get(oid, -1e9) < 5.0:
                continue
            self._pulling[oid] = now
            self.send_to_osd(peer_osd, MOSDPGPull(
                pgid=self.pgid, from_osd=self.whoami, shard=my_shard,
                oid=oid, map_epoch=self.map_epoch()))
        if peer_inv:
            maxv = max(peer_inv.values())
            with self.lock:
                # never mint versions below what the cluster has seen
                if maxv > self.last_version:
                    self.last_version = maxv

    def handle_pull(self, msg) -> None:
        """A (usually freshly revived) primary asks for our newer copy
        of an object: push it to the requester's shard."""
        self._push_object(msg.oid, msg.shard, msg.from_osd)

    def _gather_push_meta(self, oid) -> tuple[dict, dict]:
        """(attrs, omap) from our local shard for a recovery/repair
        push — handle_push removes+rewrites the target, so the push
        must carry the FULL metadata set or the target loses it. A
        whitelist here once dropped the SNAPSET xattr, so a recovered
        head forgot its clones and snap reads resolved to the head."""
        src_cid = self.cid_of_shard(
            self.my_shard() if self.pool.is_erasure() else -1)
        try:
            attrs = {k: v for k, v in
                     self.store.getattrs(src_cid, oid).items()
                     if v is not None}
        except (KeyError, NotImplementedError):
            attrs = {}
            for name in (VERSION_ATTR, "_size", "hinfo_key",
                         SNAPSET_ATTR, WHITEOUT_ATTR):
                try:
                    val = self.store.getattr(src_cid, oid, name)
                except KeyError:
                    val = None
                if val is not None:
                    attrs[name] = val
        try:
            omap = self.store.omap_get(src_cid, oid)
        except KeyError:
            omap = {}
        return attrs, omap

    def _push_object(self, oid, shard: int, peer_osd: int,
                     force: bool = False, attempt: int = 0,
                     lane: str = "recovery") -> None:
        # reservation gate (osd_max_backfills/osd_recovery_max_active):
        # a push may only run while this PG holds its lane's local AND
        # remote slots — otherwise it parks in _resv_pending and the
        # reservation round starts.  force (scrub/read repair) bypasses:
        # those are corrective rewrites of data already counted present.
        if not force and not self._holds_reservation(lane):
            entry = (oid, shard, peer_osd, attempt)
            with self.lock:
                if entry not in self._resv_pending[lane]:
                    self._resv_pending[lane].append(entry)
            self._request_reservations(lane)
            return
        # osd_recovery_sleep delay shaping (BackoffThrottle): the unit
        # is held for the push's lifetime, so concurrent pushes raise
        # occupancy and every subsequent get() sleeps longer
        throttle = None if force else getattr(
            self.daemon, "recovery_throttle", None)
        if throttle is not None:
            throttle.get(1)
        attrs, omap = self._gather_push_meta(oid)

        def on_data(data):
            if throttle is not None:
                throttle.put(1)
            if data is None:
                # reconstruction failed (mid-churn shortage): retry
                # while the peer still owes this object, or its
                # peer_missing entry never clears and reads avoid the
                # shard forever. Bounded + deduped: one retry chain per
                # (oid, peer), backing off, giving up after ~2 minutes
                # (a later peer re-report or peering round re-arms)
                key = (oid, peer_osd)
                with self.lock:
                    if key in self._push_retrying:
                        return
                    self._push_retrying.add(key)
                delay = 1.0 if attempt < 10 else 3.0
                if attempt < 40:
                    self.daemon.timer.add_event_after(
                        delay, self._retry_push, oid, shard, peer_osd,
                        attempt + 1, lane)
                else:
                    with self.lock:
                        self._push_retrying.discard(key)
                return
            # log-domain version: a replica's missing entry records the
            # LOG version of the entry that created the object; a snap
            # clone's VERSION_ATTR is the pre-capture head version
            # (deliberately older), so pushing the attr version alone
            # would never satisfy the replica's missing gate
            version = max(int(attrs.get(VERSION_ATTR, b"0") or 0),
                          self._log_version_of(oid))
            self._count_push(lane, len(data))
            msg = MOSDPGPush(
                pgid=self.pgid, from_osd=self.whoami, shard=shard,
                oid=oid, data=data, attrs=attrs, omap=omap,
                version=version, map_epoch=self.map_epoch(),
                force=force)
            if peer_osd == self.whoami:
                self.handle_push(msg)
            else:
                self.send_to_osd(peer_osd, msg)

        self.backend.recover_object(oid, shard, on_data)

    def _count_push(self, lane: str, nbytes: int) -> None:
        """l_osd_recovery_*/l_osd_backfill_* accounting, per completed
        push (best-effort: scrub harnesses run PGs against daemon
        stubs without the full counter set)."""
        perf = getattr(self.daemon, "perf", None)
        if perf is None:
            return
        try:
            perf.inc("l_osd_%s_ops" % lane)
            perf.inc("l_osd_%s_bytes" % lane, nbytes)
        except KeyError:
            pass

    def _log_version_of(self, oid) -> int:
        """Latest log version touching oid (0 when not in the log)."""
        with self.lock:
            for e in reversed(self.pg_log.entries):
                if e.oid == oid:
                    return e.version
        return 0

    def _retry_push(self, oid, shard: int, peer_osd: int,
                    attempt: int = 1, lane: str = "recovery") -> None:
        with self.lock:
            self._push_retrying.discard((oid, peer_osd))
            if self.acting_primary != self.whoami or \
                    (oid not in self.peer_missing
                     and oid not in self.backfilling):
                return
        self._push_object(oid, shard, peer_osd, attempt=attempt,
                          lane=lane)

    # -- recovery/backfill reservations --------------------------------

    def _reservers(self):
        """The daemon's four AsyncReservers, or None on the stub
        daemons scrub/unit harnesses run PGs against — a None here
        turns the whole reservation machinery into a pass-through."""
        return getattr(self.daemon, "reservations", None)

    def _holds_reservation(self, lane: str) -> bool:
        if self._reservers() is None:
            return True
        with self.lock:
            return self._resv_state[lane] == "granted"

    def _request_reservations(self, lane: str) -> None:
        """Start the reservation round: queue for the LOCAL slot; the
        grant callback fans out to the replicas' remote slots."""
        reservers = self._reservers()
        if reservers is None:
            return
        with self.lock:
            if self._resv_state[lane] not in ("idle", "toofull"):
                return            # a round is already in flight
            self._resv_state[lane] = "local_wait"
            interval = self.interval
        reservers["local_" + lane].request_reservation(
            (str(self.pgid), lane),
            lambda: self._local_reservation_granted(lane, interval),
            _RESV_PRIO[lane],
            on_preempt=lambda: self._reservation_preempted(
                lane, interval))

    def _local_reservation_granted(self, lane: str,
                                   interval: int) -> None:
        peers = None
        with self.lock:
            if interval == self.interval \
                    and self._resv_state[lane] == "local_wait":
                peers = {o for o in set(self.acting) | set(self.up)
                         if o != self.whoami and o != CRUSH_ITEM_NONE}
                self._resv_want[lane] = set(peers)
                self._resv_have[lane] = set()
                self._resv_state[lane] = ("remote_wait" if peers
                                          else "granted")
        if peers is None:
            # the interval moved while we queued: give the slot back
            self._reservers()["local_" + lane].cancel_reservation(
                (str(self.pgid), lane))
            return
        if not peers:
            self._drain_reserved_pushes(lane)
            return
        for osd in peers:
            self.send_to_osd(osd, MBackfillReserve(
                pgid=self.pgid, from_osd=self.whoami, lane=lane,
                op="request", priority=_RESV_PRIO[lane],
                map_epoch=self.map_epoch()))

    def _reservation_preempted(self, lane: str, interval: int) -> None:
        """A higher-priority PG evicted our LOCAL slot: back out of the
        whole round (remote holds included) and re-queue behind it."""
        self._release_reservation(lane, keep_pending=True)
        self._schedule_resv_retry(lane, 0.5)

    def handle_reserve(self, msg) -> None:
        """MBackfillReserve dispatch: request/release land on the
        replica role, grant/reject on the requesting primary."""
        lane = msg.lane
        if msg.op == "request":
            self._handle_reserve_request(msg)
        elif msg.op == "release":
            reservers = self._reservers()
            if reservers is not None:
                reservers["remote_" + lane].cancel_reservation(
                    (str(self.pgid), lane, msg.from_osd))
            with self.lock:
                self._resv_remote_keys.discard((lane, msg.from_osd))
        else:                      # grant | reject
            self._handle_reserve_reply(msg)

    def _handle_reserve_request(self, msg) -> None:
        lane = msg.lane

        def answer(op, reason=""):
            self.send_to_osd(msg.from_osd, MBackfillReserve(
                pgid=self.pgid, from_osd=self.whoami, lane=lane,
                op=op, priority=msg.priority,
                map_epoch=self.map_epoch(), reason=reason))

        # fullness veto BEFORE slot accounting: a backfillfull replica
        # refuses backfill outright, a full one refuses recovery — the
        # primary parks in *_toofull and retries after the drain
        check = getattr(self.daemon, "reserve_refusal", None)
        refusal = check(lane) if check is not None else None
        if refusal:
            answer("reject", refusal)
            return
        reservers = self._reservers()
        if reservers is None:
            answer("grant")
            return
        with self.lock:
            self._resv_remote_keys.add((lane, msg.from_osd))
        reservers["remote_" + lane].request_reservation(
            (str(self.pgid), lane, msg.from_osd),
            lambda: answer("grant"), msg.priority,
            on_preempt=lambda: answer("reject", "preempted"))

    def _handle_reserve_reply(self, msg) -> None:
        lane = msg.lane
        granted = False
        with self.lock:
            if self._resv_state[lane] != "remote_wait":
                return             # stale reply from a released round
            if msg.op == "grant":
                self._resv_have[lane].add(msg.from_osd)
                granted = self._resv_have[lane] >= \
                    self._resv_want[lane]
                if granted:
                    self._resv_state[lane] = "granted"
        if msg.op == "grant":
            if granted:
                self._drain_reserved_pushes(lane)
            return
        # reject: back out completely so the replicas that DID grant
        # are not pinned behind us, then park — toofull waits for the
        # replica to drain, a preempted/busy one retries sooner
        toofull = getattr(msg, "reason", "") == "toofull"
        self._release_reservation(
            lane, keep_pending=True,
            parked="toofull" if toofull else "idle")
        self._schedule_resv_retry(lane, 5.0 if toofull else 1.0)

    def _drain_reserved_pushes(self, lane: str) -> None:
        """Every slot is held: the parked pushes enter the op queue —
        RECOVERY class, so dmclock keeps client ops at their share."""
        with self.lock:
            pending = self._resv_pending[lane]
            self._resv_pending[lane] = []
        wq = getattr(self.daemon, "op_wq", None)
        prio = getattr(self.daemon, "recovery_op_priority", 10)
        for oid, shard, peer, attempt in pending:
            if wq is not None:
                wq.queue(self.pgid, self._push_object, oid, shard,
                         peer, False, attempt, lane,
                         klass="recovery", priority=prio)
            else:
                self._push_object(oid, shard, peer, False, attempt,
                                  lane)

    def _release_reservation(self, lane: str, keep_pending=False,
                             parked: str = "idle") -> None:
        """Drop the local slot and every remote hold/request for this
        lane (completion, rejection backout, preemption, interval
        change — every exit from the round goes through here)."""
        reservers = self._reservers()
        if reservers is None:
            return
        with self.lock:
            state = self._resv_state[lane]
            self._resv_state[lane] = parked
            want, self._resv_want[lane] = self._resv_want[lane], set()
            self._resv_have[lane] = set()
            if not keep_pending:
                self._resv_pending[lane] = []
        if state in ("local_wait", "remote_wait", "granted"):
            reservers["local_" + lane].cancel_reservation(
                (str(self.pgid), lane))
            for osd in want:
                self.send_to_osd(osd, MBackfillReserve(
                    pgid=self.pgid, from_osd=self.whoami, lane=lane,
                    op="release", map_epoch=self.map_epoch()))

    def _release_reservations(self) -> None:
        """Interval change: both primary-side rounds restart and every
        remote slot we granted a (possibly gone) primary is freed."""
        self._release_peering_slot()
        for lane in ("recovery", "backfill"):
            self._release_reservation(lane)
        reservers = self._reservers()
        if reservers is None:
            return
        with self.lock:
            remote, self._resv_remote_keys = \
                self._resv_remote_keys, set()
        for lane, primary in remote:
            reservers["remote_" + lane].cancel_reservation(
                (str(self.pgid), lane, primary))

    def _maybe_release_reservations(self) -> None:
        """Completion detection: a drained lane (no peer owes objects,
        nothing parked) gives its slots back immediately — holding a
        backfill slot through an idle period starves other PGs."""
        if self._reservers() is None:
            return
        with self.lock:
            rec = (self._resv_state["recovery"] != "idle"
                   and not self.peer_missing
                   and not self._resv_pending["recovery"])
            bf = (self._resv_state["backfill"] != "idle"
                  and not self.backfilling
                  and not self._resv_pending["backfill"])
        if rec:
            self._release_reservation("recovery")
        if bf:
            self._release_reservation("backfill")

    def _schedule_resv_retry(self, lane: str, delay: float) -> None:
        with self.lock:
            interval = self.interval
        timer = getattr(self.daemon, "timer", None)
        if timer is not None:
            timer.add_event_after(delay, self._resv_retry, lane,
                                  interval)

    def _resv_retry(self, lane: str, interval: int) -> None:
        with self.lock:
            if interval != self.interval \
                    or self.acting_primary != self.whoami:
                return
            if self._resv_state[lane] not in ("idle", "toofull"):
                return
            has_work = bool(self._resv_pending[lane])
        if has_work:
            # _request_reservations re-enters from idle/toofull
            with self.lock:
                self._resv_state[lane] = "idle"
            self._request_reservations(lane)

    def handle_push(self, msg) -> None:
        """Apply a recovery push to the local shard store."""
        cid = self.cid_of_shard(
            msg.shard if self.pool.is_erasure() else -1)
        # the push rewrites the hinfo xattr behind the EC backend's
        # cache: drop the cached entry or size/crc queries serve the
        # pre-recovery state
        if self.pool.is_erasure():
            self.backend.hinfo_cache.pop(msg.oid, None)
        # never let an in-flight push of an older version clobber a
        # fresher local copy (an acked client write may have landed
        # while the push was in transit)
        try:
            raw = self.store.getattr(cid, msg.oid, VERSION_ATTR)
            local_v = int(raw) if raw else 0
        except KeyError:
            local_v = -1
        # only a strictly newer push may replace an existing copy; a
        # versionless push (source object vanished mid-recovery) must
        # never clobber versioned local data
        self._pulling.pop(msg.oid, None)
        waiters = []
        with self.lock:
            if self.missing.get(msg.oid, 0) <= msg.version:
                self.missing.pop(msg.oid, None)
                self._missing_src.pop(msg.oid, None)
                waiters = self._missing_waiters.pop(msg.oid, [])
        def ack_recovered():
            # tell the primary this shard is consistent again so its
            # peer_missing map stops steering reads around us
            if msg.from_osd == self.whoami:
                with self.lock:
                    peers = self.peer_missing.get(msg.oid)
                    if peers is not None:
                        peers.discard(self.whoami)
                        if not peers:
                            self.peer_missing.pop(msg.oid, None)
                    backf = self.backfilling.get(msg.oid)
                    if backf is not None:
                        backf.discard(self.whoami)
                        if not backf:
                            self.backfilling.pop(msg.oid, None)
                self._maybe_release_reservations()
            else:
                self.send_to_osd(msg.from_osd, MOSDPGNotify(
                    pgid=self.pgid, from_osd=self.whoami,
                    missing=[msg.oid], kind="recovered",
                    map_epoch=self.map_epoch()))

        try:
            if msg.delete:
                # divergent-delete propagation: drop our ghost copy
                # unless we hold a strictly newer (recreated) version —
                # and record the delete so that if WE later become
                # primary we can propagate it instead of pulling the
                # ghost back
                with self.lock:
                    if msg.version > self._deleted_log.get(msg.oid, -1):
                        self._deleted_log.pop(msg.oid, None)
                        self._deleted_log[msg.oid] = msg.version
                if local_v >= 0 and local_v <= msg.version:
                    txn = Transaction()
                    txn.remove(cid, msg.oid)
                    txn.register_on_commit(ack_recovered)
                    self.store.queue_transaction(txn)
                else:
                    ack_recovered()
                return
            # scrub repairs (force) may overwrite SAME-version bitrot;
            # no push — forced or not — may ever roll back a strictly
            # newer (acked) local copy
            if local_v >= 0 and (local_v > msg.version
                                 or (local_v == msg.version
                                     and not msg.force)):
                ack_recovered()   # our copy is already current
                return
            txn = Transaction()
            txn.remove(cid, msg.oid)
            txn.touch(cid, msg.oid)
            if msg.data:
                txn.write(cid, msg.oid, 0, msg.data)
            for name, val in msg.attrs.items():
                txn.setattr(cid, msg.oid, name, val)
            if msg.omap:
                txn.omap_setkeys(cid, msg.oid, msg.omap)
            txn.register_on_commit(ack_recovered)
            self.store.queue_transaction(txn)
        finally:
            # the recovered object unblocks any ops held on it
            for fn in waiters:
                try:
                    fn()
                except Exception:
                    pass
