"""Fused device-side write transform (ROADMAP direction F).

Ceph's write path runs checksum -> (compress) -> EC encode as separate
host passes; here the whole object write transform is ONE jitted device
program over the staged [S, k, chunk] batch:

  (a) per-chunk crc32c + xxh32 digests of the raw data,
  (b) an entropy-bound compressibility probe (256-bin histogram ->
      Shannon bound) plus a splittable bit-plane compression stage,
      with the compress-vs-store decision taken ON DEVICE,
  (c) EC encode of the (possibly compressed) stored stream, and
  (d) per-shard crc32 of the stored chunk streams in zlib polynomial —
      exactly what HashInfo/deep-scrub verify against on disk.

One h2d of raw data, one fused program, one d2h of parity + digests +
compressed payload. The CRC machinery is a GF(2)-linear tree combine:
per-byte table CRCs are folded pairwise with precomputed 32x32 "append
2^l zero bytes" matrices (M_{2h} = M_h . M_h), so the whole digest is
O(log L) vectorized levels instead of a byte-serial loop. Dynamic
stored lengths (the compressed prefix) are handled by UN-shifting the
full-capacity CRC with inverse matrices selected by the pad's bits —
valid because the stored buffer is zero beyond the stored prefix and
x is invertible mod the CRC polynomial.

Compressed container layout (`alg=jax_device`, block B=64 bytes):
  [2*nb header bytes: (flags, consts) per block][stored planes, 8B each]
flags bit p set => bit-plane p stored raw; else constant, with its
value in consts bit p. Worst case 66/64 expansion; the device decision
stores raw beyond `required_ratio`. Decompression is a vectorized
numpy pass (read path / recovery are host-driven).

Only element-layout matrix codecs (Reed-Solomon family) fuse; other
codecs fall back to the separate path. Everything here must run on
both the TPU and CPU XLA backends (tier-1 runs JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import functools
import threading

import numpy as np

__all__ = ["FusedResult", "fused_supported", "run_fused",
           "bitplane_decompress", "bitplane_compress_host",
           "crc32c_host", "xxh32_host", "device_crc32",
           "shannon_bytes_per_byte", "COMP_ALG"]

COMP_ALG = "jax_device"
_BLOCK = 64

# -- GF(2) crc machinery (host precompute) ---------------------------------
#
# Column-mask convention: a 32x32 GF(2) matrix M is stored as 32 uint32
# columns, M[j] = M . e_j; apply(M, x) = XOR_{j: bit j of x} M[j].

_POLY_ZLIB = 0xEDB88320   # reflected crc32 (zlib/HashInfo/deep-scrub)
_POLY_C = 0x82F63B78      # reflected crc32c (Castagnoli)
_LEVELS = 31              # shift matrices for appends up to 2^30 bytes


def _crc_table(poly: int) -> np.ndarray:
    tab = np.zeros(256, dtype=np.uint64)
    for b in range(256):
        c = b
        for _ in range(8):
            c = (c >> 1) ^ (poly if c & 1 else 0)
        tab[b] = c
    return tab.astype(np.uint32)


def _mat_apply(mat: np.ndarray, x: int) -> int:
    r = 0
    j = 0
    while x:
        if x & 1:
            r ^= int(mat[j])
        x >>= 1
        j += 1
    return r


def _mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.array([_mat_apply(a, int(b[j])) for j in range(32)],
                    dtype=np.uint32)


def _mat_inv(mat: np.ndarray) -> np.ndarray:
    """GF(2) inverse by Gaussian elimination (rows as bit-vectors)."""
    # work in row form: row i as integer over columns
    m = [[(int(mat[j]) >> i) & 1 for j in range(32)] for i in range(32)]
    inv = [[1 if i == j else 0 for j in range(32)] for i in range(32)]
    for col in range(32):
        piv = next(r for r in range(col, 32) if m[r][col])
        m[col], m[piv] = m[piv], m[col]
        inv[col], inv[piv] = inv[piv], inv[col]
        for r in range(32):
            if r != col and m[r][col]:
                m[r] = [a ^ b for a, b in zip(m[r], m[col])]
                inv[r] = [a ^ b for a, b in zip(inv[r], inv[col])]
    out = np.zeros(32, dtype=np.uint32)
    for j in range(32):
        col = 0
        for i in range(32):
            col |= inv[i][j] << i
        out[j] = col
    return out


class _PolyConsts:
    """Per-polynomial host constants: byte table, append-2^l-zero-bytes
    matrices (and inverses), built once per process."""

    def __init__(self, poly: int):
        self.poly = poly
        self.table = _crc_table(poly)
        m1 = np.array([self._zero_byte_update(1 << j) for j in range(32)],
                      dtype=np.uint32)
        shifts = [m1]
        for _ in range(_LEVELS - 1):
            shifts.append(_mat_mul(shifts[-1], shifts[-1]))
        self.shift = np.stack(shifts)              # [.., 32]: append 2^l B
        self.inv = np.stack([_mat_inv(s) for s in shifts])

    def _zero_byte_update(self, state: int) -> int:
        return (state >> 8) ^ int(self.table[state & 0xFF])

    def shift_n(self, state: int, nbytes: int) -> int:
        """Host: crc register after appending nbytes zero bytes."""
        lvl = 0
        while nbytes:
            if nbytes & 1:
                state = _mat_apply(self.shift[lvl], state)
            nbytes >>= 1
            lvl += 1
        return state


_CONSTS: dict = {}
_CONSTS_LOCK = threading.RLock()


def _poly_consts(poly: int) -> _PolyConsts:
    with _CONSTS_LOCK:
        pc = _CONSTS.get(poly)
        if pc is None:
            pc = _CONSTS.setdefault(poly, _PolyConsts(poly))
        return pc


# -- host oracles (tests, read path, scrub fallback) -----------------------

def crc32c_host(data, crc: int = 0) -> int:
    """crc32c (Castagnoli) of a byte buffer — the host oracle the device
    digests are verified against."""
    tab = _poly_consts(_POLY_C).table
    c = (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF
    for b in bytes(data):
        c = int(tab[(c ^ b) & 0xFF]) ^ (c >> 8)
    return (c ^ 0xFFFFFFFF) & 0xFFFFFFFF


_XXP1, _XXP2, _XXP3 = 2654435761, 2246822519, 3266489917
_XXP4, _XXP5 = 668265263, 374761393
_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def xxh32_host(data, seed: int = 0) -> int:
    """Pure-python xxh32 (spec implementation; host oracle)."""
    data = bytes(data)
    n = len(data)
    i = 0
    if n >= 16:
        a1 = (seed + _XXP1 + _XXP2) & _M32
        a2 = (seed + _XXP2) & _M32
        a3 = seed & _M32
        a4 = (seed - _XXP1) & _M32
        while i + 16 <= n:
            for lane in range(4):
                w = int.from_bytes(data[i + 4 * lane:i + 4 * lane + 4],
                                   "little")
                if lane == 0:
                    a1 = (_rotl32((a1 + w * _XXP2) & _M32, 13) * _XXP1) & _M32
                elif lane == 1:
                    a2 = (_rotl32((a2 + w * _XXP2) & _M32, 13) * _XXP1) & _M32
                elif lane == 2:
                    a3 = (_rotl32((a3 + w * _XXP2) & _M32, 13) * _XXP1) & _M32
                else:
                    a4 = (_rotl32((a4 + w * _XXP2) & _M32, 13) * _XXP1) & _M32
            i += 16
        h = (_rotl32(a1, 1) + _rotl32(a2, 7) + _rotl32(a3, 12)
             + _rotl32(a4, 18)) & _M32
    else:
        h = (seed + _XXP5) & _M32
    h = (h + n) & _M32
    while i + 4 <= n:
        w = int.from_bytes(data[i:i + 4], "little")
        h = (_rotl32((h + w * _XXP3) & _M32, 17) * _XXP4) & _M32
        i += 4
    while i < n:
        h = (_rotl32((h + data[i] * _XXP5) & _M32, 11) * _XXP1) & _M32
        i += 1
    h ^= h >> 15
    h = (h * _XXP2) & _M32
    h ^= h >> 13
    h = (h * _XXP3) & _M32
    h ^= h >> 16
    return h


def shannon_bytes_per_byte(data) -> float:
    """Host entropy probe twin: Shannon bound in bits/byte / 8."""
    arr = np.frombuffer(bytes(data), dtype=np.uint8)
    if arr.size == 0:
        return 0.0
    counts = np.bincount(arr, minlength=256).astype(np.float64)
    p = counts[counts > 0] / arr.size
    return float(-(p * np.log2(p)).sum() / 8.0)


def bitplane_compress_host(data) -> tuple[bytes, int]:
    """Host twin of the device bit-plane stage (_bitplane_dev): same
    container, byte for byte. Returns (container, padded_len) — the
    compressor plugin and the tests use it as the oracle the fused
    program must match."""
    raw = np.frombuffer(bytes(data), dtype=np.uint8)
    padded = _roundup(max(raw.size, 1), _BLOCK)
    if padded != raw.size:
        raw = np.concatenate(
            [raw, np.zeros(padded - raw.size, dtype=np.uint8)])
    nb = padded // _BLOCK
    shifts = np.arange(8, dtype=np.uint8)
    bits = (raw.reshape(nb, 1, _BLOCK) >> shifts[None, :, None]) & 1
    b4 = bits.reshape(nb, 8, 8, 8)                           # [nb,p,g,t]
    packed = (b4.astype(np.uint16)
              << shifts[None, None, None, :].astype(np.uint16)
              ).sum(axis=-1).astype(np.uint8)                # [nb,p,g]
    all0 = np.all(packed == 0, axis=-1)
    all1 = np.all(packed == 0xFF, axis=-1)
    stored = ~(all0 | all1)
    pw = shifts.astype(np.uint32)
    flags = (stored.astype(np.uint32) << pw).sum(
        axis=-1).astype(np.uint8)
    consts = (all1.astype(np.uint32) << pw).sum(
        axis=-1).astype(np.uint8)
    header = np.stack([flags, consts], axis=1).reshape(2 * nb)
    payload = packed[stored].reshape(-1)                     # (nb,p) order
    return header.tobytes() + payload.tobytes(), padded


def bitplane_decompress(buf, padded_len: int) -> bytes:
    """Inverse of the device bit-plane stage (vectorized numpy).

    buf: the compressed container (comp_len bytes). padded_len: the
    64-aligned raw length the compressor saw; the caller trims to the
    original object length.
    """
    nb = padded_len // _BLOCK
    raw = np.frombuffer(bytes(buf), dtype=np.uint8)
    flags = raw[0:2 * nb:2]
    consts = raw[1:2 * nb:2]
    payload = raw[2 * nb:]
    shifts = np.arange(8, dtype=np.uint8)
    stored = ((flags[:, None] >> shifts) & 1).astype(bool)       # [nb, 8]
    planes = np.zeros((nb, 8, 8), dtype=np.uint8)                # [nb, p, g]
    cnt = int(stored.sum())
    planes[stored] = payload[:cnt * 8].reshape(cnt, 8)
    const_fill = np.where(((consts[:, None] >> shifts) & 1).astype(bool),
                          0xFF, 0).astype(np.uint8)              # [nb, 8]
    planes[~stored] = np.broadcast_to(
        const_fill[:, :, None], (nb, 8, 8))[~stored]
    bits = ((planes[:, :, :, None] >> shifts) & 1)               # [nb,p,g,t]
    byts = (bits.astype(np.uint16)
            << shifts[None, :, None, None].astype(np.uint16)).sum(axis=1)
    return byts.astype(np.uint8).reshape(-1).tobytes()           # [nb*64]


# -- fused program (jax) ---------------------------------------------------

def fused_supported(codec) -> bool:
    """Only element-layout matrix codecs on the jax backend fuse."""
    try:
        from ..models.matrix_base import MatrixErasureCode
    except Exception:
        return False
    return (isinstance(codec, MatrixErasureCode)
            and getattr(codec, "backend", "") == "jax"
            and getattr(codec, "_bitmat", None) is not None)


def _roundup(x: int, a: int) -> int:
    return x + (a - x % a) % a if x % a else x


def _next_pow2(x: int) -> int:
    n = 1
    while n < x:
        n <<= 1
    return n


class FusedResult:
    """Host-side view of one fused write transform."""

    __slots__ = ("parity", "stored", "shard_crcs", "chunk_crc32c",
                 "chunk_xxh32", "compressed", "comp_len", "probe_ok",
                 "entropy_bpb", "used_stripes", "stored_len", "raw_len",
                 "padded_len", "dev_stored", "dev_parity")

    def as_dict(self) -> dict:
        return {s: getattr(self, s, None) for s in self.__slots__
                if not s.startswith("dev_")}


def _dev_consts(device=None):
    """Device copies of the CRC tables/matrices, cached per home device
    (same keying idiom as the codec bitmatrix constants)."""
    import jax
    import jax.numpy as jnp
    from ..models.table_cache import device_entry_key
    key = device_entry_key(device)
    with _CONSTS_LOCK:
        cache = _CONSTS.setdefault("dev", {})
        ent = cache.get(key)
        if ent is None:
            z, c = _poly_consts(_POLY_ZLIB), _poly_consts(_POLY_C)
            arrs = tuple(jnp.asarray(a) for a in
                         (z.table, z.shift, z.inv, c.table, c.shift))
            if device is not None:
                arrs = tuple(jax.device_put(a, device) for a in arrs)
            ent = cache.setdefault(key, arrs)
    return ent


def _xor_fold(x):
    # XOR-reduce the trailing axis (power-of-two width)
    while x.shape[-1] > 1:
        x = x[..., 0::2] ^ x[..., 1::2]
    return x[..., 0]


def _mat_apply_dev(cols, x):
    """cols: [32] uint32 column masks; x: [...] uint32 -> M.x"""
    import jax.numpy as jnp
    bits = (x[..., None] >> jnp.arange(32, dtype=jnp.uint32)) \
        & jnp.uint32(1)
    return _xor_fold(jnp.where(bits.astype(bool), cols, jnp.uint32(0)))


def _crc_raw_tree(streams, table, shift):
    """crc_raw (init 0, no xor-out) of each row of streams [..., L]
    via per-byte table CRCs + log2(L) pairwise combine levels."""
    import jax.numpy as jnp
    L = streams.shape[-1]
    L2 = _next_pow2(max(L, 1))
    v = table[streams.astype(jnp.int32)]
    if L2 != L:
        pad = jnp.zeros(streams.shape[:-1] + (L2 - L,), dtype=jnp.uint32)
        v = jnp.concatenate([pad, v], axis=-1)   # front zeros: crc_raw no-op
    lvl = 0
    while v.shape[-1] > 1:
        n = v.shape[-1]
        pairs = v.reshape(v.shape[:-1] + (n // 2, 2))
        v = _mat_apply_dev(shift[lvl], pairs[..., 0]) ^ pairs[..., 1]
        lvl += 1
    return v[..., 0]


def _crc32_full(streams, table, shift, init_const):
    """Standard crc32 (init 0xFFFFFFFF, xor-out) of static-length rows.
    init_const = shift_L(0xFFFFFFFF), host-precomputed for the static L."""
    import jax.numpy as jnp
    return _crc_raw_tree(streams, table, shift) ^ init_const \
        ^ jnp.uint32(0xFFFFFFFF)


def _crc_unshift(crcs, inv, pad_bytes):
    """Undo `pad_bytes` appended zero bytes on raw-register crcs by
    applying inverse shift matrices selected by the pad's bits."""
    import jax.numpy as jnp
    c = crcs
    for lvl in range(_LEVELS):
        bit = ((pad_bytes >> lvl) & 1).astype(bool)
        c = jnp.where(bit, _mat_apply_dev(inv[lvl], c), c)
    return c


def _xxh32_dev(chunks):
    """xxh32 (seed 0) of each row of chunks [B, L] uint8, L static."""
    import jax
    import jax.numpy as jnp
    B, L = chunks.shape
    u = jnp.uint32
    P1, P2, P3 = u(_XXP1), u(_XXP2), u(_XXP3)
    P4, P5 = u(_XXP4), u(_XXP5)

    def rotl(x, r):
        return (x << u(r)) | (x >> u(32 - r))

    nblk = L // 16
    if nblk:
        w = chunks[:, :nblk * 16].reshape(B, nblk, 4, 4).astype(jnp.uint32)
        scale = (u(1) << (u(8) * jnp.arange(4, dtype=jnp.uint32)))
        words = jnp.sum(w * scale, axis=-1, dtype=jnp.uint32)  # [B,nblk,4]
        acc0 = jnp.broadcast_to(
            jnp.array([(_XXP1 + _XXP2) & _M32, _XXP2, 0,
                       (-_XXP1) & _M32], dtype=jnp.uint32), (B, 4))

        def body(i, acc):
            wv = jax.lax.dynamic_index_in_dim(words, i, axis=1,
                                              keepdims=False)
            return rotl(acc + wv * P2, 13) * P1

        acc = jax.lax.fori_loop(0, nblk, body, acc0)
        h = (rotl(acc[:, 0], 1) + rotl(acc[:, 1], 7)
             + rotl(acc[:, 2], 12) + rotl(acc[:, 3], 18))
    else:
        h = jnp.full((B,), _XXP5, dtype=jnp.uint32)
    h = h + u(L)
    i = nblk * 16
    while i + 4 <= L:
        w4 = chunks[:, i:i + 4].astype(jnp.uint32)
        word = jnp.sum(
            w4 * (u(1) << (u(8) * jnp.arange(4, dtype=jnp.uint32))),
            axis=-1, dtype=jnp.uint32)
        h = rotl(h + word * P3, 17) * P4
        i += 4
    while i < L:
        h = rotl(h + chunks[:, i].astype(jnp.uint32) * P5, 11) * P1
        i += 1
    h = h ^ (h >> u(15))
    h = h * P2
    h = h ^ (h >> u(13))
    h = h * P3
    return h ^ (h >> u(16))


def _bitplane_dev(flat, payload_cap):
    """Device bit-plane stage over flat [Np] (Np % 64 == 0).
    Returns (header [2*nb], payload [payload_cap], comp_len)."""
    import jax.numpy as jnp
    Np = flat.shape[0]
    nb = Np // _BLOCK
    shifts8 = jnp.arange(8, dtype=jnp.uint8)
    x = flat.reshape(nb, _BLOCK)
    bits = (x[:, None, :] >> shifts8[None, :, None]) & jnp.uint8(1)
    b4 = bits.reshape(nb, 8, 8, 8)                       # [nb, p, g, t]
    packed = jnp.sum(
        b4.astype(jnp.uint32) << shifts8.astype(jnp.uint32), axis=-1,
        dtype=jnp.uint32).astype(jnp.uint8)              # [nb, p, g]
    all0 = jnp.all(packed == 0, axis=-1)                 # [nb, p]
    all1 = jnp.all(packed == 0xFF, axis=-1)
    stored = ~(all0 | all1)
    pw = (jnp.uint32(1) << shifts8.astype(jnp.uint32))
    flags = jnp.sum(stored.astype(jnp.uint32) * pw, axis=-1,
                    dtype=jnp.uint32).astype(jnp.uint8)  # [nb]
    consts = jnp.sum(all1.astype(jnp.uint32) * pw, axis=-1,
                     dtype=jnp.uint32).astype(jnp.uint8)
    header = jnp.stack([flags, consts], axis=1).reshape(2 * nb)
    sm = stored.reshape(nb * 8)
    smi = sm.astype(jnp.int32)
    pos = jnp.cumsum(smi) - smi                          # exclusive
    dest = jnp.where(sm, pos * 8, payload_cap)           # OOB -> dropped
    destb = (dest[:, None]
             + jnp.arange(8, dtype=jnp.int32)).reshape(-1)
    vals = packed.reshape(nb * 8, 8).reshape(-1)
    payload = jnp.zeros(payload_cap, dtype=jnp.uint8).at[destb].set(
        vals, mode="drop")
    comp_len = jnp.int32(2 * nb) + 8 * jnp.sum(smi)
    return header, payload, comp_len


def _encode_rows(bitmat, batch, w):
    """EC encode [S, k, chunk] -> parity [S, m, chunk] (element layout),
    inlined from ops.xor_mm so it fuses into the same program."""
    from ..ops import xor_mm
    bits = xor_mm.unpack_element_bits(batch, w)
    return xor_mm.pack_element_bits(xor_mm.xor_matmul(bitmat, bits), w)


def _build_program(donate: bool):
    import jax

    @functools.partial(
        jax.jit,
        static_argnames=("w", "mode", "required_milli",
                         "entropy_max_milli", "cap2", "stripe_width"),
        donate_argnums=(0,) if donate else ())
    def program(data, bitmat, tab_z, sh_z, inv_z, tab_c, sh_c,
                init_chunk_c, init_shard_z, *, w, mode, required_milli,
                entropy_max_milli, cap2, stripe_width):
        import jax.numpy as jnp
        S, k, chunk = data.shape
        N = S * k * chunk
        flat = data.reshape(N)
        # (a) per-chunk digests of the RAW chunks
        rows = data.reshape(S * k, chunk)
        chunk_crc32c = _crc32_full(rows, tab_c, sh_c,
                                   init_chunk_c).reshape(S, k)
        chunk_xxh32 = _xxh32_dev(rows).reshape(S, k)
        if mode == "store":
            parity = _encode_rows(bitmat, data, w)        # [S, m, chunk]
            all_rows = jnp.concatenate([data, parity], axis=1)
            streams = jnp.swapaxes(all_rows, 0, 1).reshape(
                all_rows.shape[1], S * chunk)
            shard_crcs = _crc32_full(streams, tab_z, sh_z, init_shard_z)
            return {"parity": parity, "shard_crcs": shard_crcs,
                    "chunk_crc32c": chunk_crc32c,
                    "chunk_xxh32": chunk_xxh32}
        # (b) probe + bit-plane stage + on-device decision
        counts = jnp.zeros(256, dtype=jnp.int32).at[
            flat.astype(jnp.int32)].add(1)
        p = counts.astype(jnp.float32) / jnp.float32(N)
        ent = -jnp.sum(jnp.where(counts > 0,
                                 p * jnp.log2(jnp.maximum(p, 1e-12)),
                                 jnp.float32(0)))
        entropy_milli = (ent * 1000).astype(jnp.int32)    # bits/byte * 1e3
        probe_ok = entropy_milli <= jnp.int32(entropy_max_milli)
        Np = _roundup(N, _BLOCK)
        flat_p = flat if Np == N else jnp.concatenate(
            [flat, jnp.zeros(Np - N, dtype=jnp.uint8)])
        nb = Np // _BLOCK
        header, payload, comp_len = _bitplane_dev(flat_p, cap2 - 2 * nb)
        comp_full = jnp.concatenate([header, payload])    # [cap2]
        ratio_ok = comp_len * 1000 <= jnp.int32(N) * required_milli
        do_compress = probe_ok & ratio_ok
        raw_full = jnp.concatenate(
            [flat, jnp.zeros(cap2 - N, dtype=jnp.uint8)])
        stored_flat = jnp.where(do_compress, comp_full, raw_full)
        S_cap = cap2 // stripe_width
        stored = stored_flat.reshape(S_cap, k, chunk)
        # (c) EC encode of the stored stream (zero tail encodes to zero)
        parity = _encode_rows(bitmat, stored, w)          # [S_cap, m, chunk]
        # (d) per-shard crc32 of the stored prefix: full-capacity crc,
        # then un-shift the dynamic zero tail
        all_rows = jnp.concatenate([stored, parity], axis=1)
        streams = jnp.swapaxes(all_rows, 0, 1).reshape(
            all_rows.shape[1], S_cap * chunk)
        stored_len = jnp.where(do_compress, comp_len, jnp.int32(N))
        used = (stored_len + jnp.int32(stripe_width - 1)) \
            // jnp.int32(stripe_width)
        pad_bytes = ((jnp.int32(S_cap) - used)
                     * jnp.int32(chunk)).astype(jnp.uint32)
        reg = _crc_raw_tree(streams, tab_z, sh_z) ^ init_shard_z
        shard_crcs = _crc_unshift(reg, inv_z, pad_bytes) \
            ^ jnp.uint32(0xFFFFFFFF)
        return {"parity": parity, "stored": stored,
                "shard_crcs": shard_crcs,
                "chunk_crc32c": chunk_crc32c, "chunk_xxh32": chunk_xxh32,
                "do_compress": do_compress, "comp_len": comp_len,
                "probe_ok": probe_ok, "entropy_milli": entropy_milli,
                "used_stripes": used}

    return program


_PROGRAMS: dict = {}
_PROGRAM_LOCK = threading.Lock()


def fused_program(donate: bool = False):
    """The process-wide jitted fused program (PROFILER-wrapped).
    Donation only pays (and only avoids per-compile warnings) on real
    accelerators — the dispatcher passes its donation probe through."""
    with _PROGRAM_LOCK:
        prog = _PROGRAMS.get(donate)
        if prog is None:
            from ..common.profiler import PROFILER
            prog = _PROGRAMS.setdefault(
                donate, PROFILER.wrap_jit("fused_transform.program",
                                          _build_program(donate)))
    return prog


def device_crc32(data, device=None) -> int:
    """zlib crc32 of ONE byte stream, computed on device through the
    GF(2) combine tree.  Deep scrub's audit leg for resident objects:
    the primary still READS the on-disk shard bytes (silent disk
    bitrot must stay catchable — the write-time digest only says what
    the bytes SHOULD be), but the hash itself runs on device, so the
    host never walks a crc loop.  Host zlib fallback without jax."""
    buf = bytes(data)
    try:
        import jax
        import jax.numpy as jnp
    except Exception:
        import zlib
        return zlib.crc32(buf) & 0xFFFFFFFF
    z = _poly_consts(_POLY_ZLIB)
    L = len(buf)
    L2 = _next_pow2(max(L, 1))
    raw = np.frombuffer(buf, dtype=np.uint8)
    if raw.size != L2:     # leading zeros are a crc_raw no-op; the
        raw = np.concatenate(  # init const carries the TRUE length
            [np.zeros(L2 - raw.size, dtype=np.uint8), raw])
    init = np.uint32(z.shift_n(0xFFFFFFFF, L))
    from ..models.table_cache import device_entry_key
    key = ("scrub_crc", device_entry_key(device))
    with _CONSTS_LOCK:
        cache = _CONSTS.setdefault("scrub_jit", {})
        fn = cache.get(key)
    if fn is None:
        tab_z, sh_z = _dev_consts(device)[0:2]

        def crc_fn(stream, init_c, _t=tab_z, _s=sh_z):
            return _crc_raw_tree(stream[None, :], _t, _s)[0] \
                ^ init_c ^ jnp.uint32(0xFFFFFFFF)

        from ..common.profiler import PROFILER
        fn = PROFILER.wrap_jit("fused_transform.scrub_crc",
                               jax.jit(crc_fn))
        with _CONSTS_LOCK:
            fn = cache.setdefault(key, fn)
    dev = raw if device is None else jax.device_put(raw, device)
    return int(jax.block_until_ready(fn(dev, init))) & 0xFFFFFFFF


def plan_capacity(n_bytes: int, stripe_width: int) -> int:
    """Static stored-buffer capacity: fits the worst-case 66/64 container
    AND the raw payload, stripe aligned."""
    nb = _roundup(n_bytes, _BLOCK) // _BLOCK
    return _roundup(max(66 * nb, n_bytes), stripe_width)


def run_fused(codec, batch, mode: str = "store",
              required_ratio: float = 0.875,
              entropy_max_bits: float = 7.0,
              device=None, data_dev=None, donate: bool = False):
    """Run the fused transform over one staged batch.

    batch: [S, k, chunk] uint8 (host or device array). data_dev, when
    given, is the already-staged device copy (the dispatcher's h2d leg);
    otherwise batch is transferred here (the one h2d). Returns the
    on-device output dict — callers d2h it in one device_get.
    """
    import jax
    import jax.numpy as jnp
    S, k, chunk = batch.shape
    w = codec.w
    sw = k * chunk
    N = S * k * chunk
    z, c = _poly_consts(_POLY_ZLIB), _poly_consts(_POLY_C)
    tab_z, sh_z, inv_z, tab_c, sh_c = _dev_consts(device)
    bitmat = codec._device_bitmat(device) if device is not None \
        else codec._device_bitmat()
    init_chunk_c = np.uint32(c.shift_n(0xFFFFFFFF, chunk))
    if mode == "store":
        init_shard_z = np.uint32(z.shift_n(0xFFFFFFFF, S * chunk))
        cap2 = N
    else:
        cap2 = plan_capacity(N, sw)
        init_shard_z = np.uint32(z.shift_n(0xFFFFFFFF,
                                           (cap2 // sw) * chunk))
    data = data_dev if data_dev is not None else jnp.asarray(
        np.ascontiguousarray(batch))
    if device is not None and data_dev is None:
        data = jax.device_put(data, device)
    return fused_program(donate)(
        data, bitmat, tab_z, sh_z, inv_z, tab_c, sh_c,
        jnp.uint32(init_chunk_c), jnp.uint32(init_shard_z),
        w=w, mode=mode, required_milli=int(required_ratio * 1000),
        entropy_max_milli=int(entropy_max_bits * 1000), cap2=cap2,
        stripe_width=sw)


def finish_fused(out, S: int, k: int, chunk: int, mode: str):
    """One d2h of the fused outputs -> FusedResult (host numpy views).

    The single jax.device_get here IS the fused path's one d2h; callers
    must not read individual outputs beforehand.
    """
    import jax
    host = jax.device_get({k_: v for k_, v in out.items()})
    return result_from_host(host, S, k, chunk, mode, dev_out=out)


def result_from_host(host: dict, S: int, k: int, chunk: int, mode: str,
                     dev_out=None):
    """Build a FusedResult from an already-transferred host dict (the
    dispatcher's d2h stage drains the whole output in one device_get
    and hands the host dict here). dev_out keeps the device-side
    outputs reachable for HBM-tier adoption."""
    r = FusedResult()
    r.raw_len = S * k * chunk
    r.padded_len = _roundup(r.raw_len, _BLOCK)
    r.chunk_crc32c = host["chunk_crc32c"]
    r.chunk_xxh32 = host["chunk_xxh32"]
    r.shard_crcs = [int(x) for x in host["shard_crcs"]]
    r.dev_parity = dev_out["parity"] if dev_out is not None else None
    if mode == "store":
        r.parity = host["parity"]
        r.stored = None
        r.dev_stored = None
        r.compressed = False
        r.comp_len = r.raw_len
        r.probe_ok = False
        r.entropy_bpb = None
        r.stored_len = r.raw_len
        r.used_stripes = S
        return r
    r.compressed = bool(host["do_compress"])
    r.comp_len = int(host["comp_len"])
    r.probe_ok = bool(host["probe_ok"])
    r.entropy_bpb = float(host["entropy_milli"]) / 8000.0
    r.used_stripes = int(host["used_stripes"])
    r.stored_len = r.comp_len if r.compressed else r.raw_len
    used = r.used_stripes
    r.parity = host["parity"][:used]
    r.stored = host["stored"][:used]
    r.dev_stored = dev_out["stored"] if dev_out is not None else None
    return r
