"""The OSD daemon.

Role of the reference's OSD (src/osd/OSD.{h,cc}): boot (mount store,
announce to mon, catch up on maps — OSD::init :2373), fast-dispatch
incoming messages onto a sharded op queue keyed by PG (ms_fast_dispatch
:6688 -> ShardedOpWQ, OSD.h:1623), heartbeat peers and report failures
(handle_osd_ping :4731 / failure reports to the mon), react to new maps
by re-peering every hosted PG, and serve the client/cluster/heartbeat
traffic classes on separate messengers (src/ceph_osd.cc:461-483).
"""

from __future__ import annotations

import logging
import threading
import time

from ..common import Context
from ..common.reserver import AsyncReserver
from ..common.throttle import BackoffThrottle
from ..common.workqueue import Finisher, SafeTimer, ShardedThreadPool
from ..mon.mon_client import MonClient
from ..msg.message import (MOSDBoot, MOSDFailure, MOSDOpReply, MPing,
                           MPingReply)
from ..msg.async_messenger import create_messenger
from ..msg.messenger import Dispatcher
from ..store.mem_store import MemStore
from ..common.lockdep import make_rlock
from ..common.tracer import SpanCollector, TailSampler
from .op_queue import QosShardedOpWQ, make_op_queue
from .op_request import OpTracker
from .osd_map import OSDMap
from .pg import PG

__all__ = ["OSDDaemon"]


class OSDDaemon(Dispatcher):
    def __init__(self, whoami: int, monmap: dict,
                 ctx: Context | None = None, store=None,
                 auth: dict | None = None):
        self.whoami = whoami
        self.ctx = ctx or Context(name="osd.%d" % whoami)
        conf = self.ctx.conf
        self.finisher = Finisher("osd%d-fin" % whoami)
        self.store = store or MemStore(self.finisher)
        # a handed-over store (daemon restart over the same data) must
        # deliver completions through THIS daemon's finisher — its
        # creator's finisher died with the old daemon, and callbacks
        # queued there black-hole (no commit acks => wedged writes)
        self.store._finisher = self.finisher
        # arm store fault injection from the objectstore_inject_*
        # knobs (store/faults.py; a handed-over store keeps any marks
        # the previous incarnation's tests planted)
        faults = getattr(self.store, "faults", None)
        if faults is not None:
            faults.configure(conf)
        # cephx: when the cluster runs with auth, client + peer
        # connections must present "osd"-service authorizers (the
        # heartbeat messenger stays open, documented: heartbeats carry
        # no data).  The authorizer factory closes over the cephx
        # session established during init's in-band mon handshake.
        self.auth = auth
        self._cephx = None             # CephxClient after authenticate
        verifier = None
        factory = None
        key_fn = None
        if auth is not None:
            from ..auth import CephxServiceHandler
            verifier = CephxServiceHandler(
                "osd", auth["service_secrets"]["osd"])

            def factory(challenge=None):
                if self._cephx is None:
                    return None
                return self._cephx.build_authorizer("osd", challenge)

            def key_fn():
                return self._cephx.tickets["osd"]["session_key"] \
                    if self._cephx else None

        self.public_msgr = create_messenger(
            ("osd", whoami), conf=conf, auth_verifier=verifier,
            authorizer_factory=factory, session_key_fn=key_fn)
        self.cluster_msgr = create_messenger(
            ("osd", whoami), conf=conf, auth_verifier=verifier,
            authorizer_factory=factory, session_key_fn=key_fn)
        self.hb_msgr = create_messenger(("osd", whoami), conf=conf)
        self.monmap = dict(monmap)
        self.mon_client = MonClient(monmap, self.public_msgr,
                                    "osd.%d" % whoami)
        # map-advance throttle (ISSUE 19): the MonClient parks incoming
        # incrementals and applies at most this many epochs per drain
        # tick, so a 1000-epoch catch-up peers in slices
        self.mon_client.map_max_advance = \
            conf.get_val("osd_map_max_advance")
        self.osdmap = OSDMap()
        self.pgs: dict = {}
        # (session, tid) -> None (executing) | (result, data)
        from ..common.bounded import BoundedDict
        self._op_replies: BoundedDict = BoundedDict()
        self.lock = make_rlock("osd:%d" % whoami)
        # op scheduling: QoS discipline per osd_op_queue (wpq default,
        # like the reference's luminous OSD), plain FIFO as fallback
        if conf.get_val("osd_op_queue") == "fifo":
            self.op_wq = ShardedThreadPool(
                "osd%d-op" % whoami, conf.get_val("osd_op_num_shards"),
                self.ctx.hbmap)
        else:
            self.op_wq = QosShardedOpWQ(
                "osd%d-op" % whoami, conf.get_val("osd_op_num_shards"),
                lambda: make_op_queue(conf), self.ctx.hbmap)
        # pool -> (res, wgt, lim) profiles already pushed into the
        # shards, so map churn doesn't re-post unchanged rates
        self._pool_qos_applied: dict = {}
        self.client_op_priority = conf.get_val("osd_client_op_priority")
        self.recovery_op_priority = conf.get_val("osd_recovery_op_priority")
        # per-op event history + slow-request detection (OpTracker);
        # slow_size is the flight recorder's N-slowest ring
        self.op_tracker = OpTracker(
            history_size=conf.get_val("osd_op_history_size"),
            history_duration=conf.get_val("osd_op_history_duration"),
            complaint_time=conf.get_val("osd_op_complaint_time"),
            slow_size=conf.get_val("osd_op_history_slow_size"))
        # recovery/backfill reservation slots (the reference OSDService's
        # local_reserver/remote_reserver pairs): a primary must win its
        # LOCAL slot and every replica's REMOTE slot before its pushes
        # may enter the recovery op class (osd/pg.py reservation round)
        max_backfills = conf.get_val("osd_max_backfills")
        max_recovery = conf.get_val("osd_recovery_max_active")
        self.reservations = {
            "local_recovery": AsyncReserver("local_recovery",
                                            max_recovery),
            "remote_recovery": AsyncReserver("remote_recovery",
                                             max_recovery),
            "local_backfill": AsyncReserver("local_backfill",
                                            max_backfills),
            "remote_backfill": AsyncReserver("remote_backfill",
                                             max_backfills),
        }
        # peering storm control (ISSUE 19): peering itself rides a
        # reserver lane so a map-churn burst re-peers at most
        # osd_peering_max_active PGs at once instead of flooding the
        # op queue and starving client IO.  0 disables the gate
        # (pg.start_recovery bypasses the lane).
        peering_slots = conf.get_val("osd_peering_max_active")
        self.peering_gate = peering_slots > 0
        self.reservations["peering"] = AsyncReserver(
            "peering", max(1, peering_slots))
        # peering duration samples for the p99 lane
        # (ceph_pg_peering_seconds): ring of the last 256 completed
        # interval peerings, summarized in _telemetry_status
        from collections import deque
        self._peering_durations = deque(maxlen=256)
        # osd_recovery_sleep delay shaping: pushes acquire a unit for
        # the duration of the push, and BackoffThrottle injects an
        # occupancy-scaled sleep — the closer concurrent pushes sit to
        # the recovery budget, the longer each one yields to client IO
        sleep = conf.get_val("osd_recovery_sleep")
        self.recovery_throttle = BackoffThrottle(
            "osd%d-recovery-sleep" % whoami,
            max_=max(1, max_recovery),
            low_threshold=0.0, high_threshold=1.0,
            low_delay=sleep * 0.1, high_delay=sleep) \
            if sleep > 0 else None
        # full-ratio ladder thresholds (mon_osd_*_ratio; the mon ranks
        # the reported used_ratio against the same options)
        self._full_ratios = (
            conf.get_val("mon_osd_nearfull_ratio"),
            conf.get_val("mon_osd_backfillfull_ratio"),
            conf.get_val("mon_osd_full_ratio"))
        self._used_stat_cache = (0.0, -1e9)   # (ratio, stamp)
        # device-runtime profiler (common/profiler.py): process-global
        # by design (module-level jit sites have no daemon home), so
        # configure() just applies this daemon's knobs
        from ..common.profiler import PROFILER
        PROFILER.configure(conf)
        # ZTracer-style span collector, config-gated (osd_tracing with
        # an osd_tracing_sample hot-path knob); spans stitch across
        # daemons via the message-envelope (trace_id, parent_span)
        self.tracer = SpanCollector(conf=conf,
                                    endpoint="osd.%d" % whoami)
        # tail-based trace retention (SLO forensics): keep/drop at op
        # completion; finished spans buffer here pending the root's
        # verdict and kept traces ship to the mgr as MTraceFragments
        self.tail = TailSampler(conf=conf)
        self.tracer.tail = self.tail
        self._tail_expired_synced = 0
        # kept-trace wire work (verdict broadcast + mgr shipment) runs
        # on its own lane: the verdict itself is cheap, but encoding
        # span payloads on the commit path would tax every op that
        # completes behind a kept one
        from collections import deque as _deque
        self._trace_ship_cond = threading.Condition()
        self._trace_ship_q = _deque()
        self._trace_ship_stop = False
        self._trace_ship_thread = threading.Thread(
            target=self._trace_ship_loop,
            name="trace-ship-%d" % whoami, daemon=True)
        self._trace_ship_thread.start()
        if self.ctx.admin_socket is not None:
            self.op_tracker.register_admin_commands(self.ctx.admin_socket)
            self.tracer.register_admin_commands(self.ctx.admin_socket)
            # store-specific commands (BlockStore: 'bluefs stats',
            # 'bluestore fsck' — the reference's asok surface)
            register_store = getattr(self.store,
                                     "register_admin_commands", None)
            if register_store is not None:
                register_store(self.ctx.admin_socket)
        self.timer = SafeTimer("osd%d-timer" % whoami)
        # cross-op EC device-call coalescing (osd/tpu_dispatch.py):
        # concurrent PG encodes sharing a codec ride one dispatch
        # mesh-native placement (parallel/placement.py, direction D):
        # resolve this OSD's home device once — the dispatcher
        # pipeline and the HBM chunk tier both pin to it, so N
        # daemons land one-per-chip with no global device lock
        from ..parallel.placement import PLACEMENT
        try:
            self.home_device = PLACEMENT.resolve(
                whoami, conf.get_val("osd_device_index"))
        except Exception:
            self.home_device = None
        # rateless mesh dispatch (parallel/rateless.py, direction J):
        # honour the conf gate so a daemon started with
        # osd_mesh_rateless=false never pulls the process-global
        # work-stealing dispatcher into its decode paths
        try:
            from ..parallel import rateless
            rateless.set_enabled(
                bool(conf.get_val("osd_mesh_rateless")))
        except Exception:
            pass
        if conf.get_val("osd_tpu_coalesce"):
            from .tpu_dispatch import TpuDispatcher
            self.tpu_dispatcher = TpuDispatcher(
                max_batch=conf.get_val("osd_tpu_coalesce_max_batch"),
                max_delay=conf.get_val(
                    "osd_tpu_coalesce_max_delay_ms") / 1e3,
                tracer=self.tracer,
                pipeline_depth=conf.get_val("osd_tpu_pipeline_depth"),
                device=self.home_device)
            # l_tpu_* device-segment counters ride the daemon's perf
            # collection (mgr report -> prometheus)
            self.ctx.perf.add(self.tpu_dispatcher.perf)
        else:
            self.tpu_dispatcher = None
        # HBM-resident chunk tier (osd/hbm_tier.py, ROADMAP direction
        # A): the dispatcher pipeline adopts each EC encode's staged
        # data + parity device-side keyed by (pg, object); scrub-repair
        # rebuilds and recovery reconstruction read the resident copy
        # instead of re-crossing PCIe. Gated on jax being importable —
        # the tier is pure device residency and has no host fallback.
        self.hbm_tier = None
        if conf.get_val("osd_hbm_tier_enable"):
            try:
                from .hbm_tier import HbmChunkTier
                self.hbm_tier = HbmChunkTier(
                    capacity_objects=conf.get_val(
                        "osd_hbm_tier_capacity"),
                    device=self.home_device)
                self.ctx.perf.add(self.hbm_tier.perf)
            except Exception:
                self.hbm_tier = None
        self.hbm_serve_reads = conf.get_val("osd_hbm_tier_serve_reads")
        # fused write transform (osd/fused_transform.py, direction F):
        # ec_backend reads these via getattr, so a missing option
        # degrades to the classic path rather than failing startup
        try:
            if not conf.get_val("osd_fused_transform"):
                self.fused_mode = "off"
            elif conf.get_val("osd_fused_compression_mode") in (
                    "", "none", None):
                self.fused_mode = "store"
            else:
                self.fused_mode = "compress"
            self.fused_required_ratio = float(
                conf.get_val("osd_fused_required_ratio"))
            self.fused_entropy_max = float(
                conf.get_val("osd_fused_probe_entropy_max"))
        except Exception:
            self.fused_mode = "off"
            self.fused_required_ratio = 0.875
            self.fused_entropy_max = 7.0
        if self.ctx.admin_socket is not None:
            # residency + pipeline introspection (`ceph daemon osd.N
            # hbm status` / `dispatch status`)
            self.ctx.admin_socket.register(
                "hbm status",
                lambda args: (self.hbm_tier.stats()
                              if self.hbm_tier is not None
                              else {"enabled": False}),
                "HBM chunk-tier residency, hit rate and evictions")
            self.ctx.admin_socket.register(
                "dispatch status",
                lambda args: (self.tpu_dispatcher.dispatch_status()
                              if self.tpu_dispatcher is not None
                              else {"enabled": False}),
                "TPU dispatcher pipeline ring occupancy + coalescing")
            # device-runtime profiler surface: stall-attribution
            # verdict, jit registry, device-memory ledger
            self.ctx.admin_socket.register(
                "dispatch profile",
                lambda args: (self.tpu_dispatcher.dispatch_profile()
                              if self.tpu_dispatcher is not None
                              else {"enabled": False}),
                "pipeline stall attribution (busy/idle/blocked per "
                "stage + bound-stage verdict)")
            self.ctx.admin_socket.register(
                "profile dump",
                lambda args: self._profile_dump(),
                "device-runtime profiler: jit compiles/cache hits, "
                "device-memory ledger, dispatch stall attribution")
            self.ctx.admin_socket.register(
                "profile reset",
                lambda args: self._profile_reset(),
                "reset the device-runtime profiler's registries and "
                "restart the stall-attribution window")
            self.ctx.admin_socket.register(
                "mesh status",
                lambda args: self._mesh_status(),
                "device placement: local mesh, this OSD's home "
                "device, and every placement-registry assignment")
            self.ctx.admin_socket.register(
                "dump_reservations",
                lambda args: {name: r.dump()
                              for name, r in self.reservations.items()},
                "recovery/backfill reservation slots: granted holders "
                "+ priority-ordered waiters per reserver")
            self.ctx.admin_socket.register(
                "perf query dump",
                lambda args: {"queries": self.perf_query.list_queries(),
                              "results": self.perf_query.dump()},
                "live perf-query subscriptions + per-key tables "
                "(ops/bytes/latency per client/pool/pg key)")
            self.ctx.admin_socket.register(
                "dump_op_queue",
                lambda args: self._dump_op_queue(),
                "QoS op-queue state: per-class/per-pool depth, served "
                "and limit-throttle wait merged across shards")
            self.ctx.admin_socket.register(
                "osdmap status",
                lambda args: self._osdmap_status(),
                "map pipeline state: applied epoch, mon epoch, lag, "
                "inc backlog depth, peering lane occupancy + p99")
        self.hb_peers: dict = {}       # osd -> last reply stamp
        self.hb_pending: dict = {}     # osd -> first unacked ping stamp
        # cache tiering: base-pool IO runs on dedicated threads with an
        # internal RadosClient (the reference OSD's objecter), never on
        # an op-shard worker (the base PG may live on THIS osd)
        self._tier_pool = None
        self._tier_client = None
        self.mgr_addr = None           # set when an mgr joins the cluster
        # delta-encoded mgr telemetry: ship only changed counters once
        # the mgr acks a full baseline (common/telemetry.py)
        from ..common.telemetry import DeltaReporter
        self._mgr_reporter = DeltaReporter()
        self._boot_sent_epoch = -1     # epoch of the last MOSDBoot sent
        self._boot_sent_at = 0.0       # for boot retransmit rate-limit
        # l_osd_* counters (OSD.cc's PerfCounters), streamed to the mgr
        from ..common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("osd")
                     .add_u64_counter("op", "client operations")
                     .add_u64_counter("op_r", "client read operations")
                     .add_u64_counter("op_w", "client write operations")
                     .add_u64_counter("op_in_bytes", "client bytes written")
                     .add_u64_counter("op_out_bytes",
                                      "client bytes read back")
                     .add_time_avg("op_latency", "client op latency")
                     .add_u64_counter("read_err",
                                      "shard read errors (EIO/bad crc) "
                                      "seen on the EC read path "
                                      "(l_osd_read_err)")
                     .add_u64_counter("repaired",
                                      "shards rewritten by read-repair "
                                      "or scrub repair (l_osd_repaired)")
                     # recovery/backfill accounting (OSD.cc
                     # l_osd_recovery_ops/_bytes, l_osd_backfill):
                     # incremented per pushed shard on the recovery
                     # lane (peer re-reported missing) vs the backfill
                     # lane (inventory reconcile after remap)
                     .add_u64_counter("l_osd_recovery_ops",
                                      "recovery push operations "
                                      "completed")
                     .add_u64_counter("l_osd_recovery_bytes",
                                      "bytes pushed by recovery")
                     .add_u64_counter("l_osd_backfill_ops",
                                      "backfill push operations "
                                      "completed")
                     .add_u64_counter("l_osd_backfill_bytes",
                                      "bytes pushed by backfill")
                     # regenerating-code repair accounting (ROADMAP
                     # direction C): helper-side bytes read from disk
                     # and beta-fraction bytes shipped to the primary,
                     # primary-side bytes of survivor traffic AVOIDED
                     # vs a full k-chunk decode — the recovery-traffic
                     # ratio gauge derives from shipped/(shipped+saved)
                     .add_u64_counter("l_osd_repair_bytes_read",
                                      "shard bytes read by repair "
                                      "fraction requests (helper side)")
                     .add_u64_counter("l_osd_repair_bytes_shipped",
                                      "beta-fraction bytes shipped to "
                                      "the rebuilding primary")
                     .add_u64_counter("l_osd_repair_bytes_saved",
                                      "survivor bytes NOT moved vs a "
                                      "full k-chunk decode")
                     # reservation observability (dump_reservations
                     # asok / prometheus ceph_osd_reservation_*):
                     # granted + preempted are lifetime totals across
                     # the four reservers, waiting is the current
                     # queue depth — synced from the reservers at
                     # report time (_sync_reservation_perf)
                     .add_u64("l_osd_reservation_granted",
                              "reservation grants (lifetime, all "
                              "reservers)")
                     .add_u64("l_osd_reservation_waiting",
                              "reservation requests currently queued")
                     .add_u64("l_osd_reservation_preempted",
                              "reservation holders preempted by "
                              "higher priority (lifetime)")
                     # dispatch-side admission control: cumulative time
                     # client connections spent blocked on the message
                     # count/size throttles (TCP backpressure)
                     .add_time_avg("l_osd_throttle_wait",
                                   "client dispatch throttle wait")
                     # span-derived per-phase op timing (the tracing
                     # spine's aggregate view; always on — a tinc is
                     # cheap even when span objects are not minted)
                     .add_time_avg("l_osd_op_trace_queue",
                                   "op wait in the sharded op queue")
                     .add_time_avg("l_osd_op_trace_pg",
                                   "pg do_op planning/submit time")
                     .add_time_avg("l_osd_op_trace_total",
                                   "client op end-to-end on this osd")
                     .add_histogram("l_osd_op_trace_us",
                                    "op latency histogram, microseconds")
                     # dynamic per-principal perf queries
                     # (osd/perf_query.py): live subscription + key
                     # table gauges, lifetime sample/eviction totals
                     .add_u64("l_osd_pq_queries",
                              "perf queries currently subscribed")
                     .add_u64("l_osd_pq_keys",
                              "live perf-query keys across all "
                              "subscriptions (bounded by "
                              "osd_perf_query_max_keys per query)")
                     .add_u64_counter("l_osd_pq_samples",
                                      "client ops accounted into at "
                                      "least one perf query")
                     .add_u64_counter("l_osd_pq_evictions",
                                      "perf-query keys LRU-evicted at "
                                      "the table bound")
                     # map-churn observability (ISSUE 19): per-interval
                     # peering wall time (histogram in microseconds —
                     # hinc buckets are integer powers of two) and the
                     # epochs this daemon trails the mon's newest map
                     .add_histogram("l_osd_peering_us",
                                    "per-interval peering duration, "
                                    "microseconds (start_peering to "
                                    "activate)")
                     .add_u64("l_osd_map_lag_epochs",
                              "osdmap epochs this daemon trails the "
                              "monitor (backlog + unfetched)")
                     # tail-based trace retention (SLO forensics):
                     # verdicts by reason, plus the replica-side
                     # pending-buffer churn
                     .add_u64_counter("l_osd_trace_tail_kept_slo",
                                      "traces kept: op latency over "
                                      "the pool's SLO threshold")
                     .add_u64_counter("l_osd_trace_tail_kept_error",
                                      "traces kept: op errored or a "
                                      "span logged an error event")
                     .add_u64_counter("l_osd_trace_tail_kept_reservoir",
                                      "traces kept by the baseline "
                                      "reservoir draw")
                     .add_u64_counter("l_osd_trace_tail_dropped",
                                      "traces judged drop at "
                                      "completion (zero wire bytes)")
                     .add_u64_counter("l_osd_trace_tail_shipped_spans",
                                      "span fragments shipped to the "
                                      "mgr trace store")
                     .add_u64_counter("l_osd_trace_tail_expired",
                                      "pending replica fragments "
                                      "reaped by the verdict TTL")
                     .create_perf_counters())
        self.ctx.perf.add(self.perf)
        # per-principal perf-query engine (osd/perf_query.py): the
        # mgr subscribes queries via MOSDPerfQuery; pg.do_op wraps
        # reply callables through it when any query is live
        from .perf_query import PerfQueryEngine
        self.perf_query = PerfQueryEngine(conf=conf, perf=self.perf)
        # messenger admission control (tentpole leg 3): over-budget
        # client connections block in the reader — TCP backpressure —
        # instead of ballooning the op queue.  Public messenger only:
        # cluster/heartbeat traffic must never be throttled behind
        # client bytes.
        self.public_msgr.enable_dispatch_throttle(
            conf.get_val("osd_client_message_cap"),
            conf.get_val("osd_client_message_size_cap"),
            wait_cb=lambda dt: self.perf.tinc(
                "l_osd_throttle_wait", dt))
        # cluster log channel (the reference's clog): operator-facing
        # events (shard EIO, scrub errors, repairs) go to the mon's
        # replicated LogMonitor and surface via 'ceph log last'
        from ..common.clog import ClogChannel
        self.clog = ClogChannel(self.public_msgr, monmap,
                                "osd.%d" % whoami)
        self._running = False
        self.stopped_pgs = False

    # -- lifecycle -----------------------------------------------------

    def init(self) -> None:
        self.store.mount()
        # BlockStore: the l_bluefs_* counters exist only after mount;
        # register them so 'perf dump'/'perf schema', the mgr report,
        # and PrometheusModule all carry them
        bluefs = getattr(self.store, "bluefs", None)
        if bluefs is not None and getattr(bluefs, "perf", None) \
                is not None:
            self.ctx.perf.add(bluefs.perf)
        for msgr in (self.public_msgr, self.cluster_msgr, self.hb_msgr):
            msgr.bind()
            msgr.add_dispatcher_head(self)
            msgr.start()
        self.finisher.start()
        self.op_wq.start()
        self.timer.init()
        self._running = True
        self.mon_client.map_callbacks.append(self._on_osdmap)
        if self.auth is not None:
            # in-band cephx with the mon BEFORE any cluster dial: peer
            # OSDs demand an authorizer minted from this ticket
            self._cephx = self.mon_client.authenticate(
                "osd.%d" % self.whoami, self.auth["secret"],
                service="osd")
        self.mon_client.sub_want()
        self._boot()
        self._hb_tick()
        self._agent_tick()
        self._mgr_report_tick()

    def _send_mon(self, msg) -> None:
        """One-way control traffic (boot, failure reports, pg stats)
        broadcast to EVERY monitor: peons forward to the leader and
        the services are idempotent/deduping, so the message survives
        any minority of dead mons — including the old leader.  A
        single fixed target (the old monmap[min] behavior) wedged
        reviving OSDs forever when exactly that mon was the one that
        died."""
        for rank in sorted(self.monmap):
            self.public_msgr.send_message(msg, self.monmap[rank])

    def _boot(self, epoch: int | None = None) -> None:
        # record the epoch of the map that PROMPTED this boot (the new
        # map is not installed yet when called from _on_osdmap)
        self._boot_sent_epoch = self.map_epoch() if epoch is None \
            else epoch
        self._boot_sent_at = time.monotonic()
        self._send_mon(
            MOSDBoot(osd_id=self.whoami,
                     public_addr=self.public_msgr.my_addr,
                     cluster_addr=self.cluster_msgr.my_addr,
                     hb_addr=self.hb_msgr.my_addr))

    def shutdown(self) -> None:
        self._running = False
        with self._trace_ship_cond:
            self._trace_ship_stop = True
            self._trace_ship_cond.notify()
        self.timer.shutdown()
        if self.tpu_dispatcher is not None:
            self.tpu_dispatcher.shutdown()
        with self.lock:
            tier_pool, self._tier_pool = self._tier_pool, None
            tier_client, self._tier_client = self._tier_client, None
        if tier_pool is not None:
            tier_pool.shutdown(wait=False, cancel_futures=True)
        if tier_client is not None:
            tier_client.shutdown()
        self.op_wq.stop()
        self.finisher.stop()
        for msgr in (self.public_msgr, self.cluster_msgr, self.hb_msgr):
            msgr.shutdown()
        self.store.umount()
        self.ctx.shutdown()

    # -- map handling --------------------------------------------------

    def map_epoch(self) -> int:
        return self.osdmap.epoch

    def ec_profile_for(self, pool) -> dict:
        """Resolve the pool's EC profile from the published osdmap."""
        prof = self.osdmap.ec_profiles.get(pool.erasure_code_profile)
        if prof is None:
            raise KeyError("no EC profile %r" % pool.erasure_code_profile)
        return prof

    def _on_osdmap(self, newmap) -> None:
        if newmap is None:
            return
        # the map says we're dead but we're clearly not: re-boot (the
        # reference OSD does the same when it sees itself marked down —
        # covers a late failure report racing a quick restart). Only
        # once per epoch: a boot is already in flight for maps at or
        # below the epoch we last booted against.
        if self._running and newmap.exists(self.whoami) \
                and newmap.is_down(self.whoami) \
                and newmap.epoch > self._boot_sent_epoch:
            self._boot(epoch=newmap.epoch)
        with self.lock:
            self.osdmap = newmap
            pgs = list(self.pgs.values())
        self._apply_pool_qos(newmap)
        for pg in pgs:
            self.op_wq.queue(pg.pgid, pg.on_map_change)
        self._scan_for_new_pgs()

    def _apply_pool_qos(self, m) -> None:
        """Push pool dmclock profiles from the osdmap into every op
        shard: a pool with a profile gets its own "client:<name>"
        class so another pool's flood cannot consume its reservation."""
        if not isinstance(self.op_wq, QosShardedOpWQ):
            return
        for pool in m.pools.values():
            if not getattr(pool, "has_qos", lambda: False)():
                continue
            prof = (pool.qos_reservation, pool.qos_weight or 500.0,
                    pool.qos_limit)
            if self._pool_qos_applied.get(pool.name) == prof:
                continue
            if self.op_wq.set_pool_qos(pool.name, *prof):
                self._pool_qos_applied[pool.name] = prof

    def _qos_class_for(self, pool) -> str:
        """Op class for a client op: per-pool when the pool carries a
        QoS profile (bounded cardinality — one extra class per
        profiled pool), plain "client" otherwise."""
        if pool is not None and getattr(pool, "has_qos",
                                        lambda: False)():
            return "client:%s" % pool.name
        return "client"

    def _dump_op_queue(self) -> dict:
        if isinstance(self.op_wq, QosShardedOpWQ):
            classes = self.op_wq.dump()
        else:
            classes = {}
        return {"discipline": self.ctx.conf.get_val("osd_op_queue"),
                "num_shards": self.ctx.conf.get_val("osd_op_num_shards"),
                "classes": classes,
                "pool_profiles": dict(self._pool_qos_applied)}

    def _scan_for_new_pgs(self) -> None:
        """Instantiate PGs this OSD is acting in (load_pgs analog)."""
        from .osd_map import PGID
        m = self.osdmap
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                pgid = PGID(pool_id, ps)
                with self.lock:
                    if pgid in self.pgs:
                        continue
                up, upp, acting, actp = m.pg_to_up_acting_osds(pgid)
                if self.whoami in acting or self.whoami in up:
                    self._get_pg(pgid, pool)

    def _get_pg(self, pgid, pool=None):
        with self.lock:
            pg = self.pgs.get(pgid)
            if pg is None:
                if pool is None:
                    pool = self.osdmap.pools.get(pgid.pool)
                    if pool is None:
                        return None
                pg = self.pgs[pgid] = PG(self, pgid, pool)
                self.op_wq.queue(pgid, pg.on_map_change)
        return pg

    def scrub_pg(self, pgid, deep: bool = False,
                 repair: bool = False) -> bool:
        """Kick a (deep) scrub of one PG ('ceph pg scrub' /
        'ceph pg deep-scrub' surface); runs on the op queue at scrub
        class priority.  repair=True is the 'ceph pg repair' spelling:
        rebuild what the scrub flags even when osd_scrub_auto_repair
        is off."""
        pg = self.pgs.get(pgid)
        if pg is None:
            return False
        # the seq bump + queued marker happen synchronously and under
        # the PG lock: callers polling scrub_stats must never read a
        # PREVIOUS scrub's terminal state as this scrub's result, and a
        # superseded scrub (or its deep worker) must never write stats
        # over a newer one's
        with pg.lock:
            pg._scrub_seq = getattr(pg, "_scrub_seq", 0) + 1
            seq = pg._scrub_seq
            pg.scrub_stats = {"state": "queued"}
        self.op_wq.queue(pg.pgid, pg.scrub, seq, deep, repair,
                         klass="scrub",
                         priority=self.recovery_op_priority)
        return True

    # -- cache tiering plumbing ----------------------------------------

    def tier_submit(self, fn, *args) -> None:
        """Run blocking cross-pool tier IO on the dedicated tier
        threads (lazily created; most OSDs never host a tier PG).
        Work arriving after shutdown began is dropped — recreating the
        pool post-teardown would leak threads past daemon stop."""
        with self.lock:
            if not self._running:
                return
            if self._tier_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._tier_pool = ThreadPoolExecutor(
                    max_workers=2,
                    thread_name_prefix="osd%d-tier" % self.whoami)
            pool = self._tier_pool
        pool.submit(self._tier_run, fn, *args)

    @staticmethod
    def _tier_run(fn, *args) -> None:
        try:
            fn(*args)
        except Exception:
            logging.getLogger("ceph_tpu.osd").exception(
                "tier operation failed")

    def tier_client(self):
        """The OSD-internal RadosClient the tier path uses for base-
        pool IO (the reference OSD's own Objecter)."""
        with self.lock:
            if not self._running:
                raise RuntimeError("osd.%d shutting down" % self.whoami)
            client = self._tier_client
        if client is not None:
            return client
        from ..client.rados import RadosClient
        fresh = RadosClient(self.monmap, client_id=100000 + self.whoami)
        fresh.connect()
        with self.lock:
            if self._running and self._tier_client is None:
                self._tier_client = fresh
                fresh = None
            client = self._tier_client
        if fresh is not None:
            fresh.shutdown()    # lost the creation race / shutting down
        if client is None:
            raise RuntimeError("osd.%d shutting down" % self.whoami)
        return client

    def _agent_tick(self) -> None:
        """Periodic tier-agent pass over primary cache-tier PGs
        (OSD::tick -> agent_entry role)."""
        if not self._running:
            return
        with self.lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            pool = pg.pool
            if pool.is_tier() \
                    and pool.cache_mode in ("writeback", "readproxy") \
                    and pg.is_primary() and pg.peer_state == "active" \
                    and (pool.target_max_objects > 0
                         or pool.target_max_bytes > 0):
                self.tier_submit(pg._tier().agent_scan)
        self.timer.add_event_after(
            self.ctx.conf.get_val("osd_agent_interval"),
            self._agent_tick)

    def queue_recovery(self, pg) -> None:
        self.op_wq.queue(pg.pgid, pg.start_recovery,
                         klass="recovery",
                         priority=self.recovery_op_priority)

    # -- sends ---------------------------------------------------------

    def _osd_addr(self, osd: int, kind: str):
        addrs = self.osdmap.get_addr(osd)
        if isinstance(addrs, dict):
            return addrs.get(kind)
        return addrs

    def send_to_client(self, addr, msg) -> None:
        """Push a message to a client's advertised address (the
        watch/notify path rides the public messenger)."""
        self.public_msgr.send_message(msg, addr)

    def send_to_osd_cluster(self, osd: int, msg) -> None:
        addr = self._osd_addr(osd, "cluster")
        if addr is not None:
            self.cluster_msgr.send_message(msg, addr)

    # -- heartbeats ----------------------------------------------------

    def _hb_tick(self) -> None:
        if not self._running:
            return
        conf = self.ctx.conf
        now = time.monotonic()
        # the boot message is one-shot: on a lossy link a dropped
        # MOSDBoot would strand the OSD forever, so retransmit while
        # the map doesn't show us up (rate-limited)
        if not self.osdmap.is_up(self.whoami) \
                and now - self._boot_sent_at >= 1.0:
            self._boot()
        # likewise the mon's map pushes are one-shot: renew the
        # subscription periodically so a dropped MOSDMap doesn't leave
        # this OSD on a stale map (PGs never instantiated -> every
        # client op bounces with EAGAIN)
        self.mon_client.renew_subs()
        grace = conf.get_val("osd_heartbeat_grace")
        peers = [o for o in self.osdmap.get_up_osds()
                 if o != self.whoami]
        for osd in peers:
            addr = self._osd_addr(osd, "hb")
            if addr is None:
                continue
            self.hb_pending.setdefault(osd, now)
            self.hb_msgr.send_message(
                MPing(stamp=now, epoch=self.map_epoch()), addr)
            # the reply handler may pop the entry between the send and
            # this read (it raced a KeyError here once): a popped entry
            # means the ping was acked — nothing is unacked
            first_unacked = self.hb_pending.get(osd, now)
            if now - first_unacked > grace:
                self.ctx.dout("osd", 1,
                              "osd.%d no reply from osd.%d for %.2fs -> "
                              "reporting failure"
                              % (self.whoami, osd, now - first_unacked))
                self._send_mon(
                    MOSDFailure(reporter=self.whoami, target=osd,
                                failed_for=now - first_unacked,
                                epoch=self.map_epoch()))
                self.hb_pending[osd] = now  # don't spam
        # pg stats to the mon on the same cadence (MPGStats): primaries
        # report scrub errors + rough usage so the HealthMonitor can
        # derive OSD_SCRUB_ERRORS / POOL_FULL mon-side
        self._report_pg_stats()
        self.timer.add_event_after(
            conf.get_val("osd_heartbeat_interval"), self._hb_tick)

    def _mgr_report_tick(self) -> None:
        """The mgr telemetry stream (DaemonServer's MMgrReport role)
        on its OWN cadence — mgr_stats_period, decoupled from the
        heartbeat so operators can tune (or pin off, period=0) the
        report volume without touching failure detection.  Reports are
        delta-encoded (ISSUE 18): after the mgr acks a full baseline
        only changed counters travel, and the schema rides only on the
        first report / hash change; status, pg stats and perf-query
        values still ship whole each period."""
        if not self._running:
            return
        period = self.ctx.conf.get_val("mgr_stats_period")
        if period <= 0:
            # reporting pinned off; poll cheaply for a config change
            self.timer.add_event_after(1.0, self._mgr_report_tick)
            return
        try:
            if self.mgr_addr is not None:
                from ..msg.message import MMgrReport
                rep = self._mgr_reporter.prepare(
                    self.ctx.perf.perf_dump(),
                    self.ctx.perf.perf_schema())
                self.public_msgr.send_message(
                    MMgrReport(daemon_name="osd.%d" % self.whoami,
                               daemon_type="osd",
                               perf=rep["perf"],
                               metadata={"id": self.whoami},
                               status=self._telemetry_status(),
                               pg_stats=self._collect_pg_stats(),
                               perf_schema=rep["schema"],
                               perf_query=(self.perf_query.dump()
                                           if self.perf_query.active
                                           else {}),
                               report_seq=rep["seq"],
                               incarnation=rep["incarnation"],
                               schema_hash=rep["schema_hash"],
                               delta_base=rep["delta_base"]),
                    self.mgr_addr)
        finally:
            # a failed report must never kill the tick chain — the
            # stream self-heals on the next period
            self.timer.add_event_after(period, self._mgr_report_tick)

    def _profile_dump(self) -> dict:
        """The `profile dump` asok payload: every profiler leg in one
        document (what `ceph_cli daemon osd.N profile dump` renders)."""
        from ..common.profiler import PROFILER
        doc = PROFILER.dump()
        if self.tpu_dispatcher is not None:
            doc["dispatch"] = self.tpu_dispatcher.dispatch_profile()
        tier = getattr(self, "hbm_tier", None)
        if tier is not None:
            try:
                doc["hbm"] = tier.stats()
            except Exception:
                pass
        return doc

    def _profile_reset(self) -> dict:
        from ..common.profiler import PROFILER
        PROFILER.reset()
        if self.tpu_dispatcher is not None:
            self.tpu_dispatcher.profile_reset()
        return {"reset": True}

    def _mesh_status(self) -> dict:
        """The `mesh status` asok payload: the local device mesh, this
        OSD's resolved home device, and the whole placement registry
        (every co-resident daemon's assignment)."""
        from ..parallel.placement import PLACEMENT, device_label
        doc = PLACEMENT.assignments()
        doc["whoami"] = self.whoami
        doc["home_device"] = device_label(
            getattr(self, "home_device", None))
        if self.tpu_dispatcher is not None:
            doc["dispatcher_device"] = device_label(
                self.tpu_dispatcher.device)
        tier = getattr(self, "hbm_tier", None)
        if tier is not None:
            doc["hbm_tier_device"] = device_label(tier.device)
        try:
            from ..parallel import rateless
            disp = rateless.get_dispatcher(create=False)
            if disp is not None:
                # per-device health table: ewma_ms / inflight / stolen /
                # redispatched / blacklisted / probation per chip
                doc["rateless"] = disp.status()
        except Exception:
            pass
        return doc

    def _telemetry_status(self) -> dict:
        """The gauge bag riding MMgrReport.status: store capacity
        truth plus device-utilization (dispatch queue depth,
        coalescing, rolling per-codec MB/s, HBM residency)."""
        status: dict = {}
        try:
            status["statfs"] = self.store.statfs()
        except Exception:
            pass
        if self.tpu_dispatcher is not None:
            try:
                status["tpu"] = self.tpu_dispatcher.telemetry()
            except Exception:
                pass
            try:
                # ring occupancy + stall attribution for the mgr's
                # prometheus exposition (ceph_tpu_stage_* series)
                status["dispatch"] = \
                    self.tpu_dispatcher.dispatch_status()
            except Exception:
                pass
        tier = getattr(self, "hbm_tier", None)
        if tier is not None:
            try:
                status["hbm"] = tier.stats()
            except Exception:
                pass
        try:
            from ..parallel import rateless
            disp = rateless.get_dispatcher(create=False)
            if disp is not None:
                status["mesh"] = disp.status()
        except Exception:
            pass
        try:
            if isinstance(self.op_wq, QosShardedOpWQ):
                status["op_queue"] = self.op_wq.dump()
        except Exception:
            pass
        try:
            # map-churn lane (ISSUE 19): the mgr's prometheus module
            # emits ceph_osdmap_epoch{ceph_daemon}, ceph_osd_map_lag_
            # epochs and the ceph_pg_peering_seconds p99 from this bag
            status["osdmap"] = {
                "epoch": self.osdmap.epoch,
                "lag_epochs": self.mon_client.map_lag_epochs(),
                "peering_p99": self.peering_p99(),
            }
        except Exception:
            pass
        return status

    # -- map-churn observability (ISSUE 19) ---------------------------

    def note_peering_done(self, seconds: float) -> None:
        """One interval's peering completed (start_peering ->
        activate): feed the histogram + the p99 ring."""
        try:
            self.perf.hinc("l_osd_peering_us", int(seconds * 1e6))
        except Exception:
            pass
        self._peering_durations.append(seconds)

    def peering_p99(self) -> float:
        """p99 of the last completed interval peerings (seconds)."""
        samples = sorted(self._peering_durations)
        if not samples:
            return 0.0
        return samples[min(len(samples) - 1,
                           int(0.99 * len(samples)))]

    def _osdmap_status(self) -> dict:
        """The `osdmap status` asok payload: applied epoch vs the
        mon's newest, inc-backlog depth behind the advance throttle,
        and the peering lane's occupancy."""
        mc = self.mon_client
        with mc._advance_lock:
            backlog = len(mc._inc_backlog)
        res = self.reservations["peering"].dump()
        return {
            "epoch": self.osdmap.epoch,
            "mon_epoch": mc.mon_epoch,
            "lag_epochs": mc.map_lag_epochs(),
            "inc_backlog": backlog,
            "map_max_advance": mc.map_max_advance,
            "peering_gate": self.peering_gate,
            "peering_active": len(res.get("granted", [])),
            "peering_waiting": len(res.get("waiting", [])),
            "peering_p99": self.peering_p99(),
        }

    # -- fullness ladder ----------------------------------------------

    def used_ratio(self) -> float:
        """Store occupancy fraction from statfs, cached ~0.5s — the
        full/backfillfull gates sit on the client-op and reservation
        hot paths and must not statfs per op."""
        now = time.monotonic()
        ratio, stamp = self._used_stat_cache
        if now - stamp < 0.5:
            return ratio
        try:
            st = self.store.statfs()
            total = st.get("total", 0)
            ratio = (st.get("used", 0) / total) if total else 0.0
        except Exception:
            ratio = 0.0
        self._used_stat_cache = (ratio, now)
        return ratio

    def is_nearfull(self) -> bool:
        return self.used_ratio() >= self._full_ratios[0]

    def is_backfillfull(self) -> bool:
        return self.used_ratio() >= self._full_ratios[1]

    def is_full(self) -> bool:
        return self.used_ratio() >= self._full_ratios[2]

    def reserve_refusal(self, lane: str) -> str | None:
        """Fullness veto on incoming remote-reservation requests: a
        backfillfull OSD refuses new backfill (the primary parks in
        backfill_toofull), and recovery into a FULL osd pauses until
        it drains.  None = no objection."""
        if lane == "backfill" and self.is_backfillfull():
            return "toofull"
        if lane == "recovery" and self.is_full():
            return "toofull"
        return None

    def _sync_reservation_perf(self) -> None:
        granted = waiting = preempted = 0
        for r in self.reservations.values():
            granted += r.granted_total
            waiting += r.num_waiting()
            preempted += r.preempted_total
        self.perf.set("l_osd_reservation_granted", granted)
        self.perf.set("l_osd_reservation_waiting", waiting)
        self.perf.set("l_osd_reservation_preempted", preempted)
        try:
            self.perf.set("l_osd_map_lag_epochs",
                          self.mon_client.map_lag_epochs())
        except Exception:
            pass

    def _collect_pg_stats(self) -> dict:
        """Primary PGs' stat rows (shared by the mon MPGStats report
        and the mgr telemetry report)."""
        with self.lock:
            pgs = [pg for pg in self.pgs.values() if pg.is_primary()]
        stats = {}
        for pg in pgs:
            try:
                stats[str(pg.pgid)] = pg.get_stats()
            except Exception:
                continue
        return stats

    def _report_pg_stats(self) -> None:
        """Primary PGs' stats to the mon (MPGStats).  Rate-limited to
        1s and skipped entirely while nothing changed cheaply-visibly
        would be nicer, but at framework scale the report is a few
        dict copies; the mon dedups derived-state churn itself."""
        now = time.monotonic()
        if now - getattr(self, "_last_pg_report", 0.0) < 1.0:
            return
        self._last_pg_report = now
        stats = self._collect_pg_stats()
        # slow-request count rides the same report (OSD_SLOW_OPS feed);
        # it must go out even with no primary-PG stats so a wedged op
        # on a just-demoted primary still surfaces
        slow = self.op_tracker.slow_ops_count()
        # device-runtime health feeds ride the same report: in-window
        # recompile count (DEVICE_RECOMPILE_STORM) and HBM tier
        # occupancy (DEVICE_MEM_NEARFULL)
        recompiles = 0
        from ..common.profiler import PROFILER
        if PROFILER.enabled:
            try:
                recompiles = PROFILER.storm_count()
            except Exception:
                pass
        nearfull = 0.0
        tier = getattr(self, "hbm_tier", None)
        if tier is not None:
            try:
                occ = tier.occupancy()
                if occ >= self.ctx.conf.get_val(
                        "osd_hbm_nearfull_ratio"):
                    nearfull = occ
            except Exception:
                pass
        # store occupancy rides every report too: the HealthMonitor
        # ranks it against the mon_osd_*_ratio ladder (OSD_NEARFULL /
        # OSD_BACKFILLFULL / OSD_FULL) — an over-threshold ratio keeps
        # reports flowing via the alert latch so the check can CLEAR
        used = self.used_ratio()
        # blacklisted mesh devices ride the report too (DEVICE_DEGRADED);
        # the alert latch keeps reports flowing after probation re-admits
        # the chip so the mon sees the zero and clears the check
        degraded = 0
        try:
            from ..parallel import rateless
            disp = rateless.get_dispatcher(create=False)
            if disp is not None:
                degraded = disp.degraded()
        except Exception:
            pass
        self._sync_reservation_perf()
        alerting = slow or recompiles or nearfull or degraded \
            or used >= self._full_ratios[0]
        if not stats and not alerting \
                and not getattr(self, "_alert_reported", False):
            return
        self._alert_reported = bool(alerting)
        from ..msg.message import MPGStats
        self._send_mon(MPGStats(osd_id=self.whoami, pg_stats=stats,
                                epoch=self.map_epoch(), slow_ops=slow,
                                recompiles=recompiles,
                                mem_nearfull=nearfull,
                                used_ratio=used,
                                devices_degraded=degraded))

    # -- dispatch ------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        t = msg.get_type()
        if t == "MPing":
            self.hb_msgr.send_message(
                MPingReply(stamp=msg.stamp, epoch=self.map_epoch()),
                msg.from_addr)
            return True
        if t == "MPingReply":
            osd = msg.from_name[1] if msg.from_name else None
            if osd is not None:
                self.hb_peers[osd] = msg.stamp
                self.hb_pending.pop(osd, None)
            return True
        if t == "MOSDOp":
            self._enqueue_client_op(msg)
            return True
        if t == "MOSDPerfQuery":
            self._handle_perf_query(msg)
            return True
        if t == "MMgrReportAck":
            self._mgr_reporter.ack(msg.ack_seq, resync=msg.resync)
            return True
        if t == "MTraceFragment":
            self._handle_trace_verdict(msg)
            return True
        if t in ("MOSDECSubOpWrite", "MOSDECSubOpWriteReply",
                 "MOSDECSubOpRead", "MOSDECSubOpReadReply",
                 "MOSDECSubOpRepairRead", "MOSDECSubOpRepairReadReply",
                 "MOSDRepOp", "MOSDRepOpReply", "MOSDPGScan",
                 "MOSDPGPush", "MOSDPGPull", "MOSDPGQuery",
                 "MOSDPGNotify", "MOSDPGLog", "MWatchNotifyAck",
                 "MBackfillReserve"):
            self._enqueue_sub_op(msg)
            return True
        return False

    def _handle_perf_query(self, msg) -> None:
        """mgr -> OSD perf-query subscription control
        (MOSDPerfQuery add/remove/list)."""
        from ..msg.message import MOSDPerfQueryReply
        result = 0
        if msg.op == "add":
            self.perf_query.add_query(msg.query_id, msg.spec)
        elif msg.op == "remove":
            if not self.perf_query.remove_query(msg.query_id):
                result = -2            # ENOENT
        queries = (self.perf_query.list_queries()
                   if msg.op == "list" else {})
        if msg.from_addr is not None:
            self.public_msgr.send_message(
                MOSDPerfQueryReply(query_id=msg.query_id,
                                   result=result, queries=queries),
                msg.from_addr)

    # -- tail-based trace retention (SLO forensics) --------------------

    def _trace_tail_verdict(self, pg, span, op, result,
                            op_type: str) -> tuple[bool, str]:
        """Root-side keep/drop for a completed client op's trace.
        Returns (kept, reason).  On keep: this daemon's buffered
        fragments ship to the mgr and the verdict broadcasts to the
        acting set so replicas release theirs.  Spans still open at
        reply time (a synchronous read's pg_do_op) miss the shipment —
        the same snapshot boundary the flight recorder has."""
        tail = self.tail
        spans = tail.take(span.trace_id) or []
        pool_name = ""
        if pg is not None:
            pool = self.osdmap.pools.get(pg.pgid.pool)
            if pool is not None:
                pool_name = pool.name
        duration = op.duration
        kept, reason = tail.verdict(pool_name, duration, result, spans)
        self._sync_tail_perf()
        if not kept:
            self.perf.inc("l_osd_trace_tail_dropped")
            return False, ""
        self.perf.inc("l_osd_trace_tail_kept_" + reason)
        # slo/error keeps are forensic: pull the replicas' fragments
        # for a full cross-daemon tree.  Reservoir keeps are the
        # baseline latency population — the root's own tree suffices,
        # and skipping the broadcast keeps the steady-state sampling
        # cost at one shipment per kept op (replica fragments TTL out)
        if pg is not None and reason != "reservoir":
            from ..msg.message import MTraceFragment
            for peer in getattr(pg, "acting", ()):
                if peer == self.whoami:
                    continue
                self._trace_ship_enqueue("osd", peer, MTraceFragment(
                    op="verdict", trace_id=span.trace_id,
                    daemon_name="osd.%d" % self.whoami,
                    pool=pool_name, op_type=op_type, keep=True,
                    reason=reason, duration=duration))
        self._ship_trace_fragments(span.trace_id, spans, pool_name,
                                   op_type, duration, reason)
        return True, reason

    def _ship_trace_fragments(self, trace_id: int, spans: list,
                              pool: str, op_type: str, duration: float,
                              reason: str) -> None:
        """OSD -> mgr: one MTraceFragment with this daemon's span
        dumps for a kept trace, anchored so the mgr can place the
        sender's monotonic stamps on a shared wall axis.  The anchor
        pair is stamped HERE (one instant) — the ship lane may send
        it later, which cannot skew the alignment."""
        if not spans:
            return
        from ..msg.message import MTraceFragment
        self.perf.inc("l_osd_trace_tail_shipped_spans", len(spans))
        # bulk diagnostic payload: pack the span records into ONE
        # opaque blob so the wire codec prices a single bytes value,
        # not hundreds of tagged ones (json round-trips the compact
        # dump_wire lists; exotic keyval types fall back to raw)
        try:
            import json as _json
            spans = _json.dumps(spans,
                                separators=(",", ":")).encode()
        except (TypeError, ValueError):
            pass
        self._trace_ship_enqueue("mgr", None, MTraceFragment(
            op="ship", trace_id=trace_id,
            daemon_name="osd.%d" % self.whoami,
            pool=pool, op_type=op_type, keep=True,
            reason=reason, duration=duration, spans=spans,
            anchor_wall=time.time(),
            anchor_mono=time.monotonic()))

    def _trace_ship_enqueue(self, kind: str, target, msg) -> None:
        with self._trace_ship_cond:
            self._trace_ship_q.append((kind, target, msg))
            self._trace_ship_cond.notify()

    def _trace_ship_loop(self) -> None:
        while True:
            with self._trace_ship_cond:
                while not self._trace_ship_q and \
                        not self._trace_ship_stop:
                    self._trace_ship_cond.wait(0.5)
                if self._trace_ship_stop and not self._trace_ship_q:
                    return
                batch = list(self._trace_ship_q)
                self._trace_ship_q.clear()
            for kind, target, msg in batch:
                try:
                    if kind == "osd":
                        self.send_to_osd_cluster(target, msg)
                    elif self.mgr_addr is not None:
                        self.public_msgr.send_message(msg,
                                                      self.mgr_addr)
                except Exception:
                    pass       # a lost fragment is a lost fragment

    def _handle_trace_verdict(self, msg) -> None:
        """Replica side: the root's keep verdict arrived — ship the
        fragments buffered under that trace_id (drop verdicts are
        never sent; the pending TTL reaps those fragments)."""
        spans = self.tail.take(msg.trace_id)
        if msg.keep and spans:
            self._ship_trace_fragments(msg.trace_id, spans, msg.pool,
                                       msg.op_type, msg.duration,
                                       msg.reason)
        self._sync_tail_perf()

    def _sync_tail_perf(self) -> None:
        """Fold the TailSampler's TTL-reap count into the perf stream
        (the sampler itself has no perf handle)."""
        expired = self.tail.stats["pending_expired"]
        delta = expired - self._tail_expired_synced
        if delta > 0:
            self._tail_expired_synced = expired
            self.perf.inc("l_osd_trace_tail_expired", delta)

    WRITE_OP_KINDS = frozenset((
        "create", "write", "writefull", "append", "zero", "truncate",
        "remove", "setxattr", "rmxattr", "omap_set", "omap_rm",
        "omap_clear", "resetxattrs", "watch", "unwatch", "notify",
        "rollback", "call"))

    #: mutating ops still admitted on a FULL osd: they free space (or
    #: add none), and rejecting them would wedge a full cluster full
    #: forever (the reference admits deletes on a full pool the same
    #: way)
    FULL_EXEMPT_OP_KINDS = frozenset((
        "remove", "rmxattr", "omap_rm", "omap_clear", "truncate",
        "zero", "unwatch"))

    def _check_op_caps(self, msg) -> str | None:
        """OSDCap enforcement (src/osd/OSDCap.cc is_capable, called
        from PrimaryLogPG::do_op's cap check): the connection's
        verified ticket caps must cover the op's rwx needs on the
        target pool, and the ticket's key version must clear the
        authmap revocation watermark.  Returns a denial reason, or
        None when allowed (always None on auth-less clusters)."""
        if self.auth is None or msg.pgid is None:
            return None               # pgid-less op: EAGAIN path below
        info = getattr(msg, "auth_info", None)
        if not info:
            return "unauthenticated connection"
        authmap = self.mon_client.authmap or {}
        floor = authmap.get("revoked", {}).get(info["entity"], 0)
        if info.get("key_version", 1) < floor:
            return "key revoked for %s" % info["entity"]
        caps = info.get("_parsed_caps")
        if caps is None:
            from ..auth.caps import parse_caps
            try:
                caps = parse_caps(info.get("caps") or "")
            except Exception:
                return "malformed caps"
            info["_parsed_caps"] = caps   # per-connection cache
        pgid = self._normalize_pgid(msg.pgid)
        pool = self.osdmap.pools.get(pgid.pool)
        pool_name = pool.name if pool is not None else None
        from ..msg.message import OSD_READ_OPS
        need = set()
        for op in msg.ops:
            if not op:
                continue
            if op[0] == "call":
                need.add("x")
            elif op[0] in OSD_READ_OPS:
                need.add("r")
            else:
                # fail CLOSED: every mutating op kind — and any kind
                # this table has never heard of — demands 'w'.  The
                # old shape defaulted unknown kinds to 'r', so a new
                # op added to the PG without a matching entry here
                # (omap_clear once) silently bypassed write caps.
                need.add("w")
        if not caps.is_capable("".join(sorted(need)), pool_name):
            return "caps %r do not cover %s on pool %r" % (
                info.get("caps", ""), "".join(sorted(need)), pool_name)
        return None

    def _enqueue_client_op(self, msg) -> None:
        denial = self._check_op_caps(msg)
        if denial is not None:
            import errno as _errno
            self.public_msgr.send_message(
                MOSDOpReply(tid=msg.tid, result=-_errno.EACCES,
                            data=denial.encode(),
                            map_epoch=self.map_epoch()),
                msg.from_addr)
            return
        pg = self._get_pg(msg.pgid and self._normalize_pgid(msg.pgid))
        client_addr = msg.from_addr
        # retransmit dedup for non-idempotent ops (the client resends
        # with the SAME tid on slow replies): an op still executing is
        # dropped (the eventual reply satisfies the client); a finished
        # one replays its recorded reply (PG log reqid dedup role)
        mutating = any(op and op[0] in self.WRITE_OP_KINDS
                       for op in msg.ops)
        # full-ratio protection: a FULL osd rejects writes at admission
        # with ENOSPC — reads keep flowing (the data is still there)
        # and space-freeing ops stay admitted so the operator can dig
        # the cluster out
        if mutating and self.is_full() and \
                any(op and op[0] in self.WRITE_OP_KINDS
                    and op[0] not in self.FULL_EXEMPT_OP_KINDS
                    for op in msg.ops):
            import errno as _errno
            self.public_msgr.send_message(
                MOSDOpReply(tid=msg.tid, result=-_errno.ENOSPC,
                            data=b"osd full",
                            map_epoch=self.map_epoch()),
                client_addr)
            return
        dedup_key = ((getattr(msg, "session", "") or msg.client_id,
                      msg.tid) if mutating else None)
        if dedup_key is not None:
            with self.lock:
                cached = self._op_replies.get(dedup_key, False)
                if cached is False:
                    # atomically claim execution (a racing duplicate
                    # must not also execute)
                    self._op_replies[dedup_key] = None
            if cached is None:
                return                 # in flight: drop the duplicate
            if cached is not False:
                self.public_msgr.send_message(
                    MOSDOpReply(tid=msg.tid, result=cached[0],
                                data=cached[1],
                                map_epoch=self.map_epoch()),
                    client_addr)
                return
        op = self.op_tracker.create_request(
            "osd_op(tid=%s pg=%s %s)" % (msg.tid, msg.pgid,
                                         getattr(msg, "op", "?")))
        # perf-query latency anchor: attribution measures from the
        # op_request's initiation, not from whenever pg.do_op first
        # ran — queue wait is part of what the client experienced
        msg._pq_start = op.initiated_mono
        # stitch under the client's trace when the envelope carries a
        # context; a context-less op (old client, tracing off there)
        # still gets an OSD-rooted trace subject to local sampling
        span = self.tracer.continue_trace(
            "osd_op", getattr(msg, "trace_id", 0),
            getattr(msg, "parent_span", 0))
        if not span.valid():
            span = self.tracer.start_trace("osd_op")
        span.keyval("tid", msg.tid)
        span.keyval("pg", str(msg.pgid))
        msg.trace = span   # receive-side annotation: the PG and the
        #                    backends hang their spans off it

        replied = [False]
        # dispatch-throttle hand-off: the messenger attached an
        # idempotent release closure and would put the units back right
        # after ms_dispatch returns — adopting moves the release to the
        # REPLY, so queued-but-unserved ops keep holding their budget
        # (that occupancy is exactly what backpressures the reader)
        throttle_release = getattr(msg, "throttle_release", None)

        self.perf.inc("op")
        # read/write split + real payload accounting: the op's byte
        # operands ARE the write payload (MOSDOp carries no top-level
        # data field — the old getattr(msg, "data") read always 0)
        in_bytes = sum(len(arg) for op_t in msg.ops for arg in op_t
                       if isinstance(arg, (bytes, bytearray)))
        self.perf.inc("op_w" if mutating else "op_r")
        self.perf.inc("op_in_bytes", in_bytes)

        def reply(result, data):
            if replied[0]:
                return
            replied[0] = True
            if throttle_release is not None:
                throttle_release()
            if dedup_key is not None:
                with self.lock:
                    if result == -11:
                        # EAGAIN is not an outcome: the client retries
                        # the same tid and it must execute next time
                        self._op_replies.pop(dedup_key, None)
                    else:
                        self._op_replies[dedup_key] = (result, data)
            if isinstance(data, (bytes, bytearray)):
                self.perf.inc("op_out_bytes", len(data))
            elif isinstance(data, list):
                self.perf.inc("op_out_bytes", sum(
                    len(d) for d in data
                    if isinstance(d, (bytes, bytearray))))
            self.perf.tinc("op_latency", op.duration)
            self.perf.tinc("l_osd_op_trace_total", op.duration)
            self.perf.hinc("l_osd_op_trace_us",
                           max(0, int(op.duration * 1e6)))
            op.mark_commit_sent()
            # dmclock phase stamp (set by the QoS shard at dequeue):
            # reservation-phase completions feed the client's rho
            self.public_msgr.send_message(
                MOSDOpReply(tid=msg.tid, result=result, data=data,
                            map_epoch=self.map_epoch(),
                            qos_phase=getattr(msg, "_qos_phase", "")),
                client_addr)
            span.keyval("result", result)
            span.finish()
            # tail-sampler verdict (SLO forensics): judge the finished
            # trace HERE, where latency and result are known — keep
            # ships this daemon's fragments to the mgr and the verdict
            # to the acting set; drop sends nothing anywhere (replica
            # TTLs reap the unjudged fragments)
            kept, reason = False, ""
            if span.valid():
                try:
                    kept, reason = self._trace_tail_verdict(
                        pg, span, op, result,
                        "write" if mutating else "read")
                except Exception:
                    pass
            # flight recorder: snapshot the finished trace tree onto
            # the op BEFORE mark_done files it into history — the
            # historic dump keeps the cross-daemon tree even after the
            # live span ring rolls over
            if span.valid():
                try:
                    op.set_trace(span.trace_id,
                                 self.tracer.dump(
                                     trace_id=span.trace_id),
                                 kept=kept, reason=reason)
                except Exception:
                    pass
            op.mark_done()

        if pg is None:
            op.mark_event("no_pg")
            reply(-11, None)
            return
        op.mark_event("queued_for_pg")
        q0 = time.monotonic()

        def run(m, r):
            t_run = time.monotonic()
            self.perf.tinc("l_osd_op_trace_queue", t_run - q0)
            span.child_interval("op_queue", q0, t_run)
            op.mark_event("reached_pg")
            op.mark_started()
            try:
                with span.child("pg_do_op"):
                    pg.do_op(m, r)
            except Exception:
                # never leak the op as in-flight-forever or leave the
                # client hanging: fail it with EIO
                op.mark_event("exception")
                reply(-5, None)
                raise
            finally:
                self.perf.tinc("l_osd_op_trace_pg",
                               time.monotonic() - t_run)

        if throttle_release is not None:
            msg._throttle_adopted = True
        self.op_wq.queue(pg.pgid, run, msg, reply,
                         klass=self._qos_class_for(pg.pool),
                         priority=self.client_op_priority,
                         cost=in_bytes,
                         delta=getattr(msg, "qos_delta", 0.0),
                         rho=getattr(msg, "qos_rho", 0.0),
                         qos_obj=msg)

    def _normalize_pgid(self, raw_pgid):
        pool = self.osdmap.pools.get(raw_pgid.pool)
        if pool is None:
            return raw_pgid
        return pool.raw_pg_to_pg(raw_pgid)

    def _enqueue_sub_op(self, msg) -> None:
        pg = self._get_pg(msg.pgid)
        if pg is None:
            return
        t = msg.get_type()

        def run():
            backend = pg.backend
            if t == "MOSDECSubOpWrite":
                backend.handle_sub_write(msg)
            elif t == "MOSDECSubOpWriteReply":
                backend.handle_sub_write_reply(msg)
            elif t == "MOSDECSubOpRead":
                backend.handle_sub_read(msg)
            elif t == "MOSDECSubOpReadReply":
                backend.handle_sub_read_reply(msg)
            elif t == "MOSDECSubOpRepairRead":
                backend.handle_repair_read(msg)
            elif t == "MOSDECSubOpRepairReadReply":
                backend.handle_repair_read_reply(msg)
            elif t == "MOSDRepOp":
                backend.handle_rep_op(msg)
            elif t == "MOSDRepOpReply":
                backend.handle_rep_op_reply(msg)
            elif t == "MOSDPGScan":
                pg.handle_scan(msg)
            elif t == "MOSDPGPush":
                pg.handle_push(msg)
            elif t == "MOSDPGPull":
                pg.handle_pull(msg)
            elif t == "MOSDPGQuery":
                pg.handle_query(msg)
            elif t == "MOSDPGNotify":
                pg.handle_notify(msg)
            elif t == "MOSDPGLog":
                pg.handle_log(msg)
            elif t == "MWatchNotifyAck":
                pg.handle_notify_ack(msg)
            elif t == "MBackfillReserve":
                pg.handle_reserve(msg)

        # recovery data movement (push/pull/scan — and the regenerating
        # repair fraction reads, which only exist to rebuild a shard)
        # must ride the recovery class or QoS settings have no effect
        # on actual backfill traffic
        if t in ("MOSDPGPush", "MOSDPGScan", "MOSDPGPull",
                 "MOSDPGQuery", "MOSDPGNotify", "MOSDPGLog",
                 "MOSDECSubOpRepairRead", "MOSDECSubOpRepairReadReply",
                 "MBackfillReserve"):
            self.op_wq.queue(msg.pgid, run, klass="recovery",
                             priority=self.recovery_op_priority)
        else:
            self.op_wq.queue(msg.pgid, run, klass="osd_subop",
                             priority=self.client_op_priority)
