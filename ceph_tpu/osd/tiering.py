"""Cache tiering: HitSet, promote/proxy, flush/evict, tier agent.

Role of the reference's cache-tier machinery:

  HitSet            src/osd/HitSet.{h,cc} (BloomHitSet over
                    src/common/bloom_filter.hpp): per-PG bloom filters
                    of recently-accessed objects, rolled every
                    `hit_set_period` seconds and archived (up to
                    `hit_set_count`) as PG-local objects the agent
                    consults for eviction temperature.
  maybe_handle_cache  PrimaryLogPG::maybe_handle_cache_detail
                    (src/osd/PrimaryLogPG.cc:2169-2380): an op hitting
                    a cache-tier PG for a non-resident object either
                    PROMOTES it (copy-from the base pool, then replay
                    the op locally), PROXIES it (serve from base
                    without promoting), or forwards, per cache_mode.
  flush / evict     PrimaryLogPG::start_flush / agent_maybe_evict
                    (:8542,:8700): dirty objects are written back to
                    the base pool (deletes propagate as removes), then
                    marked clean; clean cold objects are dropped from
                    the cache entirely.
  TierAgentState    src/osd/TierAgentState.h: the background agent
                    wakes periodically, estimates fullness/dirtyness
                    against `target_max_objects`/`target_max_bytes`,
                    and queues flushes and evictions.

Threading: the op-shard worker must never block on cross-pool IO (the
base pool's PGs may live on this same OSD), so every tier operation is
a three-phase pipeline:

  capture  (op-shard worker; serialized with client ops for the PG)
  base IO  (the daemon's tier thread pool, via an internal RadosClient
            submitting with ignore_overlay — the objecter's
            CEPH_OSD_FLAG_IGNORE_OVERLAY analog)
  install  (op-shard worker again; verifies nothing raced, applies an
            internal replicated transaction, answers waiters)

Simplifications vs the reference (documented contract): promotion and
flush move the object HEAD (data + user xattrs + omap); snapshots taken
while an object lives in the cache work normally inside the cache pool,
and an object with clones or watchers refuses eviction with EBUSY
instead of evicting per-clone.  Forward-mode proxied-write
exactly-once state (_proxy_done/_proxy_inflight) is memory-only on
the cache primary: after a cache-PG primary failover, a client
retransmit of a write the base pool already applied can be re-proxied
and double-applied (non-idempotent ops like append).  The reference's
forward mode carried the same caveat and was deprecated for it —
operators should drain the tier via flush before relying on forward
mode across failovers (the promote path is not affected: it adopts
durable base reqids).
"""

from __future__ import annotations

import hashlib
import math
import struct
import threading
import time
from collections import deque

from ..msg.message import OSD_READ_OPS as _READ_KINDS

__all__ = ["HitSet", "PGTier", "DIRTY_ATTR", "HITSET_PREFIX"]

DIRTY_ATTR = "_dirty"
HITSET_PREFIX = "_hitset_"

# how long a confirmed base-pool miss is believed before re-probing
ABSENT_TTL = 1.0


class HitSet:
    """Bloom filter of object names (BloomHitSet,
    src/osd/HitSet.h:300-420 over src/common/bloom_filter.hpp).

    Sized from (target_size, fpp) with the standard optimal-bits
    formula; k hash probes derive from one SHA-1 via the Kirsch-
    Mitzenmacher double-hashing construction."""

    def __init__(self, target_size: int = 1000, fpp: float = 0.05,
                 nbits: int | None = None, k: int | None = None,
                 data: bytes | None = None):
        if nbits is None:
            nbits = max(64, int(-target_size * math.log(max(fpp, 1e-9))
                                / (math.log(2) ** 2)))
        self.nbits = nbits
        if k is None:
            k = max(1, round(nbits / max(target_size, 1) * math.log(2)))
        self.k = min(k, 16)
        self.bits = bytearray((nbits + 7) // 8) if data is None \
            else bytearray(data)
        self.count = 0

    def _probes(self, name: str):
        d = hashlib.sha1(name.encode()).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:16], "little") | 1
        for i in range(self.k):
            yield (h1 + i * h2) % self.nbits

    def insert(self, name: str) -> None:
        for p in self._probes(name):
            self.bits[p >> 3] |= 1 << (p & 7)
        self.count += 1

    def contains(self, name: str) -> bool:
        return all(self.bits[p >> 3] & (1 << (p & 7))
                   for p in self._probes(name))

    def encode(self) -> bytes:
        return struct.pack("<IIQ", self.nbits, self.k, self.count) \
            + bytes(self.bits)

    @classmethod
    def decode(cls, raw: bytes) -> "HitSet":
        nbits, k, count = struct.unpack_from("<IIQ", raw)
        hs = cls(nbits=nbits, k=k, data=raw[16:])
        hs.count = count
        return hs


class PGTier:
    """Per-PG cache-tier state + logic, attached lazily to PGs whose
    pool is a tier (pg_pool_t.tier_of >= 0)."""

    def __init__(self, pg):
        self.pg = pg
        self.lock = threading.Lock()
        from ..common.bounded import BoundedDict
        self._promoting: dict = {}    # oid -> [waiter continuations]
        # bounded: one-shot accesses must not accumulate forever on a
        # long-lived cache PG fronting a large base pool
        self._absent: BoundedDict = BoundedDict(4096)
        self._atime: BoundedDict = BoundedDict(65536)
        self.dirty_at: dict = {}      # oid -> first-dirty stamp
        self.hit_set: HitSet | None = None
        self._hit_set_start = 0.0
        self._archives: deque = deque()     # (name, HitSet), oldest first
        self._archives_loaded = False
        self._agent_busy = False
        self._agent_inflight: set = set()
        # proxied-WRITE dedup: the base pool sees the internal client's
        # (session, tid), not the real client's, so the exactly-once
        # guarantee must be re-established here — a retransmit of a
        # proxied write must attach to (or replay) the first proxy, not
        # spawn a second one (double-applied append otherwise)
        self._proxy_done: BoundedDict = BoundedDict()
        self._proxy_inflight: dict = {}   # (session, tid) -> [reply_fns]

    # ------------------------------------------------------------------
    # entry from PG.do_op

    def maybe_handle(self, msg, reply_fn) -> bool:
        """True = the tier path owns this op (parked, proxied, or
        answered); False = run the normal local execution."""
        pg = self.pg
        pool = pg.pool
        mode = pool.cache_mode
        oid = msg.oid
        op0 = msg.ops[0][0] if msg.ops else ""
        if op0 in ("cache_flush", "cache_try_flush", "cache_evict"):
            self._handle_cache_op(op0, msg, reply_fn)
            return True
        if op0 == "list" or not oid:
            return False    # PG-scoped ops list THIS pool's contents
        if isinstance(oid, str) and oid.startswith(HITSET_PREFIX):
            return False              # internal objects: no tier games
        is_write = any(op[0] not in _READ_KINDS for op in msg.ops)
        if mode == "forward":
            # drain mode: EVERYTHING forwards to the base, residency
            # notwithstanding (how the reference drains a cache before
            # dismantling it). Watch/notify cannot forward — the base
            # PG would register the OSD's INTERNAL client as the
            # watcher and notifies would never reach the real one
            if any(op[0] in ("watch", "unwatch", "notify")
                   for op in msg.ops):
                reply_fn(-95, None)   # EOPNOTSUPP during drain
                return True
            if is_write:
                self._start_proxy_write(msg, reply_fn)
            else:
                pg.daemon.tier_submit(self._do_proxy, msg, reply_fn)
            return True
        if mode == "readonly" and is_write:
            # a readonly cache never accepts writes — not even for
            # resident objects (they would shadow the base copy and be
            # silently lost on evict)
            reply_fn(-30, None)       # EROFS
            return True
        self._record_hit(oid)
        if pg._object_size(oid) is not None:
            return False              # resident (whiteouts included)
        now = time.monotonic()
        with self.lock:
            stamp = self._absent.get(oid)
            absent = stamp is not None and now - stamp < ABSENT_TTL
            if stamp is not None and not absent:
                del self._absent[oid]
            if absent and is_write:
                # the write is about to create it locally
                self._absent.pop(oid, None)
        if mode == "readproxy" and not is_write:
            # non-resident read: serve from the base, no promote
            pg.daemon.tier_submit(self._do_proxy, msg, reply_fn)
            return True
        # writeback (all ops), readproxy writes, readonly reads
        if absent:
            return False        # local miss is the true answer
        self._start_promote(oid, msg, reply_fn)
        return True

    # ------------------------------------------------------------------
    # promotion (PrimaryLogPG::promote_object)

    def _start_promote(self, oid, msg, reply_fn) -> None:
        pg = self.pg
        rerun = lambda: pg.do_op(msg, reply_fn)   # noqa: E731
        with self.lock:
            waiters = self._promoting.get(oid)
            if waiters is not None:
                waiters.append(rerun)
                return
            self._promoting[oid] = [rerun]
        pg.daemon.tier_submit(self._do_promote, oid)

    def _do_promote(self, oid) -> None:
        """Tier thread: fetch a CONSISTENT (data, xattrs, omap)
        snapshot from the base pool in one COPY_GET op — three
        separate reads could interleave with a base-pool writer and
        install a torn object."""
        pg = self.pg
        base = pg.pool.tier_of
        cl = pg.daemon.tier_client()
        try:
            r, snap = cl.submit_op(base, oid, [("copy_get",)],
                                   ignore_overlay=True)
            if r == -2:
                fetched = None
            elif r < 0:
                raise OSError(-r, "promote copy_get failed")
            else:
                fetched = (bytes(snap["data"]), dict(snap["attrs"]),
                           dict(snap["omap"]),
                           list(snap.get("reqids") or []))
        except Exception:
            # transient base trouble: release the waiters after a
            # beat — each re-entry re-promotes until the client's own
            # deadline gives up
            pg.daemon.timer.add_event_after(0.5, self._fail_promote, oid)
            return
        pg.daemon.op_wq.queue(pg.pgid, self._finish_promote, oid,
                              fetched, klass="client",
                              priority=pg.daemon.client_op_priority)

    def _run_waiters(self, waiters) -> None:
        """Re-enter parked ops through the op queue, NOT inline: the
        caller may be a timer/finisher thread, and client-op execution
        must stay serialized on the PG's op-shard worker."""
        pg = self.pg
        for w in waiters:
            pg.daemon.op_wq.queue(pg.pgid, w, klass="client",
                                  priority=pg.daemon.client_op_priority)

    def _fail_promote(self, oid) -> None:
        with self.lock:
            waiters = self._promoting.pop(oid, [])
        self._run_waiters(waiters)

    def _finish_promote(self, oid, fetched) -> None:
        """Op-shard worker: install the object if nothing raced, then
        answer everyone who parked on the promote."""
        pg = self.pg

        def release():
            with self.lock:
                waiters = self._promoting.pop(oid, [])
            self._run_waiters(waiters)

        if fetched is None:
            with self.lock:
                self._absent[oid] = time.monotonic()
            release()
            return
        if pg._object_size(oid) is not None:
            release()                 # a racing write created it
            return
        data, xattrs, omap, reqids = fetched
        from .pg import is_user_xattr
        from .pg_transaction import PGTransaction
        t = PGTransaction()
        t.create(oid)
        if data:
            t.write(oid, 0, data)
        for k, v in xattrs.items():
            if is_user_xattr(k):
                t.setattr(oid, k, v)
        if omap:
            t.omap_setkeys(oid, omap)
        # adopt the base object's client reqids (finish_promote role):
        # a retransmit of a write the BASE already applied must replay,
        # not re-apply, now that this PG answers for the object
        with pg.lock:
            for reqid, version in reqids:
                key = tuple(reqid)
                if key not in pg._reqids:
                    pg._reqids[key] = version
        if not pg.submit_internal_write(oid, t, len(data), release):
            release()   # demoted meanwhile: waiters retarget via EAGAIN

    # ------------------------------------------------------------------
    # proxying (PrimaryLogPG::do_proxy_read / do_proxy_write)

    def _do_proxy(self, msg, reply_fn) -> None:
        """Tier thread: forward the whole op vector to the base pool
        and relay the answer."""
        pg = self.pg
        cl = pg.daemon.tier_client()
        try:
            r, data = cl.submit_op(
                pg.pool.tier_of, msg.oid, msg.ops,
                snapc=getattr(msg, "snapc", (0, ())),
                snap=getattr(msg, "snap", 0), ignore_overlay=True)
        except Exception:
            r, data = -110, None      # ETIMEDOUT
        reply_fn(r, data)

    def _start_proxy_write(self, msg, reply_fn) -> None:
        """Dedup admission for proxied writes (exactly-once): a
        retransmitted (session, tid) joins the in-flight proxy or
        replays its recorded outcome."""
        key = (getattr(msg, "session", ""), msg.tid)
        if not key[0]:
            self.pg.daemon.tier_submit(self._do_proxy, msg, reply_fn)
            return
        with self.lock:
            done = self._proxy_done.get(key)
            if done is None:
                fns = self._proxy_inflight.get(key)
                if fns is not None:
                    fns.append(reply_fn)
                    return
                self._proxy_inflight[key] = [reply_fn]
        if done is not None:
            reply_fn(*done)
            return
        self.pg.daemon.tier_submit(self._do_proxy_write, msg, key)

    def _do_proxy_write(self, msg, key) -> None:
        pg = self.pg
        cl = pg.daemon.tier_client()
        try:
            r, data = cl.submit_op(
                pg.pool.tier_of, msg.oid, msg.ops,
                snapc=getattr(msg, "snapc", (0, ())),
                ignore_overlay=True)
        except Exception:
            r, data = -110, None
        with self.lock:
            # recorded even on timeout: the base-side op MAY have
            # applied, so a retransmit must get this answer rather
            # than re-apply a non-idempotent write
            self._proxy_done[key] = (r, data)
            fns = self._proxy_inflight.pop(key, [])
        for fn in fns:
            fn(r, data)

    # ------------------------------------------------------------------
    # flush (PrimaryLogPG::start_flush): three phases

    def _handle_cache_op(self, kind, msg, reply_fn) -> None:
        pg = self.pg
        if not pg.active_for_write():
            with pg.lock:
                pg.waiting_for_active.append(
                    lambda: pg.do_op(msg, reply_fn))
            return
        if kind == "cache_evict":
            self._evict(msg.oid, reply_fn)
        else:
            self._flush_capture(msg.oid, kind == "cache_try_flush",
                                reply_fn)

    def _flush_capture(self, oid, try_flush: bool, reply_fn) -> None:
        """Op-shard worker: snapshot (version, bytes, attrs, omap)."""
        pg = self.pg
        if pg._object_size(oid) is None:
            reply_fn(-2, None)
            return
        if pg.local_getattr(oid, DIRTY_ATTR) is None:
            reply_fn(0, None)         # already clean
            return
        v0 = pg._object_version(oid)
        whiteout = pg._is_whiteout(oid)
        cid = pg.cid_of_shard(-1)
        if whiteout:
            captured = (v0, None, {}, {})
        else:
            from .pg import user_xattrs
            try:
                data = pg.store.read(cid, oid)
            except KeyError:
                data = b""
            try:
                attrs = user_xattrs(pg.store.getattrs(cid, oid))
            except KeyError:
                attrs = {}
            try:
                omap = pg.store.omap_get(cid, oid)
            except KeyError:
                omap = {}
            captured = (v0, bytes(data), attrs, omap)
        pg.daemon.tier_submit(self._do_flush_io, oid, captured,
                              try_flush, reply_fn)

    def _do_flush_io(self, oid, captured, try_flush, reply_fn) -> None:
        """Tier thread: push the capture to the base pool."""
        pg = self.pg
        v0, data, attrs, omap = captured
        cl = pg.daemon.tier_client()
        base = pg.pool.tier_of
        try:
            if data is None:          # flushing a whiteout = delete
                r, _ = cl.submit_op(base, oid, [("remove",)],
                                    ignore_overlay=True)
                if r < 0 and r != -2:
                    raise OSError(-r, "flush delete failed")
            else:
                # full metadata REPLACEMENT (copy-from semantics):
                # attrs/omap keys deleted in the cache must not
                # survive in the base and resurrect on promote
                ops = [("writefull", data), ("resetxattrs",),
                       ("omap_clear",)]
                ops += [("setxattr", k, v) for k, v in attrs.items()]
                if omap:
                    ops.append(("omap_set", omap))
                r, _ = cl.submit_op(base, oid, ops,
                                    ignore_overlay=True)
                if r < 0:
                    raise OSError(-r, "flush write failed")
        except Exception:
            reply_fn(-5, None)        # EIO: base pool unreachable
            return
        pg.daemon.op_wq.queue(pg.pgid, self._flush_finish, oid, v0,
                              try_flush, reply_fn, klass="tier",
                              priority=pg.daemon.recovery_op_priority)

    def _flush_finish(self, oid, v0, try_flush, reply_fn) -> None:
        """Op-shard worker: nothing raced? mark clean (or erase a
        fully-flushed whiteout)."""
        pg = self.pg
        if pg._object_version(oid) != v0:
            if try_flush:
                reply_fn(-16, None)   # EBUSY: a writer raced us
            else:
                # blocking flavor: flush the NEW content
                self._flush_capture(oid, False, reply_fn)
            return
        from .pg_transaction import PGTransaction
        t = PGTransaction()
        ss = pg._load_snapset(oid)
        deleting = False
        if pg._is_whiteout(oid) and not ss["clones"]:
            t.remove(oid)             # tombstone fully propagated
            deleting = True
        else:
            t.rmattr(oid, DIRTY_ATTR)

        def done():
            with self.lock:
                self.dirty_at.pop(oid, None)
                self._agent_inflight.discard(oid)
            reply_fn(0, None)

        if not pg.submit_internal_write(oid, t, None, done,
                                        deleting=deleting):
            reply_fn(-11, None)   # EAGAIN: no longer the primary

    # ------------------------------------------------------------------
    # evict (PrimaryLogPG::agent_maybe_evict / do CACHE_EVICT)

    def _evict(self, oid, reply_fn) -> None:
        """Op-shard worker: drop a clean, unwatched, snapless object."""
        pg = self.pg
        if pg._object_size(oid) is None:
            reply_fn(-2, None)
            return
        busy = (pg.local_getattr(oid, DIRTY_ATTR) is not None
                or pg.watchers.get(oid)
                or pg._load_snapset(oid)["clones"])
        if busy:
            reply_fn(-16, None)       # EBUSY
            return
        from .pg_transaction import PGTransaction
        t = PGTransaction()
        t.remove(oid)

        def done():
            with self.lock:
                self._atime.pop(oid, None)
                self._agent_inflight.discard(oid)
            reply_fn(0, None)

        if not pg.submit_internal_write(oid, t, None, done,
                                        deleting=True):
            reply_fn(-11, None)   # EAGAIN: no longer the primary

    # ------------------------------------------------------------------
    # hit sets

    def _record_hit(self, oid) -> None:
        pg = self.pg
        pool = pg.pool
        now = time.monotonic()
        with self.lock:
            self._atime[oid] = now
            if pool.hit_set_period <= 0:
                return
            rolled = None
            if self.hit_set is None:
                self.hit_set = self._fresh_hit_set()
                self._hit_set_start = now
            elif now - self._hit_set_start >= pool.hit_set_period:
                rolled = self.hit_set
                self.hit_set = self._fresh_hit_set()
                self._hit_set_start = now
            self.hit_set.insert(oid)
        if rolled is not None:
            self._archive_hit_set(rolled)

    def _fresh_hit_set(self) -> HitSet:
        pool = self.pg.pool
        target = max(pool.target_max_objects // max(pool.pg_num, 1),
                     64)
        return HitSet(target_size=target, fpp=pool.hit_set_fpp)

    def _archive_hit_set(self, hs: HitSet) -> None:
        """Persist a rolled hit set as a PG-local replicated object and
        trim the archive to hit_set_count (HitSet archive objects,
        PrimaryLogPG::hit_set_persist). Names embed WALL-CLOCK time:
        they must sort oldest-first across restarts and primary moves,
        which a monotonic stamp cannot."""
        pg = self.pg
        name = "%s%020.6f" % (HITSET_PREFIX, time.time())
        from .pg_transaction import PGTransaction
        t = PGTransaction()
        t.create(name)
        t.write(name, 0, hs.encode())
        if not pg.submit_internal_write(name, t, None, lambda: None):
            return                    # demoted: archives stay volatile
        doomed = []
        with self.lock:
            self._archives.append((name, hs))
            keep = max(pg.pool.hit_set_count - 1, 0)
            while len(self._archives) > keep:
                doomed.append(self._archives.popleft()[0])
        for old in doomed:
            td = PGTransaction()
            td.remove(old)
            pg.submit_internal_write(old, td, None, lambda: None,
                                     deleting=True)

    def _load_archives(self) -> None:
        """Lazy restart path: decode persisted archives from the
        store."""
        pg = self.pg
        with self.lock:
            if self._archives_loaded:
                return
            self._archives_loaded = True
        cid = pg.cid_of_shard(-1)
        found = []
        for oid in pg.store.list_objects(cid):
            if isinstance(oid, str) and oid.startswith(HITSET_PREFIX):
                try:
                    found.append((oid, HitSet.decode(
                        pg.store.read(cid, oid))))
                except Exception:
                    continue
        found.sort()                  # name embeds start stamp: oldest first
        with self.lock:
            known = {n for n, _ in self._archives}
            fresh = [item for item in found if item[0] not in known]
            self._archives = deque(fresh + list(self._archives))

    def _is_warm(self, oid) -> bool:
        with self.lock:
            sets = ([self.hit_set] if self.hit_set is not None else []) \
                + [hs for _, hs in self._archives]
        return any(hs.contains(oid) for hs in sets)

    # ------------------------------------------------------------------
    # agent (TierAgentState + PrimaryLogPG::agent_work)

    def agent_scan(self) -> None:
        """Tier thread: estimate fullness, queue flushes/evictions.
        Targets are per-PG shares of the pool-wide knobs (the
        reference divides by pg_num the same way,
        PrimaryLogPG::agent_choose_mode)."""
        pg = self.pg
        pool = pg.pool
        if pool.target_max_objects <= 0 and pool.target_max_bytes <= 0:
            return
        with self.lock:
            if self._agent_busy:
                return
            self._agent_busy = True
        try:
            self._load_archives()
            from .pg import META_OID, is_clone_oid
            cid = pg.cid_of_shard(-1)
            objs = []
            nbytes = 0
            for oid in pg.store.list_objects(cid):
                if oid == META_OID or is_clone_oid(oid) \
                        or (isinstance(oid, str)
                            and oid.startswith(HITSET_PREFIX)):
                    continue
                st = pg.store.stat(cid, oid)
                if st is None:
                    continue
                dirty = pg.local_getattr(oid, DIRTY_ATTR) is not None
                objs.append((oid, st["size"], dirty))
                nbytes += st["size"]
            pgn = max(pool.pg_num, 1)
            max_obj = pool.target_max_objects / pgn \
                if pool.target_max_objects else float("inf")
            max_bytes = pool.target_max_bytes / pgn \
                if pool.target_max_bytes else float("inf")
            now = time.monotonic()
            with self.lock:
                atime = dict(self._atime)
                dirty_at = dict(self.dirty_at)
                inflight = set(self._agent_inflight)
            # flush: dirty volume above target * dirty_ratio
            dirty_objs = [(dirty_at.get(o, 0.0), o, sz)
                          for o, sz, d in objs if d and o not in inflight]
            dirty_objs.sort()         # oldest-dirty first
            dirty_count = sum(1 for _, _, _ in dirty_objs)
            dirty_bytes = sum(sz for _, _, sz in dirty_objs)
            over_objs = dirty_count - pool.cache_target_dirty_ratio \
                * max_obj if max_obj != float("inf") else -1
            over_bytes = dirty_bytes - pool.cache_target_dirty_ratio \
                * max_bytes if max_bytes != float("inf") else -1
            for stamp, oid, sz in dirty_objs:
                if over_objs <= 0 and over_bytes <= 0:
                    break
                if stamp and now - stamp < pool.cache_min_flush_age:
                    continue
                self._agent_queue_flush(oid)
                over_objs -= 1
                over_bytes -= sz
            # evict: total volume above target * full_ratio
            count = len(objs)
            over_objs = count - pool.cache_target_full_ratio * max_obj \
                if max_obj != float("inf") else -1
            over_bytes = nbytes - pool.cache_target_full_ratio \
                * max_bytes if max_bytes != float("inf") else -1
            if over_objs > 0 or over_bytes > 0:
                # clean objects, coldest first: not in any hit set,
                # then oldest access
                clean = [(self._is_warm(o), atime.get(o, 0.0), o, sz)
                         for o, sz, d in objs
                         if not d and o not in inflight]
                clean.sort()
                for warm, at, oid, sz in clean:
                    if over_objs <= 0 and over_bytes <= 0:
                        break
                    if at and now - at < pool.cache_min_evict_age:
                        continue
                    self._agent_queue_evict(oid)
                    over_objs -= 1
                    over_bytes -= sz
        finally:
            with self.lock:
                self._agent_busy = False

    def _agent_queue_flush(self, oid) -> None:
        pg = self.pg
        with self.lock:
            if oid in self._agent_inflight:
                return
            self._agent_inflight.add(oid)

        def done(result, data):
            with self.lock:
                self._agent_inflight.discard(oid)

        pg.daemon.op_wq.queue(pg.pgid, self._flush_capture, oid, True,
                              done, klass="tier",
                              priority=pg.daemon.recovery_op_priority)

    def _agent_queue_evict(self, oid) -> None:
        pg = self.pg
        with self.lock:
            if oid in self._agent_inflight:
                return
            self._agent_inflight.add(oid)

        def done(result, data):
            with self.lock:
                self._agent_inflight.discard(oid)

        pg.daemon.op_wq.queue(pg.pgid, self._evict, oid, done,
                              klass="tier",
                              priority=pg.daemon.recovery_op_priority)

