"""Crypto provider plugin slot.

Role of the reference's src/crypto/ (CryptoPlugin + the isa-l and
openssl accelerated providers, loaded through the same plugin registry
as the erasure codecs): the symmetric crypto cephx uses is pluggable,
so accelerated implementations can replace the baseline without
touching the protocol.

Providers implement authenticated encryption (seal/unseal) and keyed
MACs. The baseline `stdlib` provider is the HMAC-SHA256
encrypt-then-MAC keystream construction cephx shipped with; alternate
providers register under their own name (create("isal")-style lookup,
ENOENT on absent ones, mirroring the compressor registry's contract).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

__all__ = ["CryptoProvider", "StdlibProvider", "register", "create",
           "providers"]


class CryptoProvider:
    """Provider interface (CryptoPlugin/CryptoHandler role)."""

    name = "none"

    def seal(self, key: bytes, plaintext: bytes) -> bytes:
        raise NotImplementedError

    def unseal(self, key: bytes, blob: bytes) -> bytes:
        raise NotImplementedError

    def mac(self, key: bytes, data: bytes) -> bytes:
        raise NotImplementedError


class StdlibProvider(CryptoProvider):
    """Baseline: HMAC-SHA256 counter keystream + encrypt-then-MAC —
    authenticated encryption from the stdlib, standing in for the
    reference's AES providers."""

    name = "stdlib"

    @staticmethod
    def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        counter = 0
        while len(out) < n:
            out += hmac.new(key, nonce + struct.pack("<Q", counter),
                            hashlib.sha256).digest()
            counter += 1
        return bytes(out[:n])

    def seal(self, key: bytes, plaintext: bytes) -> bytes:
        nonce = os.urandom(16)
        ks = self._keystream(key, nonce, len(plaintext))
        ct = bytes(a ^ b for a, b in zip(plaintext, ks))
        tag = hmac.new(key, nonce + ct, hashlib.sha256).digest()
        return nonce + ct + tag

    def unseal(self, key: bytes, blob: bytes) -> bytes:
        from .cephx import AuthError
        if len(blob) < 48:
            raise AuthError("sealed blob too short")
        nonce, ct, tag = blob[:16], blob[16:-32], blob[-32:]
        want = hmac.new(key, nonce + ct, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise AuthError("sealed blob failed integrity check")
        ks = self._keystream(key, nonce, len(ct))
        return bytes(a ^ b for a, b in zip(ct, ks))

    def mac(self, key: bytes, data: bytes) -> bytes:
        return hmac.new(key, data, hashlib.sha256).digest()


_PROVIDERS: dict[str, CryptoProvider] = {}


def register(provider: CryptoProvider) -> None:
    if provider.name in _PROVIDERS:
        raise FileExistsError(
            "crypto provider %r already registered" % provider.name)
    _PROVIDERS[provider.name] = provider


def providers() -> list[str]:
    return sorted(_PROVIDERS)


def create(name: str = "stdlib") -> CryptoProvider:
    p = _PROVIDERS.get(name)
    if p is None:
        raise FileNotFoundError(
            2, "crypto provider %r not found (have: %s)"
            % (name, ", ".join(providers())))
    return p


register(StdlibProvider())
