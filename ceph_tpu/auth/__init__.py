"""Authentication subsystem (cephx-style tickets).

Rendition of the reference's auth layer (/root/reference/src/auth/):
entity keyrings, a monitor-side key server that verifies clients by
challenge-response and issues session tickets, and per-connection
authorizers that services verify without talking to the monitor —
the cephx trust model (doc/dev/cephx_protocol.rst). Crypto primitives
are stdlib-only: HMAC-SHA256 for proofs/integrity and an HMAC counter
keystream for ticket confidentiality (where the reference uses AES).
"""

from .keyring import KeyRing, generate_secret  # noqa: F401
from .cephx import (  # noqa: F401
    AuthError, CephxClient, CephxServer, CephxServiceHandler,
    seal, unseal)
from .caps import Caps, CapsError, parse_caps  # noqa: F401
