"""cephx-style ticket protocol.

The reference's cephx (/root/reference/src/auth/cephx/,
doc/dev/cephx_protocol.rst) in three roles:

  CephxServer          monitor-side key server: challenge-response
                       against the entity's keyring secret, then issues
                       a (ticket, sealed session key) pair. The ticket is
                       sealed with the *service* secret, so services can
                       verify it offline.
  CephxClient          client-side state machine: prove identity, unseal
                       the session key, mint per-connection authorizers.
  CephxServiceHandler  daemon-side verifier: validates an authorizer
                       using only the shared service secret (no monitor
                       round-trip), answers with a mutual-auth proof.

Crypto: HMAC-SHA256 proofs; `seal`/`unseal` provide authenticated
encryption from the stdlib (HMAC counter keystream + HMAC tag) standing
in for the reference's AES.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import threading
import time

from .. import encoding

AUTH_SERVICE = "auth"
DEFAULT_TICKET_TTL = 3600.0   # auth_service_ticket_ttl (options.cc)


class AuthError(Exception):
    """EACCES-class failure: bad key, bad ticket, expired, tampered."""


# ---------------------------------------------------------------------------
# authenticated encryption via the pluggable crypto provider slot
# (src/crypto/ role; the default stdlib provider is the HMAC keystream
# construction this module originally inlined)


def _provider():
    from . import crypto
    return crypto.create(_crypto_provider_name)


_crypto_provider_name = "stdlib"


def set_crypto_provider(name: str) -> None:
    """Select the registered crypto provider cephx uses."""
    from . import crypto
    crypto.create(name)            # ENOENT on absent, like the reference
    global _crypto_provider_name
    _crypto_provider_name = name


def seal(key: bytes, plaintext: bytes) -> bytes:
    """Encrypt-then-MAC: nonce || ciphertext || tag."""
    return _provider().seal(key, plaintext)


def unseal(key: bytes, blob: bytes) -> bytes:
    return _provider().unseal(key, blob)


def _proof(key: bytes, challenge: bytes) -> bytes:
    return hmac.new(key, b"cephx-proof" + challenge,
                    hashlib.sha256).digest()


# ---------------------------------------------------------------------------
# monitor side


class CephxServer:
    """Key server: verifies entities, issues tickets.

    keyring: entity secrets (client.admin, osd.0, ...).
    service_secrets: per-service ticket-sealing keys (the rotating
    secrets the monitor shares with daemons in the reference).
    """

    MAX_CHALLENGES = 1024          # unauthenticated-state bound
    CHALLENGE_TTL = 60.0

    def __init__(self, keyring, service_secrets: dict[str, bytes],
                 ticket_ttl: float = DEFAULT_TICKET_TTL):
        self.keyring = keyring
        self.service_secrets = dict(service_secrets)
        self.ticket_ttl = ticket_ttl
        # (entity, challenge) -> issue time: multiple outstanding
        # challenges per entity so concurrent authentications don't
        # clobber each other; bounded + expiring because round 1 is
        # unauthenticated (anyone can ask). Locked: handlers run on
        # concurrent messenger reader threads.
        self._challenges: dict[tuple, float] = {}
        self._chal_lock = threading.Lock()

    def _prune_challenges(self, now: float) -> None:
        dead = [k for k, ts in self._challenges.items()
                if now - ts > self.CHALLENGE_TTL]
        for k in dead:
            del self._challenges[k]
        while len(self._challenges) >= self.MAX_CHALLENGES:
            self._challenges.pop(next(iter(self._challenges)))

    def get_challenge(self, entity: str,
                      now: float | None = None) -> bytes:
        now = time.time() if now is None else now
        ch = os.urandom(16)
        with self._chal_lock:
            self._prune_challenges(now)
            self._challenges[(entity, ch)] = now
        return ch

    def handle_request(self, entity: str, proof: bytes,
                       service: str = "osd",
                       now: float | None = None) -> dict:
        """Verify the challenge proof; issue {ticket, sealed session key}.

        Raises AuthError on unknown entity / wrong key / no challenge.
        """
        now_t = time.time() if now is None else now
        secret = self.keyring.get_secret_bytes(entity)
        if secret is None:
            raise AuthError("entity %s: unknown or no challenge" % entity)
        with self._chal_lock:
            matched = None
            for (ent, ch), ts in self._challenges.items():
                if ent == entity and now_t - ts <= self.CHALLENGE_TTL \
                        and hmac.compare_digest(proof, _proof(secret, ch)):
                    matched = (ent, ch)
                    break
            if matched is None:
                if not any(ent == entity
                           for ent, _ in self._challenges):
                    raise AuthError(
                        "entity %s: unknown or no challenge" % entity)
                raise AuthError(
                    "entity %s: bad proof (wrong key)" % entity)
            del self._challenges[matched]
        svc_secret = self.service_secrets.get(service)
        if svc_secret is None:
            raise AuthError("no service secret for %r" % service)
        session_key = os.urandom(32)
        ticket = seal(svc_secret, encoding.encode_any({
            "entity": entity,
            "caps": self.keyring.get_caps(entity).get(service, ""),
            "session_key": session_key,
            "expires": now_t + self.ticket_ttl,
            "service": service,
            # key version at issue: daemons compare against the
            # authmap revocation watermark so a rekey/caps change
            # invalidates live tickets before their TTL
            "key_version": self.keyring.get_version(entity),
        }))
        return {"service": service,
                "ticket": ticket,
                "sealed_session_key": seal(secret, session_key)}


# ---------------------------------------------------------------------------
# client side


class CephxClient:
    def __init__(self, entity: str, secret_b64: str):
        import base64
        self.entity = entity
        self.secret = base64.b64decode(secret_b64)
        self.tickets: dict[str, dict] = {}   # service -> {ticket, key}

    def build_proof(self, challenge: bytes) -> bytes:
        return _proof(self.secret, challenge)

    def open_session(self, reply: dict) -> None:
        """Consume a CephxServer.handle_request reply."""
        session_key = unseal(self.secret, reply["sealed_session_key"])
        self.tickets[reply["service"]] = {
            "ticket": reply["ticket"], "session_key": session_key}

    def build_authorizer(self, service: str = "osd",
                         challenge: bytes | None = None) -> dict:
        """Per-connection authorizer presented in the banner.

        With `challenge` (the service's per-connection random, the
        reference's CephxAuthorizeChallenge — the CVE-2018-1128 fix),
        the proof covers it, so a captured authorizer cannot be
        replayed on a new connection."""
        t = self.tickets.get(service)
        if t is None:
            raise AuthError("no ticket for service %r" % service)
        nonce = os.urandom(16)
        return {
            "entity": self.entity,
            "service": service,
            "ticket": t["ticket"],
            "nonce": nonce,
            "has_challenge": challenge is not None,
            "proof": hmac.new(
                t["session_key"],
                b"authorizer" + nonce + (challenge or b""),
                hashlib.sha256).digest(),
        }

    def verify_reply(self, service: str, reply_proof: bytes,
                     nonce: bytes) -> bool:
        """Mutual auth: the service proves it could read the ticket."""
        t = self.tickets.get(service)
        if t is None or not isinstance(reply_proof, bytes):
            return False
        want = hmac.new(t["session_key"], b"authorizer-reply" + nonce,
                        hashlib.sha256).digest()
        return hmac.compare_digest(reply_proof, want)


# ---------------------------------------------------------------------------
# service (daemon) side


class CephxServiceHandler:
    def __init__(self, service: str, service_secret: bytes):
        self.service = service
        self.service_secret = service_secret

    def verify_authorizer(self, authorizer: dict,
                          now: float | None = None,
                          challenge: bytes | None = None) -> dict:
        """Validate an authorizer offline; returns
        {entity, caps, session_key, reply_proof} or raises AuthError.

        When the caller minted a per-connection `challenge`, the proof
        must cover it (replay protection; the messenger always runs
        this mode via its BANNER_RETRY round)."""
        try:
            ticket = encoding.decode_any(
                unseal(self.service_secret, authorizer["ticket"]),
                restricted=True)
        except (KeyError, TypeError, encoding.DecodeError) as e:
            raise AuthError("malformed authorizer: %s" % e)
        if not isinstance(ticket, dict):
            raise AuthError("malformed authorizer ticket")
        now = time.time() if now is None else now
        if ticket["service"] != self.service:
            raise AuthError("ticket for %r used on %r"
                            % (ticket["service"], self.service))
        if now > ticket["expires"]:
            raise AuthError("ticket for %s expired" % ticket["entity"])
        if ticket["entity"] != authorizer.get("entity"):
            raise AuthError("authorizer entity mismatch")
        nonce = authorizer.get("nonce", b"")
        if challenge is not None and not authorizer.get("has_challenge"):
            raise AuthError("authorizer lacks required challenge proof")
        want = hmac.new(ticket["session_key"],
                        b"authorizer" + nonce + (challenge or b""),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(authorizer.get("proof", b""), want):
            raise AuthError("authorizer proof invalid")
        reply = hmac.new(ticket["session_key"], b"authorizer-reply" + nonce,
                         hashlib.sha256).digest()
        return {"entity": ticket["entity"], "caps": ticket["caps"],
                "key_version": ticket.get("key_version", 1),
                "session_key": ticket["session_key"], "reply_proof": reply}
