"""Entity keyrings.

Models the reference's KeyRing (/root/reference/src/auth/KeyRing.{h,cc})
and its text format:

    [client.admin]
        key = <base64 secret>
        caps mon = "allow *"

Secrets are random 32-byte keys, base64-encoded on disk.
"""

from __future__ import annotations

import base64
import os


def generate_secret() -> str:
    return base64.b64encode(os.urandom(32)).decode("ascii")


class KeyRing:
    def __init__(self):
        self._keys: dict[str, str] = {}      # entity -> base64 secret
        self._caps: dict[str, dict] = {}     # entity -> {service: capspec}
        # entity -> key version, bumped on rekey/caps change so issued
        # tickets (which embed the version) can be revoked by version
        # watermark (the AuthMonitor rotation mechanism)
        self._versions: dict[str, int] = {}

    def add(self, entity: str, secret: str | None = None,
            caps: dict | None = None) -> str:
        secret = secret or generate_secret()
        bump = entity in self._keys and self._keys[entity] != secret
        self._keys[entity] = secret
        if caps:
            self._caps[entity] = dict(caps)
        if bump:
            self.bump_version(entity)
        else:
            self._versions.setdefault(entity, 1)
        return secret

    def set_caps(self, entity: str, caps: dict) -> None:
        self._caps[entity] = dict(caps)
        self.bump_version(entity)

    def get_version(self, entity: str) -> int:
        return self._versions.get(entity, 1)

    def bump_version(self, entity: str) -> int:
        self._versions[entity] = self._versions.get(entity, 1) + 1
        return self._versions[entity]

    def remove(self, entity: str) -> None:
        self._keys.pop(entity, None)
        self._caps.pop(entity, None)
        self._versions.pop(entity, None)

    def get(self, entity: str) -> str | None:
        return self._keys.get(entity)

    def get_secret_bytes(self, entity: str) -> bytes | None:
        s = self._keys.get(entity)
        return base64.b64decode(s) if s is not None else None

    def get_caps(self, entity: str) -> dict:
        return dict(self._caps.get(entity, {}))

    def entities(self) -> list[str]:
        return sorted(self._keys)

    # -- text format ---------------------------------------------------

    def emit(self) -> str:
        out = []
        for entity in sorted(self._keys):
            out.append("[%s]" % entity)
            out.append("\tkey = %s" % self._keys[entity])
            for svc, spec in sorted(self._caps.get(entity, {}).items()):
                out.append('\tcaps %s = "%s"' % (svc, spec))
        return "\n".join(out) + "\n"

    @classmethod
    def parse(cls, text: str) -> "KeyRing":
        kr = cls()
        entity = None
        for raw in text.splitlines():
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("[") and line.endswith("]"):
                entity = line[1:-1]
                continue
            if entity is None:
                raise ValueError("keyring line outside a section: %r" % line)
            if line.startswith("key"):
                _, _, v = line.partition("=")
                kr._keys[entity] = v.strip()
            elif line.startswith("caps"):
                head, _, v = line.partition("=")
                svc = head.split()[1]
                kr._caps.setdefault(entity, {})[svc] = v.strip().strip('"')
        return kr

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.emit())

    @classmethod
    def load(cls, path: str) -> "KeyRing":
        with open(path) as f:
            return cls.parse(f.read())
