"""Capability grammar + enforcement.

Role of the reference's OSDCap (/root/reference/src/osd/OSDCap.{h,cc})
and MonCap (/root/reference/src/mon/MonCap.{h,cc}): parse entity cap
strings from the keyring / auth database into grant lists and answer
is_capable() on the hot paths — the OSD checks pool-scoped rwx per op,
the monitor checks r/w/x per command.

Grammar (the subset the framework enforces; the reference adds
object_prefix, namespaces, profiles and network restrictions):

    capspec   := grant (',' grant)*
    grant     := 'allow' (('*'|[rwx]+) ('pool=' name)?
                          | 'command' '"' prefix '"')

'*' grants rwx everywhere.  A grant with pool=NAME matches only that
pool; without, it matches every pool.  'allow command "<prefix>"'
(MonCap command grants) admits exactly that mon command prefix.
"""

from __future__ import annotations

__all__ = ["CapGrant", "Caps", "CapsError", "parse_caps"]


class CapsError(ValueError):
    pass


class CapGrant:
    __slots__ = ("perms", "pool", "command")

    def __init__(self, perms: frozenset, pool: str | None = None,
                 command: str | None = None):
        self.perms = perms
        self.pool = pool
        self.command = command

    def __repr__(self):
        if self.command is not None:
            return "allow command %r" % self.command
        spec = "*" if self.perms == frozenset("rwx") else \
            "".join(p for p in "rwx" if p in self.perms)
        return "allow %s%s" % (spec,
                               " pool=%s" % self.pool if self.pool
                               else "")


def parse_caps(spec: str) -> "Caps":
    """Parse a capability string ('allow rwx pool=data, allow r')."""
    grants: list[CapGrant] = []
    spec = (spec or "").strip()
    if not spec:
        return Caps(grants)
    for part in spec.split(","):
        toks = part.strip().split()
        if not toks:
            continue
        if toks[0] != "allow":
            raise CapsError("grant must start with 'allow': %r" % part)
        if len(toks) < 2:
            raise CapsError("empty grant: %r" % part)
        if toks[1] == "command":
            cmd = part.strip()[len("allow command"):].strip()
            if not (cmd.startswith('"') and cmd.endswith('"')
                    and len(cmd) >= 2):
                raise CapsError("command grant needs a quoted "
                                "prefix: %r" % part)
            grants.append(CapGrant(frozenset(), command=cmd[1:-1]))
            continue
        if toks[1] == "*":
            perms = frozenset("rwx")
        else:
            if not set(toks[1]) <= set("rwx"):
                raise CapsError("bad perms %r" % toks[1])
            perms = frozenset(toks[1])
        pool = None
        for extra in toks[2:]:
            if extra.startswith("pool="):
                pool = extra[len("pool="):]
            else:
                raise CapsError("unknown grant qualifier %r" % extra)
        grants.append(CapGrant(perms, pool=pool))
    return Caps(grants)


class Caps:
    """A parsed grant list (OSDCap / MonCap role)."""

    def __init__(self, grants: list[CapGrant]):
        self.grants = grants

    def is_capable(self, need: str, pool: str | None = None) -> bool:
        """True when the union of matching grants covers every perm in
        `need` (OSDCap::is_capable semantics: grants accumulate)."""
        needed = set(need)
        for g in self.grants:
            if g.command is not None:
                continue
            if g.pool is not None and g.pool != pool:
                continue
            needed -= g.perms
            if not needed:
                return True
        return not needed

    def is_command_capable(self, prefix: str,
                           need: str = "") -> bool:
        """Mon command admission: an exact command grant matches, or
        the r/w/x perms cover the command's class."""
        for g in self.grants:
            if g.command is not None and prefix == g.command:
                return True
        return self.is_capable(need) if need else False

    def allows_anything(self) -> bool:
        return any(g.perms or g.command is not None
                   for g in self.grants)

    def __repr__(self):
        return ", ".join(repr(g) for g in self.grants) or "(none)"
