"""rgw — S3-subset object gateway over RADOS.

Role of the reference's radosgw REST front
(/root/reference/src/rgw/rgw_rest_s3.cc + rgw_op.cc, bucket index per
rgw_bucket.cc): an HTTP server that maps the S3 object API onto rados
objects, with bucket indexes kept in omap — the same layering, at
framework scale:

  service GET  /                 list buckets (XML)
  bucket  PUT  /<bucket>         create
          GET  /<bucket>         list objects (prefix= & max-keys=)
          DELETE /<bucket>       remove (409 unless empty)
  object  PUT  /<bucket>/<key>   store (returns ETag = md5, like S3)
          GET  /<bucket>/<key>   fetch
          HEAD /<bucket>/<key>   stat
          DELETE /<bucket>/<key>

Layout in the backing pool: bucket roster in the omap of
`.rgw.buckets`; per-bucket index object `.bucket.index.<bucket>` whose
omap maps key -> {size, etag, mtime} (the reference's bucket index
shards, unsharded here); object data in `<bucket>/<key>`.

Auth: AWS signature v2 ("Authorization: AWS <access>:<sig>",
HMAC-SHA1 over the canonical StringToSign — rgw_auth_s3.cc role).
Anonymous access is refused when credentials are configured.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlsplit
from xml.sax.saxutils import escape

from .. import encoding

__all__ = ["RGWServer", "S3Error"]

ROSTER_OID = ".rgw.buckets"


def _index_oid(bucket: str) -> str:
    return ".bucket.index.%s" % bucket


def _data_oid(bucket: str, key: str) -> str:
    return "%s/%s" % (bucket, key)


class S3Error(Exception):
    def __init__(self, status: int, code: str, message: str = ""):
        super().__init__(code)
        self.status = status
        self.code = code
        self.message = message or code

    def body(self) -> bytes:
        return ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                "<Error><Code>%s</Code><Message>%s</Message></Error>"
                % (self.code, self.message)).encode()


class _Store:
    """The rados-facing half (rgw_op.cc's RGWOp execute bodies)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx
        self._lock = threading.Lock()

    # -- buckets -------------------------------------------------------

    def list_buckets(self) -> list[str]:
        try:
            return sorted(self.ioctx.omap_get(ROSTER_OID))
        except OSError:
            return []

    def create_bucket(self, bucket: str) -> None:
        with self._lock:
            if bucket in self.list_buckets():
                raise S3Error(409, "BucketAlreadyExists", bucket)
            self.ioctx.write_full(_index_oid(bucket), b"")
            self.ioctx.omap_set(ROSTER_OID, {bucket: b"1"})

    def _require_bucket(self, bucket: str) -> None:
        if bucket not in self.list_buckets():
            raise S3Error(404, "NoSuchBucket", bucket)

    def delete_bucket(self, bucket: str) -> None:
        with self._lock:
            self._require_bucket(bucket)
            if self.list_objects(bucket):
                raise S3Error(409, "BucketNotEmpty", bucket)
            self.ioctx.remove(_index_oid(bucket))
            self.ioctx.omap_rm_keys(ROSTER_OID, [bucket])

    # -- objects -------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000) -> list[dict]:
        self._require_bucket(bucket)
        try:
            index = self.ioctx.omap_get(_index_oid(bucket))
        except OSError:
            return []
        out = []
        for key in sorted(index):
            if prefix and not key.startswith(prefix):
                continue
            meta = encoding.decode_any(index[key])
            meta["key"] = key
            out.append(meta)
            if len(out) >= max_keys:
                break
        return out

    def put_object(self, bucket: str, key: str, data: bytes) -> str:
        self._require_bucket(bucket)
        etag = hashlib.md5(data).hexdigest()
        self.ioctx.write_full(_data_oid(bucket, key), data)
        self.ioctx.omap_set(_index_oid(bucket), {
            key: encoding.encode_any({
                "size": len(data), "etag": etag,
                "mtime": time.time()})})
        return etag

    def head_object(self, bucket: str, key: str) -> dict:
        self._require_bucket(bucket)
        try:
            index = self.ioctx.omap_get(_index_oid(bucket))
            raw = index[key]
        except (OSError, KeyError):
            raise S3Error(404, "NoSuchKey", key)
        return encoding.decode_any(raw)

    def get_object(self, bucket: str, key: str) -> tuple[bytes, dict]:
        meta = self.head_object(bucket, key)
        data = self.ioctx.read(_data_oid(bucket, key))
        return data, meta

    def delete_object(self, bucket: str, key: str) -> None:
        self.head_object(bucket, key)       # 404 if absent
        self.ioctx.remove(_data_oid(bucket, key))
        self.ioctx.omap_rm_keys(_index_oid(bucket), [key])


def _sign_v2(secret: str, string_to_sign: str) -> str:
    mac = hmac.new(secret.encode(), string_to_sign.encode(),
                   hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


def string_to_sign(method: str, path: str, headers: dict) -> str:
    """AWS v2 canonical string (the subset the gateway checks)."""
    return "\n".join([
        method,
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        headers.get("date", ""),
        path,
    ])


class RGWServer:
    """The HTTP front (rgw_rest_s3.cc's handler table)."""

    def __init__(self, ioctx, host: str = "127.0.0.1", port: int = 0,
                 credentials: dict | None = None):
        self.store = _Store(ioctx)
        self.credentials = dict(credentials or {})
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):   # quiet
                pass

            def _dispatch(self, method):
                try:
                    gw._check_auth(method, self)
                    status, headers, body = gw._route(method, self)
                except S3Error as e:
                    status, body = e.status, e.body()
                    headers = {"Content-Type": "application/xml"}
                except Exception as e:   # internal
                    status = 500
                    body = S3Error(500, "InternalError",
                                   str(e)).body()
                    headers = {"Content-Type": "application/xml"}
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if method != "HEAD":
                    self.wfile.write(body)

            def do_GET(self):
                self._dispatch("GET")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def do_HEAD(self):
                self._dispatch("HEAD")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.addr = self.httpd.server_address
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "RGWServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="rgw", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- auth ----------------------------------------------------------

    def _check_auth(self, method, req) -> None:
        if not self.credentials:
            return
        auth = req.headers.get("Authorization", "")
        if not auth.startswith("AWS "):
            raise S3Error(403, "AccessDenied", "missing AWS auth")
        try:
            access, sig = auth[4:].split(":", 1)
        except ValueError:
            raise S3Error(403, "AccessDenied", "malformed auth")
        secret = self.credentials.get(access)
        if secret is None:
            raise S3Error(403, "InvalidAccessKeyId", access)
        path = urlsplit(req.path).path
        hdrs = {k.lower(): v for k, v in req.headers.items()}
        want = _sign_v2(secret, string_to_sign(method, path, hdrs))
        if not hmac.compare_digest(sig, want):
            raise S3Error(403, "SignatureDoesNotMatch", "")

    # -- routing -------------------------------------------------------

    def _route(self, method, req):
        split = urlsplit(req.path)
        parts = unquote(split.path).lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        query = parse_qs(split.query)
        if not bucket:
            if method == "GET":
                return self._list_buckets()
            raise S3Error(405, "MethodNotAllowed", method)
        if not key:
            if method == "PUT":
                self.store.create_bucket(bucket)
                return 200, {"Location": "/" + bucket}, b""
            if method == "DELETE":
                self.store.delete_bucket(bucket)
                return 204, {}, b""
            if method == "GET":
                return self._list_objects(bucket, query)
            raise S3Error(405, "MethodNotAllowed", method)
        if method == "PUT":
            length = int(req.headers.get("Content-Length", "0"))
            data = req.rfile.read(length) if length else b""
            etag = self.store.put_object(bucket, key, data)
            return 200, {"ETag": '"%s"' % etag}, b""
        if method == "GET":
            data, meta = self.store.get_object(bucket, key)
            return 200, {"Content-Type": "binary/octet-stream",
                         "ETag": '"%s"' % meta["etag"]}, data
        if method == "HEAD":
            meta = self.store.head_object(bucket, key)
            return 200, {"Content-Length-Real": str(meta["size"]),
                         "ETag": '"%s"' % meta["etag"]}, b""
        if method == "DELETE":
            self.store.delete_object(bucket, key)
            return 204, {}, b""
        raise S3Error(405, "MethodNotAllowed", method)

    # -- XML renderings (rgw_rest_s3 dump_* role) ----------------------

    def _list_buckets(self):
        rows = "".join(
            "<Bucket><Name>%s</Name></Bucket>" % escape(b)
            for b in self.store.list_buckets())
        body = ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                "<ListAllMyBucketsResult><Buckets>%s</Buckets>"
                "</ListAllMyBucketsResult>" % rows).encode()
        return 200, {"Content-Type": "application/xml"}, body

    def _list_objects(self, bucket, query):
        prefix = (query.get("prefix") or [""])[0]
        max_keys = int((query.get("max-keys") or ["1000"])[0])
        entries = self.store.list_objects(bucket, prefix, max_keys)
        rows = "".join(
            "<Contents><Key>%s</Key><Size>%d</Size>"
            "<ETag>&quot;%s&quot;</ETag></Contents>"
            % (escape(e["key"]), e["size"], e["etag"])
            for e in entries)
        body = ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                "<ListBucketResult><Name>%s</Name><Prefix>%s</Prefix>"
                "%s</ListBucketResult>"
                % (escape(bucket), escape(prefix), rows)).encode()
        return 200, {"Content-Type": "application/xml"}, body
