"""rgw — S3-subset object gateway over RADOS.

Role of the reference's radosgw REST front
(/root/reference/src/rgw/rgw_rest_s3.cc + rgw_op.cc, bucket index per
rgw_bucket.cc): an HTTP server that maps the S3 object API onto rados
objects, with bucket indexes kept in omap — the same layering, at
framework scale:

  service GET  /                 list buckets (XML)
  bucket  PUT  /<bucket>         create
          GET  /<bucket>         list objects (prefix= & max-keys=)
          DELETE /<bucket>       remove (409 unless empty)
  object  PUT  /<bucket>/<key>   store (returns ETag = md5, like S3)
          GET  /<bucket>/<key>   fetch
          HEAD /<bucket>/<key>   stat
          DELETE /<bucket>/<key>

Layout in the backing pool: bucket roster in the omap of
`.rgw.buckets`; per-bucket index object `.bucket.index.<bucket>` whose
omap maps key -> {size, etag, mtime} (the reference's bucket index
shards, unsharded here); object data in `<bucket>/<key>`.

Auth: AWS signature v2 ("Authorization: AWS <access>:<sig>",
HMAC-SHA1 over the canonical StringToSign — rgw_auth_s3.cc role).
Anonymous access is refused when credentials are configured.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlsplit
from xml.sax.saxutils import escape

from .. import encoding

__all__ = ["RGWServer", "S3Error"]

ROSTER_OID = ".rgw.buckets"


def _index_oid(bucket: str) -> str:
    return ".bucket.index.%s" % bucket


def _data_oid(bucket: str, key: str) -> str:
    return "%s/%s" % (bucket, key)


class S3Error(Exception):
    def __init__(self, status: int, code: str, message: str = ""):
        super().__init__(code)
        self.status = status
        self.code = code
        self.message = message or code

    def body(self) -> bytes:
        return ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                "<Error><Code>%s</Code><Message>%s</Message></Error>"
                % (self.code, self.message)).encode()


class _Store:
    """The rados-facing half (rgw_op.cc's RGWOp execute bodies)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx
        self._lock = threading.Lock()

    # -- buckets -------------------------------------------------------

    def list_buckets(self) -> list[str]:
        try:
            return sorted(self.ioctx.omap_get(ROSTER_OID))
        except OSError:
            return []

    def create_bucket(self, bucket: str, acl: str = "private") -> None:
        with self._lock:
            if bucket in self.list_buckets():
                raise S3Error(409, "BucketAlreadyExists", bucket)
            self.ioctx.write_full(_index_oid(bucket), b"")
            self.ioctx.omap_set(ROSTER_OID, {
                bucket: encoding.encode_any({"acl": acl})})

    def bucket_acl(self, bucket: str) -> str:
        """Canned ACL stored in the roster row; rosters written before
        ACLs existed hold b"1" and read as private."""
        try:
            raw = self.ioctx.omap_get(ROSTER_OID)[bucket]
        except (OSError, KeyError):
            raise S3Error(404, "NoSuchBucket", bucket)
        try:
            return encoding.decode_any(raw).get("acl", "private")
        except Exception:
            return "private"

    def set_bucket_acl(self, bucket: str, acl: str) -> None:
        with self._lock:
            self._require_bucket(bucket)
            self.ioctx.omap_set(ROSTER_OID, {
                bucket: encoding.encode_any({"acl": acl})})

    def _require_bucket(self, bucket: str) -> None:
        if bucket not in self.list_buckets():
            raise S3Error(404, "NoSuchBucket", bucket)

    def delete_bucket(self, bucket: str) -> None:
        with self._lock:
            self._require_bucket(bucket)
            if self.list_objects(bucket):
                raise S3Error(409, "BucketNotEmpty", bucket)
            if self.list_multipart_uploads(bucket):
                # parts would leak and a recreated bucket would
                # resurrect stale uploads; S3 refuses the same way
                raise S3Error(409, "BucketNotEmpty",
                              "in-flight multipart uploads")
            try:
                self.ioctx.remove(self._mp_state_oid(bucket))
            except Exception:
                pass
            self.ioctx.remove(_index_oid(bucket))
            self.ioctx.omap_rm_keys(ROSTER_OID, [bucket])

    # -- objects -------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000) -> list[dict]:
        self._require_bucket(bucket)
        try:
            index = self.ioctx.omap_get(_index_oid(bucket))
        except OSError:
            return []
        out = []
        for key in sorted(index):
            if prefix and not key.startswith(prefix):
                continue
            meta = encoding.decode_any(index[key])
            meta["key"] = key
            out.append(meta)
            if len(out) >= max_keys:
                break
        return out

    def put_object(self, bucket: str, key: str, data: bytes) -> str:
        self._require_bucket(bucket)
        etag = hashlib.md5(data).hexdigest()
        self.ioctx.write_full(_data_oid(bucket, key), data)
        self.ioctx.omap_set(_index_oid(bucket), {
            key: encoding.encode_any({
                "size": len(data), "etag": etag,
                "mtime": time.time()})})
        return etag

    def head_object(self, bucket: str, key: str) -> dict:
        self._require_bucket(bucket)
        try:
            index = self.ioctx.omap_get(_index_oid(bucket))
            raw = index[key]
        except (OSError, KeyError):
            raise S3Error(404, "NoSuchKey", key)
        return encoding.decode_any(raw)

    def get_object(self, bucket: str, key: str) -> tuple[bytes, dict]:
        meta = self.head_object(bucket, key)
        data = self.ioctx.read(_data_oid(bucket, key))
        return data, meta

    def delete_object(self, bucket: str, key: str) -> None:
        self.head_object(bucket, key)       # 404 if absent
        self.ioctx.remove(_data_oid(bucket, key))
        self.ioctx.omap_rm_keys(_index_oid(bucket), [key])

    # -- multipart uploads (RGWInitMultipart / RGWPutObj part /
    # RGWCompleteMultipart / RGWAbortMultipart, rgw_op.cc) ------------

    def _mp_state_oid(self, bucket: str) -> str:
        return "__rgw_mp__%s" % bucket

    def _mp_part_oid(self, bucket: str, upload_id: str,
                     part: int) -> str:
        return "__rgw_mpp__%s/%s/%06d" % (bucket, upload_id, part)

    def _mp_get_state(self, bucket: str, upload_id: str) -> dict:
        try:
            raw = self.ioctx.omap_get(
                self._mp_state_oid(bucket))[upload_id]
        except (OSError, KeyError):
            raise S3Error(404, "NoSuchUpload", upload_id)
        return encoding.decode_any(raw)

    def _mp_put_state(self, bucket: str, upload_id: str,
                      state: dict) -> None:
        self.ioctx.omap_set(self._mp_state_oid(bucket),
                            {upload_id: encoding.encode_any(state)})

    def initiate_multipart(self, bucket: str, key: str) -> str:
        self._require_bucket(bucket)
        upload_id = uuid.uuid4().hex
        # the state oid must exist before omap ops on some backends
        try:
            self.ioctx.write_full(self._mp_state_oid(bucket), b"")
        except OSError:
            pass
        self._mp_put_state(bucket, upload_id,
                           {"key": key, "parts": {}})
        return upload_id

    def upload_part(self, bucket: str, upload_id: str,
                    part_number: int, data: bytes) -> str:
        if not 1 <= part_number <= 10000:
            raise S3Error(400, "InvalidArgument",
                          "partNumber must be 1..10000")
        self._mp_get_state(bucket, upload_id)   # 404 before the write
        etag = hashlib.md5(data).hexdigest()
        # the part oid is unique to (upload, part): its write needs no
        # lock — parallel part uploads are the point of multipart; only
        # the state read-modify-write serializes
        self.ioctx.write_full(
            self._mp_part_oid(bucket, upload_id, part_number), data)
        with self._lock:
            state = self._mp_get_state(bucket, upload_id)
            state["parts"][str(part_number)] = {
                "etag": etag, "size": len(data)}
            self._mp_put_state(bucket, upload_id, state)
        return etag

    def complete_multipart(self, bucket: str, upload_id: str,
                           parts: list) -> str:
        """parts: [(part_number, etag)] in the client's requested
        order — must be ascending and match the uploaded parts. The
        final object is assembled part by part (RGW stitches a
        manifest; atop rados, append is the same shape) and the
        multipart ETag is md5-of-part-digests '-N' per S3."""
        with self._lock:
            state = self._mp_get_state(bucket, upload_id)
            if not parts:
                raise S3Error(400, "MalformedXML", "no parts")
            last = 0
            digests = b""
            for n, etag in parts:
                if n <= last:
                    raise S3Error(400, "InvalidPartOrder", str(n))
                last = n
                have = state["parts"].get(str(n))
                if have is None or have["etag"] != etag.strip('"'):
                    raise S3Error(400, "InvalidPart", str(n))
                digests += bytes.fromhex(have["etag"])
            key = state["key"]
            final_etag = "%s-%d" % (hashlib.md5(digests).hexdigest(),
                                    len(parts))
            # assemble then land in ONE write_full so a concurrent GET
            # never observes a truncated/partial object (real RGW
            # stitches a manifest; at framework scale the object fits)
            data = b"".join(
                self.ioctx.read(self._mp_part_oid(bucket, upload_id, n))
                for n, _etag in parts)
            self.ioctx.write_full(_data_oid(bucket, key), data)
            self.ioctx.omap_set(_index_oid(bucket), {
                key: encoding.encode_any({
                    "size": len(data), "etag": final_etag,
                    "mtime": time.time()})})
            self._mp_cleanup(bucket, upload_id, state)
        return final_etag

    def abort_multipart(self, bucket: str, upload_id: str) -> None:
        with self._lock:
            state = self._mp_get_state(bucket, upload_id)
            self._mp_cleanup(bucket, upload_id, state)

    def _mp_cleanup(self, bucket: str, upload_id: str,
                    state: dict) -> None:
        for n in state["parts"]:
            try:
                self.ioctx.remove(
                    self._mp_part_oid(bucket, upload_id, int(n)))
            except Exception:
                pass
        self.ioctx.omap_rm_keys(self._mp_state_oid(bucket), [upload_id])

    def list_multipart_uploads(self, bucket: str) -> list[dict]:
        self._require_bucket(bucket)
        try:
            raw = self.ioctx.omap_get(self._mp_state_oid(bucket))
        except OSError:
            return []
        return [{"upload_id": uid,
                 "key": encoding.decode_any(st)["key"]}
                for uid, st in sorted(raw.items())]


def _parse_complete_xml(xml: str) -> list:
    """[(part_number, etag)] from a CompleteMultipartUpload body —
    order-agnostic WITHIN each <Part> (AWS's own request syntax puts
    ETag before PartNumber; clients vary)."""
    parts = []
    for m in re.finditer(r"<Part>(.*?)</Part>", xml, re.S):
        blk = m.group(1)
        pn = re.search(r"<PartNumber>\s*(\d+)\s*</PartNumber>", blk)
        et = re.search(r"<ETag>(.*?)</ETag>", blk, re.S)
        if pn is None or et is None:
            raise S3Error(400, "MalformedXML", "incomplete Part")
        etag = re.sub(r"&quot;|\"", "", et.group(1)).strip()
        parts.append((int(pn.group(1)), etag))
    return parts


def _sign_v2(secret: str, string_to_sign: str) -> str:
    mac = hmac.new(secret.encode(), string_to_sign.encode(),
                   hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


def string_to_sign(method: str, path: str, headers: dict) -> str:
    """AWS v2 canonical string (the subset the gateway checks)."""
    return "\n".join([
        method,
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        headers.get("date", ""),
        path,
    ])


class RGWServer:
    """The HTTP front (rgw_rest_s3.cc's handler table)."""

    def __init__(self, ioctx, host: str = "127.0.0.1", port: int = 0,
                 credentials: dict | None = None):
        self.store = _Store(ioctx)
        self.credentials = dict(credentials or {})
        self._ioctx = ioctx
        # mgr telemetry: l_rgw_* counters (RGWServer has no messenger
        # of its own — start_mgr_reports borrows the rados client's)
        from ..common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("rgw")
                     .add_u64_counter("req", "HTTP requests served")
                     .add_u64_counter("failed_req",
                                      "requests answered >= 400")
                     .add_u64_counter("get_b", "bytes served by GET")
                     .add_u64_counter("put_b", "bytes taken by PUT")
                     .create_perf_counters())
        self._mgr_timer: threading.Timer | None = None
        # Swift front session tokens (X-Auth-Token -> account); the
        # reference's rgw swift front keeps these in its token cache
        self._swift_tokens: dict[str, str] = {}
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):   # quiet
                pass

            def _dispatch(self, method):
                gw.perf.inc("req")
                try:
                    path = urlsplit(self.path).path
                    if path == "/auth/v1.0" or \
                            path.startswith("/swift/"):
                        # Swift front: token auth + text errors
                        status, headers, body = gw._swift(method, self)
                    else:
                        principal = gw._check_auth(method, self)
                        status, headers, body = gw._route(
                            method, self, principal)
                except S3Error as e:
                    status, body = e.status, e.body()
                    headers = {"Content-Type": "application/xml"}
                except Exception as e:   # internal
                    status = 500
                    body = S3Error(500, "InternalError",
                                   str(e)).body()
                    headers = {"Content-Type": "application/xml"}
                if status >= 400:
                    gw.perf.inc("failed_req")
                elif method == "GET":
                    gw.perf.inc("get_b", len(body))
                elif method == "PUT":
                    gw.perf.inc(
                        "put_b",
                        int(self.headers.get("Content-Length") or 0))
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if method != "HEAD":
                    self.wfile.write(body)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def do_HEAD(self):
                self._dispatch("HEAD")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.addr = self.httpd.server_address
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "RGWServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="rgw", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._mgr_timer is not None:
            self._mgr_timer.cancel()
            self._mgr_timer = None
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- mgr telemetry -------------------------------------------------

    def start_mgr_reports(self, mgr_addr, name: str = "rgw.0",
                          period: float | None = None) -> None:
        """RGW leg of the cluster telemetry stream: ship the l_rgw_*
        counters to the mgr on the mgr_stats_period cadence, riding
        the backing rados client's messenger (the gateway is an HTTP
        front, not a cluster daemon with its own messenger)."""
        client = self._ioctx.client
        if period is None:
            period = client.ctx.conf.get_val("mgr_stats_period") \
                if getattr(client, "ctx", None) is not None else 0.5
        if period <= 0:
            return
        from ..common.telemetry import DeltaReporter
        reporter = DeltaReporter()

        class _AckDispatcher:
            # acks arrive on the borrowed client messenger; everything
            # else falls through to the rados client's own dispatcher
            def ms_dispatch(self, msg) -> bool:
                if msg.get_type() == "MMgrReportAck" \
                        and msg.daemon_name == name:
                    reporter.ack(msg.ack_seq, resync=msg.resync)
                    return True
                return False

        try:
            client.msgr.add_dispatcher_head(_AckDispatcher())
        except Exception:
            pass                     # no acks = full reports, still fine

        def tick():
            from ..msg.message import MMgrReport
            try:
                rep = reporter.prepare({"rgw": self.perf.dump()},
                                       {"rgw": self.perf.schema()})
                client.msgr.send_message(
                    MMgrReport(daemon_name=name, daemon_type="rgw",
                               perf=rep["perf"],
                               metadata={"addr": str(self.addr)},
                               perf_schema=rep["schema"],
                               report_seq=rep["seq"],
                               incarnation=rep["incarnation"],
                               schema_hash=rep["schema_hash"],
                               delta_base=rep["delta_base"]),
                    mgr_addr)
            except Exception:
                return               # messenger gone: stop reporting
            self._mgr_timer = threading.Timer(period, tick)
            self._mgr_timer.daemon = True
            self._mgr_timer.start()

        tick()

    # -- auth ----------------------------------------------------------

    def _check_auth(self, method, req) -> str | None:
        """Verify the AWS v2 signature when present.

        Returns the authenticated access key, or None for an anonymous
        request — anonymous is no longer rejected here; per-route
        canned-ACL checks (_authorize) decide what it may touch.  A
        PRESENT but bad signature still fails closed."""
        if not self.credentials:
            return None
        auth = req.headers.get("Authorization", "")
        if not auth:
            return None
        if not auth.startswith("AWS "):
            raise S3Error(403, "AccessDenied", "malformed auth")
        try:
            access, sig = auth[4:].split(":", 1)
        except ValueError:
            raise S3Error(403, "AccessDenied", "malformed auth")
        secret = self.credentials.get(access)
        if secret is None:
            raise S3Error(403, "InvalidAccessKeyId", access)
        path = urlsplit(req.path).path
        hdrs = {k.lower(): v for k, v in req.headers.items()}
        want = _sign_v2(secret, string_to_sign(method, path, hdrs))
        if not hmac.compare_digest(sig, want):
            raise S3Error(403, "SignatureDoesNotMatch", "")
        return access

    #: canned ACLs both fronts understand (rgw_acl.cc's canned set,
    #: minus the ownership-transfer ones a single-tenant gateway
    #: cannot express)
    CANNED_ACLS = ("private", "public-read", "public-read-write")

    def _authorize(self, principal, bucket, want: str) -> None:
        """Gate one op: want is 'read' | 'write' | 'owner'.

        Authenticated principals own everything (single-tenant);
        anonymous requests pass only where the bucket's canned ACL
        grants them, and never at the service/owner level."""
        if not self.credentials or principal is not None:
            return
        if want == "owner" or not bucket:
            raise S3Error(403, "AccessDenied", "authentication required")
        acl = self.store.bucket_acl(bucket)
        if want == "read" and acl in ("public-read",
                                      "public-read-write"):
            return
        if want == "write" and acl == "public-read-write":
            return
        raise S3Error(403, "AccessDenied", "anonymous vs %s acl" % acl)

    # -- Swift front (rgw_rest_swift.cc role) --------------------------
    #
    # TempAuth-style handshake: GET /auth/v1.0 with X-Auth-User /
    # X-Auth-Key returns X-Auth-Token + X-Storage-Url; the data API
    # lives under /swift/v1/<container>[/<object>]. Containers and S3
    # buckets are the same namespace (one roster, one index), so ACLs
    # set on either front gate anonymous access on both.

    def _swift(self, method, req):
        try:
            return self._swift_route(method, req)
        except S3Error as e:
            # Swift speaks plain-text errors, not S3's XML envelope
            return e.status, {"Content-Type": "text/plain"}, \
                ("%s: %s\n" % (e.code, e.message)).encode()

    def _swift_principal(self, req) -> str | None:
        if not self.credentials:
            return "anonymous-ok"       # auth off: everything passes
        return self._swift_tokens.get(
            req.headers.get("X-Auth-Token", ""))

    @staticmethod
    def _swift_acl_from(req, default: str = "private") -> str | None:
        """Map Swift container ACL headers onto the canned set:
        X-Container-Read '.r:*' -> public-read, plus X-Container-Write
        '.r:*'/'*' -> public-read-write. Returns None when neither
        header is present (POST must not clobber an unrelated ACL)."""
        read_hdr = req.headers.get("X-Container-Read")
        write_hdr = req.headers.get("X-Container-Write")
        if read_hdr is None and write_hdr is None:
            return None
        public_read = ".r:*" in (read_hdr or "")
        public_write = ".r:*" in (write_hdr or "") or \
            (write_hdr or "").strip() == "*"
        if public_write:
            return "public-read-write"
        if public_read:
            return "public-read"
        return default

    def _swift_route(self, method, req):
        split = urlsplit(req.path)
        path = unquote(split.path)
        if path == "/auth/v1.0":
            user = req.headers.get("X-Auth-User", "")
            key = req.headers.get("X-Auth-Key", "")
            if self.credentials and \
                    self.credentials.get(user) != key:
                raise S3Error(401, "Unauthorized", "bad credentials")
            token = "AUTH_tk" + uuid.uuid4().hex
            self._swift_tokens[token] = user or "anonymous"
            url = "http://%s:%d/swift/v1" % (self.addr[0],
                                             self.addr[1])
            return 200, {"X-Auth-Token": token,
                         "X-Storage-Token": token,
                         "X-Storage-Url": url}, b""
        if not (path == "/swift/v1" or path.startswith("/swift/v1/")):
            raise S3Error(404, "NotFound", path)
        rest = path[len("/swift/v1"):].lstrip("/")
        cparts = rest.split("/", 1) if rest else []
        container = cparts[0] if cparts else ""
        obj = cparts[1] if len(cparts) > 1 else ""
        query = parse_qs(split.query, keep_blank_values=True)
        principal = self._swift_principal(req)
        if not container:               # account level
            if method in ("GET", "HEAD"):
                self._authorize(principal, None, "owner")
                names = self.store.list_buckets()
                body = ("".join(n + "\n" for n in names)).encode() \
                    if method == "GET" else b""
                return (200 if names and method == "GET" else 204), \
                    {"Content-Type": "text/plain",
                     "X-Account-Container-Count": str(len(names))}, \
                    body
            raise S3Error(405, "MethodNotAllowed", method)
        if not obj:                     # container level
            if method == "PUT":
                self._authorize(principal, None, "owner")
                acl = self._swift_acl_from(req) or "private"
                try:
                    self.store.create_bucket(container, acl)
                    return 201, {}, b""
                except S3Error as e:
                    if e.code != "BucketAlreadyExists":
                        raise
                    if self._swift_acl_from(req) is not None:
                        self.store.set_bucket_acl(container, acl)
                    return 202, {}, b""
            if method == "POST":
                self._authorize(principal, container, "owner")
                acl = self._swift_acl_from(req)
                if acl is not None:
                    self.store.set_bucket_acl(container, acl)
                return 204, {}, b""
            if method == "DELETE":
                self._authorize(principal, container, "owner")
                self.store.delete_bucket(container)
                return 204, {}, b""
            if method == "GET":
                self._authorize(principal, container, "read")
                prefix = (query.get("prefix") or [""])[0]
                entries = self.store.list_objects(container, prefix)
                body = "".join(e["key"] + "\n"
                               for e in entries).encode()
                return (200 if entries else 204), \
                    {"Content-Type": "text/plain"}, body
            if method == "HEAD":
                self._authorize(principal, container, "read")
                entries = self.store.list_objects(container)
                acl = self.store.bucket_acl(container)
                hdrs = {"X-Container-Object-Count":
                        str(len(entries))}
                if acl in ("public-read", "public-read-write"):
                    hdrs["X-Container-Read"] = ".r:*"
                if acl == "public-read-write":
                    hdrs["X-Container-Write"] = ".r:*"
                return 204, hdrs, b""
            raise S3Error(405, "MethodNotAllowed", method)
        # object level
        if method == "PUT":
            self._authorize(principal, container, "write")
            data = self._read_body(req)
            etag = self.store.put_object(container, obj, data)
            gw_hdrs = {"Etag": etag}
            return 201, gw_hdrs, b""
        if method == "GET":
            self._authorize(principal, container, "read")
            data, meta = self.store.get_object(container, obj)
            return 200, {"Content-Type": "binary/octet-stream",
                         "Etag": meta["etag"]}, data
        if method == "HEAD":
            self._authorize(principal, container, "read")
            meta = self.store.head_object(container, obj)
            return 200, {"Content-Length-Real": str(meta["size"]),
                         "Etag": meta["etag"]}, b""
        if method == "DELETE":
            self._authorize(principal, container, "write")
            self.store.delete_object(container, obj)
            return 204, {}, b""
        raise S3Error(405, "MethodNotAllowed", method)

    # -- routing -------------------------------------------------------

    @staticmethod
    def _read_body(req) -> bytes:
        try:
            length = int(req.headers.get("Content-Length", "0") or 0)
        except ValueError:
            raise S3Error(400, "InvalidArgument", "Content-Length")
        return req.rfile.read(length) if length > 0 else b""

    def _canned_acl_from(self, req, default: str = "private") -> str:
        acl = req.headers.get("x-amz-acl", "") or default
        if acl not in self.CANNED_ACLS:
            raise S3Error(400, "InvalidArgument",
                          "unsupported canned acl %r" % acl)
        return acl

    def _route(self, method, req, principal=None):
        split = urlsplit(req.path)
        parts = unquote(split.path).lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        # keep_blank_values: S3 subresources are valueless keys
        # ("?uploads", "?acl") that parse_qs drops by default
        query = parse_qs(split.query, keep_blank_values=True)
        if not bucket:
            if method == "GET":
                self._authorize(principal, None, "owner")
                return self._list_buckets()
            raise S3Error(405, "MethodNotAllowed", method)
        if not key:
            if "acl" in query:
                # bucket ACL subresource: owner-only on both verbs
                self._authorize(principal, bucket, "owner")
                if method == "PUT":
                    self.store.set_bucket_acl(
                        bucket, self._canned_acl_from(req))
                    return 200, {}, b""
                if method == "GET":
                    acl = self.store.bucket_acl(bucket)
                    body = ("<?xml version=\"1.0\" encoding=\"UTF-8\""
                            "?><AccessControlPolicy><Canned>%s"
                            "</Canned></AccessControlPolicy>"
                            % escape(acl)).encode()
                    return 200, {"Content-Type": "application/xml"}, \
                        body
                raise S3Error(405, "MethodNotAllowed", method)
            if method == "PUT":
                self._authorize(principal, None, "owner")
                self.store.create_bucket(bucket,
                                         self._canned_acl_from(req))
                return 200, {"Location": "/" + bucket}, b""
            if method == "DELETE":
                self._authorize(principal, bucket, "owner")
                self.store.delete_bucket(bucket)
                return 204, {}, b""
            if method == "GET":
                self._authorize(principal, bucket, "read")
                if "uploads" in query:
                    return self._list_uploads(bucket)
                return self._list_objects(bucket, query)
            raise S3Error(405, "MethodNotAllowed", method)
        if method in ("PUT", "POST", "DELETE"):
            self._authorize(principal, bucket, "write")
        else:
            self._authorize(principal, bucket, "read")
        if method == "POST":
            # drain the body up front: on a keep-alive connection an
            # unread body corrupts the next request's parse
            body_in = self._read_body(req)
            if "uploads" in query:
                upload_id = self.store.initiate_multipart(bucket, key)
                body = ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                        "<InitiateMultipartUploadResult>"
                        "<Bucket>%s</Bucket><Key>%s</Key>"
                        "<UploadId>%s</UploadId>"
                        "</InitiateMultipartUploadResult>"
                        % (escape(bucket), escape(key),
                           upload_id)).encode()
                return 200, {"Content-Type": "application/xml"}, body
            if "uploadId" in query:
                parts = _parse_complete_xml(
                    body_in.decode("utf-8", "replace"))
                etag = self.store.complete_multipart(
                    bucket, query["uploadId"][0], parts)
                body = ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                        "<CompleteMultipartUploadResult><Key>%s</Key>"
                        "<ETag>&quot;%s&quot;</ETag>"
                        "</CompleteMultipartUploadResult>"
                        % (escape(key), etag)).encode()
                return 200, {"Content-Type": "application/xml"}, body
            raise S3Error(405, "MethodNotAllowed", method)
        if method == "PUT":
            data = self._read_body(req)
            if "partNumber" in query and "uploadId" in query:
                try:
                    part_no = int(query["partNumber"][0])
                except ValueError:
                    raise S3Error(400, "InvalidArgument",
                                  query["partNumber"][0])
                etag = self.store.upload_part(
                    bucket, query["uploadId"][0], part_no, data)
                return 200, {"ETag": '"%s"' % etag}, b""
            etag = self.store.put_object(bucket, key, data)
            return 200, {"ETag": '"%s"' % etag}, b""
        if method == "GET":
            data, meta = self.store.get_object(bucket, key)
            rng = req.headers.get("Range", "")
            m = re.match(r"bytes=(\d*)-(\d*)$", rng or "")
            if m and (m.group(1) or m.group(2)):
                total = len(data)
                if m.group(1):
                    lo = int(m.group(1))
                    hi = int(m.group(2)) if m.group(2) else total - 1
                else:               # suffix range: last N bytes
                    lo = max(0, total - int(m.group(2)))
                    hi = total - 1
                if lo >= total or lo > hi:
                    raise S3Error(416, "InvalidRange", rng)
                hi = min(hi, total - 1)
                return 206, {
                    "Content-Type": "binary/octet-stream",
                    "Content-Range": "bytes %d-%d/%d" % (lo, hi, total),
                    "ETag": '"%s"' % meta["etag"],
                }, data[lo:hi + 1]
            return 200, {"Content-Type": "binary/octet-stream",
                         "ETag": '"%s"' % meta["etag"]}, data
        if method == "HEAD":
            meta = self.store.head_object(bucket, key)
            return 200, {"Content-Length-Real": str(meta["size"]),
                         "ETag": '"%s"' % meta["etag"]}, b""
        if method == "DELETE":
            if "uploadId" in query:
                self.store.abort_multipart(bucket, query["uploadId"][0])
                return 204, {}, b""
            self.store.delete_object(bucket, key)
            return 204, {}, b""
        raise S3Error(405, "MethodNotAllowed", method)

    # -- XML renderings (rgw_rest_s3 dump_* role) ----------------------

    def _list_uploads(self, bucket):
        rows = "".join(
            "<Upload><Key>%s</Key><UploadId>%s</UploadId></Upload>"
            % (escape(u["key"]), u["upload_id"])
            for u in self.store.list_multipart_uploads(bucket))
        body = ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                "<ListMultipartUploadsResult><Bucket>%s</Bucket>%s"
                "</ListMultipartUploadsResult>"
                % (escape(bucket), rows)).encode()
        return 200, {"Content-Type": "application/xml"}, body

    def _list_buckets(self):
        rows = "".join(
            "<Bucket><Name>%s</Name></Bucket>" % escape(b)
            for b in self.store.list_buckets())
        body = ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                "<ListAllMyBucketsResult><Buckets>%s</Buckets>"
                "</ListAllMyBucketsResult>" % rows).encode()
        return 200, {"Content-Type": "application/xml"}, body

    def _list_objects(self, bucket, query):
        prefix = (query.get("prefix") or [""])[0]
        max_keys = int((query.get("max-keys") or ["1000"])[0])
        entries = self.store.list_objects(bucket, prefix, max_keys)
        rows = "".join(
            "<Contents><Key>%s</Key><Size>%d</Size>"
            "<ETag>&quot;%s&quot;</ETag></Contents>"
            % (escape(e["key"]), e["size"], e["etag"])
            for e in entries)
        body = ("<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
                "<ListBucketResult><Name>%s</Name><Prefix>%s</Prefix>"
                "%s</ListBucketResult>"
                % (escape(bucket), escape(prefix), rows)).encode()
        return 200, {"Content-Type": "application/xml"}, body
