"""Journaler: an append/replay/trim journal striped over RADOS objects.

Role of the reference's src/journal/ library (Journaler.cc,
JournalMetadata.cc, Entry.cc, ObjectRecorder.cc, JournalTrimmer.cc):

  metadata object   `journal.<id>` — omap carries the journal's
                    geometry ("meta": order, splay_width,
                    entries_per_object) plus one record per registered
                    client ("client.<id>": commit position). Clients
                    are the master writer ("") and mirror peers;
                    trimming may only pass the MINIMUM commit position
                    over all of them (JournalMetadata::committed).
  data objects      `journal_data.<id>.<objnum>` — entries are
                    splayed across `splay_width` concurrent streams
                    (ObjectRecorder), advancing to a fresh object set
                    as objects fill. The reference advances sets when
                    an object exceeds 2^order bytes; here the set
                    advances every `entries_per_object` entries per
                    stream — same role (bounded objects + splay) with
                    a deterministic tid -> object mapping:
                        object(tid) = (tid % w) + w * set(tid)
                        set(tid)    = tid // (w * entries_per_object)
  entry framing     Entry.cc: a preamble magic, the entry tid, the
                    tag, the payload, and a CRC the replayer verifies
                    (torn tail entries after a crash are dropped, not
                    replayed as garbage).

Single-writer contract: like the reference (which gates journaling
behind librbd's exclusive lock), exactly one master Journaler appends
at a time; readers/committers are unrestricted.
"""

from __future__ import annotations

import struct
import zlib

from .. import encoding

__all__ = ["Journaler", "JournalExists", "JournalNotFound"]

ENTRY_MAGIC = b"JRNE"


class JournalExists(Exception):
    pass


class JournalNotFound(Exception):
    pass


def _meta_oid(journal_id: str) -> str:
    return "journal.%s" % journal_id


def _data_oid(journal_id: str, objnum: int) -> str:
    return "journal_data.%s.%d" % (journal_id, objnum)


def _frame(tid: int, tag: str, payload: bytes) -> bytes:
    tag_b = tag.encode()
    body = struct.pack("<QII", tid, len(tag_b), len(payload)) \
        + tag_b + payload
    return ENTRY_MAGIC + struct.pack("<I", zlib.crc32(body)) + body


def _unframe(buf: bytes, off: int):
    """Parse one entry at off; returns (tid, tag, payload, next_off) or
    None for a torn/corrupt tail (replay stops there, like the
    reference treats a bad preamble as end-of-journal)."""
    if off + 24 > len(buf) or buf[off:off + 4] != ENTRY_MAGIC:
        return None
    (crc,) = struct.unpack_from("<I", buf, off + 4)
    tid, tag_len, pay_len = struct.unpack_from("<QII", buf, off + 8)
    end = off + 24 + tag_len + pay_len
    if end > len(buf):
        return None
    body = buf[off + 8:end]
    if zlib.crc32(body) != crc:
        return None
    tag = buf[off + 24:off + 24 + tag_len].decode()
    payload = buf[off + 24 + tag_len:end]
    return tid, tag, payload, end


class Journaler:
    def __init__(self, ioctx, journal_id: str, order: int = 24,
                 splay_width: int = 4, entries_per_object: int = 64):
        self.ioctx = ioctx
        self.journal_id = journal_id
        self.order = order
        self.splay_width = splay_width
        self.entries_per_object = entries_per_object
        self.next_tid = 0
        self._open = False
        self._commit_cache: dict = {}  # client_id -> last commit tid
        # journals written by the reserve-before-write append() can
        # never hold a tid past the meta floor; once that invariant is
        # established (at create, or by one legacy tail scan) the
        # writer-open scan is skipped forever
        self._tail_scanned = True
        # incarnation id: lets pollers (rbd-mirror idle cache) detect
        # a journal that was deleted and recreated under the same name
        self.nonce: str | None = None

    # -- lifecycle -----------------------------------------------------

    def create(self) -> None:
        """Persist the metadata object (journal::Journaler::create).
        A metadata object WITHOUT a "meta" omap key is a half-created
        corpse (crash between write_full and omap_set): repair it
        instead of raising, so the owning image never bricks."""
        oid = _meta_oid(self.journal_id)
        exists = True
        try:
            self.ioctx.stat(oid)
        except OSError:
            exists = False
        if exists and "meta" in self.ioctx.omap_get(oid):
            raise JournalExists(self.journal_id)
        if not exists:
            self.ioctx.write_full(oid, b"")
        import uuid
        self.nonce = uuid.uuid4().hex
        self.ioctx.omap_set(oid, {
            "meta": encoding.encode_any({
                "order": self.order,
                "splay_width": self.splay_width,
                "entries_per_object": self.entries_per_object,
                "next_tid": 0, "tail_scanned": True,
                "nonce": self.nonce})})
        self._open = True

    def open(self, for_append: bool = False) -> None:
        meta = self._load_meta()
        self.order = meta["order"]
        self.splay_width = meta["splay_width"]
        self.entries_per_object = meta["entries_per_object"]
        self.next_tid = meta["next_tid"]
        self._tail_scanned = meta.get("tail_scanned", False)
        self.nonce = meta.get("nonce")
        if for_append and not self._tail_scanned:
            # The metadata's next_tid is a *reservation floor*, not
            # the truth: the reference's JournalPlayer derives the
            # real end by scanning object tails (ObjectPlayer::fetch),
            # because a crash can leave entries the metadata has not
            # caught up to.  Scan the active object set and advance
            # past any tid found so a restarted master never re-issues
            # a tid that is already on disk with a different payload.
            # Writer-only: a read-only peer (rbd-mirror poll) must
            # neither pay 2*splay_width object reads per poll nor race
            # the master's own "meta" omap writes.
            per_set = self.splay_width * self.entries_per_object
            cur_set = self.next_tid // per_set
            for s in (cur_set, cur_set + 1):
                for i in range(self.splay_width):
                    objnum = s * self.splay_width + i
                    try:
                        buf = self.ioctx.read(
                            _data_oid(self.journal_id, objnum))
                    except OSError:
                        continue
                    off = 0
                    while True:
                        parsed = _unframe(buf, off)
                        if parsed is None:
                            break
                        tid, _tag, _payload, off = parsed
                        if tid >= self.next_tid:
                            self.next_tid = tid + 1
            self._tail_scanned = True
            self._save_meta()         # records the repair marker too
        self._open = True

    def _load_meta(self) -> dict:
        try:
            omap = self.ioctx.omap_get(_meta_oid(self.journal_id))
        except OSError:
            raise JournalNotFound(self.journal_id)
        raw = omap.get("meta")
        if raw is None:
            raise JournalNotFound(self.journal_id)
        return encoding.decode_any(raw)

    def _save_meta(self) -> None:
        self.ioctx.omap_set(_meta_oid(self.journal_id), {
            "meta": encoding.encode_any({
                "order": self.order,
                "splay_width": self.splay_width,
                "entries_per_object": self.entries_per_object,
                "next_tid": self.next_tid,
                "tail_scanned": self._tail_scanned,
                "nonce": self.nonce})})

    @staticmethod
    def exists(ioctx, journal_id: str) -> bool:
        try:
            ioctx.stat(_meta_oid(journal_id))
            return True
        except OSError:
            return False

    def remove(self) -> None:
        """Delete every data object and the metadata object."""
        per_set = self.splay_width * self.entries_per_object
        last_set = self.next_tid // per_set
        for objnum in range((last_set + 1) * self.splay_width):
            try:
                self.ioctx.remove(_data_oid(self.journal_id, objnum))
            except OSError:
                pass
        try:
            self.ioctx.remove(_meta_oid(self.journal_id))
        except OSError:
            pass
        self._open = False

    # -- geometry ------------------------------------------------------

    def _object_of(self, tid: int) -> int:
        per_set = self.splay_width * self.entries_per_object
        return (tid % self.splay_width) \
            + self.splay_width * (tid // per_set)

    # -- clients (JournalMetadata register/commit) ---------------------

    def register_client(self, client_id: str) -> None:
        key = "client.%s" % client_id
        oid = _meta_oid(self.journal_id)
        omap = self.ioctx.omap_get(oid)
        if key not in omap:
            self.ioctx.omap_set(oid, {key: encoding.encode_any(
                {"commit_tid": -1})})

    def unregister_client(self, client_id: str) -> None:
        self.ioctx.omap_rm_keys(_meta_oid(self.journal_id),
                                ["client.%s" % client_id])

    def clients(self) -> dict:
        """client_id -> commit_tid (entries <= tid are consumed)."""
        omap = self.ioctx.omap_get(_meta_oid(self.journal_id))
        out = {}
        for k, v in omap.items():
            if k.startswith("client."):
                out[k[len("client."):]] = \
                    encoding.decode_any(v)["commit_tid"]
        return out

    def commit(self, client_id: str, tid: int) -> None:
        """Advance a client's commit position (monotonic). Each client
        id has ONE committer (the single-writer contract), so the last
        position is cached in memory after the first read — per-entry
        commits cost one omap write, not a full metadata read-back."""
        cur = self._commit_cache.get(client_id)
        if cur is None:
            cur = self.committed(client_id)
        if tid > cur:
            self.ioctx.omap_set(_meta_oid(self.journal_id), {
                "client.%s" % client_id:
                    encoding.encode_any({"commit_tid": tid})})
            self._commit_cache[client_id] = tid
        else:
            self._commit_cache[client_id] = cur

    def committed(self, client_id: str) -> int:
        omap = self.ioctx.omap_get(_meta_oid(self.journal_id))
        raw = omap.get("client.%s" % client_id)
        return encoding.decode_any(raw)["commit_tid"] \
            if raw is not None else -1

    # -- append / replay / trim ----------------------------------------

    def append(self, tag: str, payload: bytes) -> int:
        """Reserve the tid durably BEFORE writing the frame.  A crash
        between the two leaves a hole at tid N (replay skips it; the
        next writer uses N+1) — never two distinct entries sharing one
        tid, which would silently desync any client whose commit
        position already covered N."""
        assert self._open, "journal not open"
        tid = self.next_tid
        self.next_tid = tid + 1
        self._save_meta()
        self.ioctx.append(_data_oid(self.journal_id,
                                    self._object_of(tid)),
                          _frame(tid, tag, payload))
        return tid

    def iterate(self, from_tid: int = -1):
        """Yield (tid, tag, payload) for every intact entry with
        tid > from_tid, in tid order (JournalPlayer role). Sets hold
        contiguous tid ranges, so reading starts at the set containing
        from_tid+1 — a tailing mirror does not re-read the whole
        journal every poll."""
        entries = []
        per_set = self.splay_width * self.entries_per_object
        meta = self._load_meta()
        if from_tid >= meta["next_tid"] - 1:
            return []                 # nothing new: zero object reads
        last_set = max(meta["next_tid"] - 1, 0) // per_set
        first_set = max(from_tid + 1, 0) // per_set
        for objnum in range(first_set * self.splay_width,
                            (last_set + 1) * self.splay_width):
            try:
                buf = self.ioctx.read(_data_oid(self.journal_id,
                                                objnum))
            except OSError:
                continue
            off = 0
            while True:
                parsed = _unframe(buf, off)
                if parsed is None:
                    break
                tid, tag, payload, off = parsed
                if tid > from_tid:
                    entries.append((tid, tag, payload))
        entries.sort(key=lambda e: e[0])
        return entries

    def trim(self) -> int:
        """Delete object sets every registered client has fully
        committed (JournalTrimmer::trim_objects). Returns how many
        data objects were removed."""
        positions = self.clients()
        if not positions:
            return 0
        floor = min(positions.values())
        per_set = self.splay_width * self.entries_per_object
        # a set s holds tids [s*per_set, (s+1)*per_set): removable when
        # every tid below the NEXT set start is committed
        removable_sets = (floor + 1) // per_set
        # trim progress lives in its OWN omap key: a mirror peer trims
        # the remote journal while the master keeps rewriting "meta",
        # and the two must not clobber each other
        oid = _meta_oid(self.journal_id)
        omap = self.ioctx.omap_get(oid)
        trimmed_before = int(omap.get("trimmed", b"0"))
        removed = 0
        for s in range(trimmed_before, removable_sets):
            for i in range(self.splay_width):
                try:
                    self.ioctx.remove(_data_oid(
                        self.journal_id, s * self.splay_width + i))
                    removed += 1
                except OSError:
                    pass
        if removable_sets > trimmed_before:
            self.ioctx.omap_set(oid, {
                "trimmed": str(removable_sets).encode()})
        return removed
