"""rbd-mirror: journal-based asynchronous image replication.

Role of the reference's src/tools/rbd_mirror/ daemon:

  ClusterWatcher / PoolReplayer   watch the remote pool for images
                    with journaling enabled and spin up a replayer per
                    image (PoolReplayer.cc role; here one polling loop
                    covers the pool).
  ImageReplayer::bootstrap        first sight of an image copies its
                    current content into the local cluster
                    (BootstrapRequest.cc / image_sync/ — a full sync),
                    pinning the journal position observed BEFORE the
                    copy began so events raced by the sync are
                    replayed afterward (replay is idempotent).
  ImageReplayer::replay           tail the REMOTE image journal from
                    this peer's commit position, apply each event to
                    the local image through the normal librbd surface,
                    then advance the commit position — which lets the
                    primary's JournalTrimmer retire fully-consumed
                    journal objects.

The peer registers in the remote journal as client
"mirror.<peer_uuid>"; the master writer is client "". Promotion/
demotion and the two-way split-brain machinery (tag ownership chains)
are out of scope: images replicate one-way, primary -> secondary.
"""

from __future__ import annotations

import threading
import uuid

from .. import encoding
from ..client.rbd import RBD, Image, ImageNotFound, _journal_id
from .journal import Journaler, JournalNotFound

__all__ = ["RbdMirror"]


class RbdMirror:
    """One-way pool replayer: remote (primary) ioctx -> local
    (secondary) ioctx."""

    def __init__(self, local_ioctx, remote_ioctx,
                 peer_uuid: str | None = None,
                 interval: float = 0.1):
        self.local = local_ioctx
        self.remote = remote_ioctx
        self.peer_uuid = peer_uuid or uuid.uuid4().hex[:12]
        self.client_id = "mirror.%s" % self.peer_uuid
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # image -> replay status (the `rbd mirror image status` role)
        self.status: dict = {}
        # image -> ((journal nonce, next_tid, pos), cached_at): a
        # crashed primary can leave a reserved-but-unwritten tail tid
        # (reserve-before-write append), which would otherwise defeat
        # the caught-up fast path and re-read the object set forever.
        # Entries EXPIRE (idle_verify_interval) so a frame whose write
        # was merely in flight during the fruitless poll is picked up
        # on the next verify instead of being suppressed forever.
        self._idle_cache: dict = {}
        self.idle_verify_interval = 5.0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="rbd-mirror-%s"
                                        % self.peer_uuid, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.replay_pool_once()
            except Exception as e:
                self.status["_pool"] = "error: %r" % (e,)
            self._stop.wait(self.interval)

    # -- replication ---------------------------------------------------

    def mirrored_images(self) -> list[str]:
        """Images on the PRIMARY with journaling enabled (pool-mode
        mirroring: the feature bit opts the image in)."""
        out = []
        for name in RBD.list(self.remote):
            try:
                # read_only: a mirror must never replay (= write) the
                # PRIMARY's journal while probing its feature bits
                img = Image(self.remote, name, read_only=True)
            except ImageNotFound:
                continue
            if "journaling" in img.meta.get("features", []):
                out.append(name)
        return out

    def replay_pool_once(self) -> None:
        for name in self.mirrored_images():
            self.replay_image_once(name)

    def replay_image_once(self, name: str) -> None:
        try:
            journal = Journaler(self.remote, _journal_id(name))
            journal.open()
        except JournalNotFound:
            return
        if self.client_id not in journal.clients():
            journal.register_client(self.client_id)
        try:
            local_img = Image(self.local, name)
        except ImageNotFound:
            local_img = self._bootstrap(name, journal)
            if local_img is None:
                return
        import time as _time
        applied = 0
        pos = journal.committed(self.client_id)
        idle_key = (journal.nonce, journal.next_tid, pos)
        cached = self._idle_cache.get(name)
        if (pos >= journal.next_tid - 1
                or (cached is not None and cached[0] == idle_key
                    and _time.monotonic() - cached[1]
                    < self.idle_verify_interval)):
            # caught up — or a tail hole with nothing new appended
            # since the last fruitless poll: zero data-object reads
            self.status[name] = {"state": "replaying", "position": pos}
            return
        for tid, tag, payload in journal.iterate(pos):
            self._apply(local_img, encoding.decode_any(payload))
            journal.commit(self.client_id, tid)
            applied += 1
        if applied:
            self._idle_cache.pop(name, None)
            journal.trim()            # let the primary retire objects
        else:
            self._idle_cache[name] = (idle_key, _time.monotonic())
        self.status[name] = {"state": "replaying",
                             "position": journal.committed(
                                 self.client_id)}

    def _bootstrap(self, name: str, journal: Journaler):
        """Full image sync (BootstrapRequest role). The commit
        position is pinned to the master's position observed BEFORE
        the copy: events landing during the copy are replayed again
        afterward, and replay is idempotent."""
        pre_copy_pos = journal.committed("")
        src = Image(self.remote, name, read_only=True)
        try:
            RBD.create(self.local, name, src.size(), order=src.order)
        except Exception:
            pass                      # raced another replayer
        dst = Image(self.local, name)
        step = src.block_size
        for off in range(0, src.size(), step):
            chunk = src.read(off, min(step, src.size() - off))
            if chunk.strip(b"\0"):
                dst.write(off, chunk)
        journal.commit(self.client_id, pre_copy_pos)
        self.status[name] = {"state": "bootstrapped",
                             "position": pre_copy_pos}
        return dst

    @staticmethod
    def _apply(img: Image, ev: dict) -> None:
        """Event application through the normal librbd surface
        (ImageReplayer -> journal/Replay handlers)."""
        img._apply_event(ev)
