"""Services on RADOS (SURVEY §1 layer 9): the object gateway.

  rgw    S3-subset REST gateway over client/rados.py — the role of
         src/rgw/rgw_rest_s3.cc at framework scale.
"""

from .rgw import RGWServer, S3Error

__all__ = ["RGWServer", "S3Error"]
