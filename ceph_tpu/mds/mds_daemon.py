"""MDS: the CephFS metadata server.

Role of the reference's src/mds/ (75k LoC) at framework scale. The
on-RADOS metadata layout follows the reference's design:

  dirfrags      each directory ino owns an object `dir.<ino>` in the
                METADATA pool whose omap maps dentry name -> encoded
                inode record (CDir/CDentry over omap,
                src/mds/CDir.cc _omap_fetch/_omap_commit). Inodes are
                embedded in their primary dentry exactly like the
                reference's primary-link embedding (doc: "inodes are
                stored in the dentry").
  inode table   `mds_inotable` allocates ino numbers
                (src/mds/InoTable.h role); root is ino 1.
  MDS journal   every metadata mutation appends an EUpdate-style
                event to a Journaler (`mds.<rank>` in the metadata
                pool — src/mds/journal.cc EUpdate, MDLog) BEFORE the
                omap apply; a newly-active MDS replays the
                uncommitted tail idempotently (crash recovery /
                failover takeover).
  file data     lives in the DATA pool as `<ino-hex>.<objno>` objects
                written directly by clients through the striper
                layout (CephFS file layout, src/osdc/Filer role) —
                the MDS never touches file bytes except to purge them
                on unlink (PurgeQueue role).

Liveness + rank: the daemon beacons to the monitor
(MMDSBeacon/MDSMonitor); the mdsmap names ONE active MDS and
standbys. A standby watches the mdsmap and takes over by replaying
the shared journal. Capabilities (client caps / coherent client
caching) are consciously reduced: metadata ops serialize at the
active MDS and clients do uncached data IO — the consistency model
of the reference with caps disabled.

Client protocol: MClientRequest{op, args} -> MClientReply, with
(session, tid) exactly-once dedup for the non-idempotent ops
(rename/unlink), like the OSD's reqid dedup.
"""

from __future__ import annotations

import errno
import threading
import time

from .. import encoding
from ..common import Context
from ..common.bounded import BoundedDict
from ..msg.async_messenger import create_messenger
from ..msg.message import MClientReply, MMDSBeacon
from ..msg.messenger import Dispatcher
from ..mon.mon_client import MonClient
from ..services.journal import JournalExists, Journaler

__all__ = ["MDSDaemon", "ROOT_INO"]

ROOT_INO = 1
INOTABLE_OID = "mds_inotable"


def dir_oid(ino: int) -> str:
    return "dir.%x" % ino


def data_oid(ino: int, objno: int) -> str:
    """CephFS data object naming: <ino-hex>.<objno-hex>
    (src/include/ceph_fs.h file layout)."""
    return "%x.%08x" % (ino, objno)


class MDSDaemon(Dispatcher):
    def __init__(self, name: str, monmap: dict,
                 ctx: Context | None = None):
        self.name = name
        self.ctx = ctx or Context(name="mds.%s" % name)
        self.msgr = create_messenger(("mds", name), conf=self.ctx.conf)
        self.monmap = dict(monmap)
        self.mon_client = MonClient(monmap, self.msgr,
                                    "mds.%s" % name)
        self.state = "boot"            # boot | standby | active
        self.lock = threading.RLock()
        self._rados = None             # internal RadosClient
        self.meta_io = None
        self.data_io = None
        self.journal: Journaler | None = None
        self._next_ino = 0
        self._replies: BoundedDict = BoundedDict()   # (session,tid)
        # mgr telemetry: l_mds_* counters + the MMgrReport stream
        from ..common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("mds")
                     .add_u64_counter("request",
                                      "client metadata requests")
                     .add_time_avg("request_latency",
                                   "client request handling time")
                     .create_perf_counters())
        self.ctx.perf.add(self.perf)
        self.mgr_addr = None
        self._last_mgr_report = 0.0
        # delta-encoded telemetry stream (common/telemetry.py)
        from ..common.telemetry import DeltaReporter
        self._mgr_reporter = DeltaReporter()
        self._running = False
        self._beacon_token = None

    # -- lifecycle -----------------------------------------------------

    def init(self) -> None:
        self.msgr.bind()
        self.msgr.add_dispatcher_head(self)
        self.msgr.start()
        self._running = True
        self.mon_client.mdsmap_callbacks.append(self._on_mdsmap)
        self.mon_client.sub_want()
        self._beacon()

    def shutdown(self) -> None:
        self._running = False
        if self._beacon_token is not None:
            self._beacon_token.cancel()
        if self._rados is not None:
            self._rados.shutdown()
        self.msgr.shutdown()
        self.ctx.shutdown()

    def _beacon(self) -> None:
        if not self._running:
            return
        self.msgr.send_message(
            MMDSBeacon(name=self.name, addr=self.msgr.my_addr,
                       state=self.state),
            self.monmap[min(self.monmap)])
        try:
            # telemetry is best-effort: it must never kill the beacon
            # chain (the mon fails an MDS that stops beaconing)
            self._mgr_report()
        except Exception:
            pass
        t = threading.Timer(
            self.ctx.conf.get_val("mds_beacon_interval"), self._beacon)
        t.daemon = True
        t.start()
        self._beacon_token = t

    def _mgr_report(self) -> None:
        """MDS leg of the cluster telemetry stream, rate-limited to
        the mgr_stats_period cadence (0 = off)."""
        if self.mgr_addr is None:
            return
        import time as _time
        period = self.ctx.conf.get_val("mgr_stats_period")
        now = _time.monotonic()
        if period <= 0 or now - self._last_mgr_report < period:
            return
        self._last_mgr_report = now
        from ..msg.message import MMgrReport
        rep = self._mgr_reporter.prepare(self.ctx.perf.perf_dump(),
                                         self.ctx.perf.perf_schema())
        self.msgr.send_message(
            MMgrReport(daemon_name="mds.%s" % self.name,
                       daemon_type="mds",
                       perf=rep["perf"],
                       metadata={"state": self.state},
                       perf_schema=rep["schema"],
                       report_seq=rep["seq"],
                       incarnation=rep["incarnation"],
                       schema_hash=rep["schema_hash"],
                       delta_base=rep["delta_base"]),
            self.mgr_addr)

    def _on_mdsmap(self, mdsmap: dict) -> None:
        active = mdsmap.get("active")
        am_active = active is not None and active["name"] == self.name
        with self.lock:
            if am_active and self.state != "active":
                if mdsmap.get("fs"):
                    self._become_active(mdsmap["fs"])
            elif not am_active:
                # demotion is immediate on seeing the map — requests
                # already in flight answer EAGAIN from then on; real
                # fencing of a PARTITIONED active (which never sees
                # this map) is the mon's blocklist role, reduced here
                self.state = "standby"

    def _become_active(self, fs: dict) -> None:
        """Take the rank: open the pools, replay the shared journal,
        load the ino table (MDSRank::boot_start sequence —
        replay -> reconnect -> active, minus caps)."""
        from ..client.rados import RadosClient
        if self._rados is None:
            self._rados = RadosClient(
                self.monmap, client_id=200000 + abs(hash(self.name))
                % 10000)
            self._rados.connect()
        self.meta_io = self._rados.open_ioctx(fs["metadata_pool"])
        self.data_io = self._rados.open_ioctx(fs["data_pool"])
        self.journal = Journaler(self.meta_io, "mds.0")
        try:
            self.journal.create()
            self.journal.register_client("")
        except JournalExists:
            self.journal.open(for_append=True)
        # first activation plants the root dirfrag
        try:
            self.meta_io.stat(dir_oid(ROOT_INO))
        except OSError:
            self.meta_io.write_full(dir_oid(ROOT_INO), b"")
            self.meta_io.write_full(INOTABLE_OID, b"")
            self.meta_io.omap_set(INOTABLE_OID,
                                  {"next_ino": b"2"})
        # replay the uncommitted journal tail (failover/crash)
        done = self.journal.committed("")
        for tid, tag, payload in self.journal.iterate(done):
            self._apply_event(encoding.decode_any(payload))
            self.journal.commit("", tid)
        self.journal.trim()
        self._next_ino = int(self.meta_io.omap_get(
            INOTABLE_OID)["next_ino"])
        self.state = "active"

    # -- ino table -----------------------------------------------------

    def _alloc_ino(self) -> int:
        ino = self._next_ino
        self._next_ino += 1
        self.meta_io.omap_set(INOTABLE_OID, {
            "next_ino": str(self._next_ino).encode()})
        return ino

    # -- dirfrag access ------------------------------------------------

    def _dentry(self, dir_ino: int, name: str):
        try:
            omap = self.meta_io.omap_get(dir_oid(dir_ino))
        except OSError:
            return None
        raw = omap.get(name)
        return encoding.decode_any(raw) if raw is not None else None

    def _set_dentry(self, dir_ino: int, name: str, rec: dict) -> None:
        self.meta_io.omap_set(dir_oid(dir_ino),
                              {name: encoding.encode_any(rec)})

    def _rm_dentry(self, dir_ino: int, name: str) -> None:
        self.meta_io.omap_rm_keys(dir_oid(dir_ino), [name])

    # -- dispatch ------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if msg.get_type() == "MMgrReportAck":
            self._mgr_reporter.ack(msg.ack_seq, resync=msg.resync)
            return True
        if msg.get_type() != "MClientRequest":
            return False
        dest = msg.reply_to or msg.from_addr
        if self.state != "active":
            self.msgr.send_message(
                MClientReply(tid=msg.tid, result=-errno.EAGAIN,
                             session=msg.session), dest)
            return True
        key = (msg.session, msg.tid)
        with self.lock:
            cached = self._replies.get(key) if msg.session else None
            if cached is None:
                self.perf.inc("request")
                import time as _time
                t0 = _time.monotonic()
                try:
                    result, data = self._handle(msg.op, msg.args)
                except OSError as e:
                    result, data = -(e.errno or errno.EIO), None
                except Exception:
                    import logging
                    logging.getLogger("ceph_tpu.mds").exception(
                        "mds op %s failed", msg.op)
                    result, data = -errno.EIO, None
                self.perf.tinc("request_latency",
                               _time.monotonic() - t0)
                cached = MClientReply(tid=msg.tid, result=result,
                                      data=data, session=msg.session)
                if msg.session:
                    self._replies[key] = cached
        self.msgr.send_message(cached, dest)
        return True

    # -- op handlers (Server::handle_client_request dispatch) ----------

    def _handle(self, op: str, args: dict):
        fn = getattr(self, "_op_" + op, None)
        if fn is None:
            return -errno.ENOSYS, None
        return fn(args)

    def _journal_update(self, ev: dict) -> int:
        return self.journal.append("mds", encoding.encode_any(ev))

    def _commit(self, jtid: int) -> None:
        self.journal.commit("", jtid)
        per_set = self.journal.splay_width \
            * self.journal.entries_per_object
        if (jtid + 1) % per_set == 0:
            self.journal.trim()

    def _apply_event(self, ev: dict) -> None:
        """Idempotent EUpdate application — both the live path (after
        journaling) and replay go through here."""
        op = ev["op"]
        if op == "set_dentry":
            self._set_dentry(ev["dir"], ev["name"], ev["rec"])
            if ev.get("mkdir"):
                try:
                    self.meta_io.stat(dir_oid(ev["rec"]["ino"]))
                except OSError:
                    self.meta_io.write_full(
                        dir_oid(ev["rec"]["ino"]), b"")
            if ev["rec"]["ino"] >= self._next_ino:
                self._next_ino = ev["rec"]["ino"] + 1
                self.meta_io.omap_set(INOTABLE_OID, {
                    "next_ino": str(self._next_ino).encode()})
        elif op == "rm_dentry":
            self._rm_dentry(ev["dir"], ev["name"])
            self._apply_purge_hints(ev)
        elif op == "rename":
            self._apply_purge_hints(ev)
            rec = self._dentry(ev["dir"], ev["name"])
            if rec is not None:
                self._rm_dentry(ev["dir"], ev["name"])
                self._set_dentry(ev["newdir"], ev["newname"], rec)

    def _apply_purge_hints(self, ev: dict) -> None:
        """Shared replay of an event's destruction side-effects: drop
        an overwritten/removed dir's dirfrag object (rmdir_ino) and
        purge a dead file inode's data objects (purge) — unlink and
        rename route through the same PurgeQueue role."""
        if ev.get("rmdir_ino"):
            try:
                self.meta_io.remove(dir_oid(ev["rmdir_ino"]))
            except OSError:
                pass
        if ev.get("purge"):
            self._purge_data(ev["purge"]["ino"],
                             ev["purge"]["size"],
                             ev["purge"]["object_size"])

    def _purge_data(self, ino: int, size: int,
                    object_size: int) -> None:
        """Unlink purges the file's data objects (PurgeQueue role)."""
        nobj = max(1, -(-size // object_size)) if size else 0
        for objno in range(nobj):
            try:
                self.data_io.remove(data_oid(ino, objno))
            except OSError:
                pass

    # individual ops ---------------------------------------------------

    DEFAULT_OBJECT_SIZE = 1 << 22      # 4 MiB (file layout default)

    def _op_lookup(self, args):
        rec = self._dentry(args["dir"], args["name"])
        if rec is None:
            return -errno.ENOENT, None
        return 0, rec

    def _op_readdir(self, args):
        try:
            omap = self.meta_io.omap_get(dir_oid(args["dir"]))
        except OSError:
            return -errno.ENOENT, None
        return 0, {name: encoding.decode_any(raw)
                   for name, raw in omap.items()}

    def _op_mkdir(self, args):
        if self._dentry(args["dir"], args["name"]) is not None:
            return -errno.EEXIST, None
        ino = self._alloc_ino()
        rec = {"ino": ino, "type": "dir", "size": 0,
               "mtime": time.time()}
        jtid = self._journal_update({"op": "set_dentry",
                                     "dir": args["dir"],
                                     "name": args["name"], "rec": rec,
                                     "mkdir": True})
        self._apply_event({"op": "set_dentry", "dir": args["dir"],
                           "name": args["name"], "rec": rec,
                           "mkdir": True})
        self._commit(jtid)
        return 0, rec

    def _op_create(self, args):
        existing = self._dentry(args["dir"], args["name"])
        if existing is not None:
            if existing["type"] != "file":
                return -errno.EISDIR, None
            return 0, existing         # open-existing semantics
        ino = self._alloc_ino()
        rec = {"ino": ino, "type": "file", "size": 0,
               "mtime": time.time(),
               "object_size": self.DEFAULT_OBJECT_SIZE}
        ev = {"op": "set_dentry", "dir": args["dir"],
              "name": args["name"], "rec": rec}
        jtid = self._journal_update(ev)
        self._apply_event(ev)
        self._commit(jtid)
        return 0, rec

    def _op_symlink(self, args):
        if not args.get("target"):
            return -errno.ENOENT, None   # authoritative empty-target check
        if self._dentry(args["dir"], args["name"]) is not None:
            return -errno.EEXIST, None
        rec = {"ino": self._alloc_ino(), "type": "symlink",
               "target": args["target"], "size": len(args["target"]),
               "mtime": time.time()}
        ev = {"op": "set_dentry", "dir": args["dir"],
              "name": args["name"], "rec": rec}
        jtid = self._journal_update(ev)
        self._apply_event(ev)
        self._commit(jtid)
        return 0, rec

    def _op_setattr(self, args):
        rec = self._dentry(args["dir"], args["name"])
        if rec is None:
            return -errno.ENOENT, None
        for k in ("size", "mtime"):
            if k in args:
                rec[k] = args[k]
        ev = {"op": "set_dentry", "dir": args["dir"],
              "name": args["name"], "rec": rec}
        jtid = self._journal_update(ev)
        self._apply_event(ev)
        self._commit(jtid)
        return 0, rec

    def _op_unlink(self, args):
        rec = self._dentry(args["dir"], args["name"])
        if rec is None:
            return -errno.ENOENT, None
        if rec["type"] == "dir":
            return -errno.EISDIR, None
        ev = {"op": "rm_dentry", "dir": args["dir"],
              "name": args["name"]}
        if rec["type"] == "file":
            ev["purge"] = {"ino": rec["ino"], "size": rec["size"],
                           "object_size": rec.get(
                               "object_size",
                               self.DEFAULT_OBJECT_SIZE)}
        jtid = self._journal_update(ev)
        self._apply_event(ev)
        self._commit(jtid)
        return 0, None

    def _op_rmdir(self, args):
        rec = self._dentry(args["dir"], args["name"])
        if rec is None:
            return -errno.ENOENT, None
        if rec["type"] != "dir":
            return -errno.ENOTDIR, None
        try:
            if self.meta_io.omap_get(dir_oid(rec["ino"])):
                return -errno.ENOTEMPTY, None
        except OSError:
            pass
        ev = {"op": "rm_dentry", "dir": args["dir"],
              "name": args["name"], "rmdir_ino": rec["ino"]}
        jtid = self._journal_update(ev)
        self._apply_event(ev)
        self._commit(jtid)
        return 0, None

    def _in_subtree(self, root_ino: int, needle_ino: int) -> bool:
        """True when needle_ino is root_ino or any dir beneath it
        (there are no parent pointers, so walk down; subtrees are
        small at this framework's scale)."""
        stack = [root_ino]
        while stack:
            d = stack.pop()
            if d == needle_ino:
                return True
            try:
                omap = self.meta_io.omap_get(dir_oid(d))
            except OSError:
                continue
            for raw in omap.values():
                r = encoding.decode_any(raw)
                if r["type"] == "dir":
                    stack.append(r["ino"])
        return False

    def _op_rename(self, args):
        rec = self._dentry(args["dir"], args["name"])
        if rec is None:
            return -errno.ENOENT, None
        if (args["dir"] == args["newdir"]
                and args["name"] == args["newname"]):
            return 0, rec             # POSIX rename-to-self: no-op
        if rec["type"] == "dir" and self._in_subtree(rec["ino"],
                                                     args["newdir"]):
            # destination inside the source's own subtree: the rename
            # would orphan the subtree in a self-cycle (reference MDS
            # rejects source-is-ancestor-of-dest with EINVAL)
            return -errno.EINVAL, None
        target = self._dentry(args["newdir"], args["newname"])
        rmdir_ino = None
        if target is not None and target["type"] == "dir":
            if rec["type"] != "dir":
                return -errno.EISDIR, None    # non-dir over dir
            try:
                if self.meta_io.omap_get(dir_oid(target["ino"])):
                    return -errno.ENOTEMPTY, None
            except OSError:
                pass
            rmdir_ino = target["ino"]         # dir over EMPTY dir: ok
        elif target is not None and rec["type"] == "dir":
            return -errno.ENOTDIR, None       # dir over non-dir
        ev = {"op": "rename", "dir": args["dir"], "name": args["name"],
              "newdir": args["newdir"], "newname": args["newname"]}
        if rmdir_ino is not None:
            ev["rmdir_ino"] = rmdir_ino
        if (target is not None and target["type"] == "file"
                and target["ino"] != rec["ino"]):
            # rename-over-file: the overwritten inode's data objects
            # would otherwise leak in the data pool (unlink purges;
            # rename must too — reference routes this through the
            # same PurgeQueue)
            ev["purge"] = {"ino": target["ino"],
                           "size": target["size"],
                           "object_size": target.get(
                               "object_size",
                               self.DEFAULT_OBJECT_SIZE)}
        jtid = self._journal_update(ev)
        self._apply_event(ev)
        self._commit(jtid)
        return 0, rec
