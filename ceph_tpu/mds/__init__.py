from .mds_daemon import MDSDaemon

__all__ = ["MDSDaemon"]
