"""ceph_tpu — a TPU-native erasure-coding and placement framework.

A from-scratch reimplementation of the capabilities of Ceph's erasure-code
subsystem and CRUSH placement engine (reference: Ceph v12.1.2), redesigned
TPU-first: the GF(2^w) codec math runs as batched bitplane matrix multiplies
on the MXU (JAX / Pallas), placement (straw2) runs as vectorized uint32/64
integer programs under jit, and the host-side rim (registry, profiles,
pipeline) stays thin and functional.

Layout:
  ceph_tpu.ops       GF(2^w) arithmetic, XOR-matmul kernels, crush hash ops
  ceph_tpu.models    codec families (RS Vandermonde/RAID6, Cauchy, LRC, SHEC, ...)
  ceph_tpu.parallel  device-mesh sharding of stripe batches and placement sweeps
  ceph_tpu.crush     crush map model + batched straw2 mapper
  ceph_tpu.utils     profiles, buffers, config
"""

__version__ = "0.1.0"
