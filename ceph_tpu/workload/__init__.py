"""Open-loop workload generation (the paper's million-client traffic
model; CBT / `rados bench` / COSBench role collapsed into a library).

The defining property is *open-loop* arrivals: each session draws its
request times from an arrival process (Poisson, bursty, diurnal) fixed
in advance, and latency is measured from the SCHEDULED arrival — not
from when a previous completion freed a slot. A closed-loop generator
silently stops applying load exactly when the system is slow, hiding
the queueing it caused (coordinated omission); an open-loop one keeps
the offered rate honest and lets queue delay show up in the recorded
percentiles.

Pieces:

- :mod:`arrivals`   — Poisson / bursty (MMPP) / diurnal / fixed
- :mod:`popularity` — Zipf object popularity (CDF + bisect)
- :mod:`recorder`   — 2^n-microsecond latency histograms per class
- :mod:`feedback`   — dmClock delta/rho client-side accounting
- :mod:`driver`     — async mini-objecter (callback completions)
- :mod:`profiles`   — RADOS read/write/mixed, RBD, RGW S3 / Swift
- :mod:`harness`    — WorkloadHarness: sessions x arrivals -> driver
"""

from .arrivals import (BurstyArrivals, DiurnalArrivals, FixedArrivals,
                       PoissonArrivals)
from .driver import AsyncRadosDriver
from .feedback import DmClockFeedback
from .harness import WorkloadHarness
from .popularity import UniformPopularity, ZipfPopularity
from .profiles import (ProfileSpec, rados_mixed, rados_read,
                       rados_write, rbd_profile, rgw_s3, rgw_swift)
from .recorder import LatencyRecorder

__all__ = [
    "PoissonArrivals", "BurstyArrivals", "DiurnalArrivals",
    "FixedArrivals", "ZipfPopularity", "UniformPopularity",
    "LatencyRecorder", "DmClockFeedback", "AsyncRadosDriver",
    "WorkloadHarness", "ProfileSpec", "rados_read", "rados_write",
    "rados_mixed", "rbd_profile", "rgw_s3", "rgw_swift",
]
