"""Latency recording: per-key power-of-two-microsecond histograms.

Same bucketing the OSD's perf histograms use (2^n us): constant memory
per key no matter how many samples, and percentile error bounded by
one octave. Keys are free-form strings — the harness uses
"<profile>/<class>" so gold and best-effort latencies never mix.
"""

from __future__ import annotations

import threading

_NBUCKETS = 64        # 2^63 us ~ 292k years: effectively unbounded


class _Hist:
    __slots__ = ("buckets", "count", "total_s", "max_s", "errors")

    def __init__(self):
        self.buckets = [0] * _NBUCKETS
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.errors = 0


def _bucket_of(us: int) -> int:
    return min(max(us, 1).bit_length() - 1, _NBUCKETS - 1)


class LatencyRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[str, _Hist] = {}

    def record(self, key: str, seconds: float) -> None:
        us = int(seconds * 1e6)
        with self._lock:
            h = self._hists.setdefault(key, _Hist())
            h.buckets[_bucket_of(us)] += 1
            h.count += 1
            h.total_s += seconds
            if seconds > h.max_s:
                h.max_s = seconds

    def record_error(self, key: str) -> None:
        with self._lock:
            self._hists.setdefault(key, _Hist()).errors += 1

    def percentile(self, key: str, p: float) -> float:
        """p in (0, 1]; returns the UPPER bound of the bucket holding
        the p-th sample (conservative: never understates latency)."""
        with self._lock:
            h = self._hists.get(key)
            if h is None or h.count == 0:
                return 0.0
            want = max(1, int(p * h.count + 0.999999))
            seen = 0
            for i, n in enumerate(h.buckets):
                seen += n
                if seen >= want:
                    return (2 ** (i + 1)) / 1e6
        return h.max_s

    def summary(self) -> dict:
        out = {}
        with self._lock:
            keys = list(self._hists)
        for key in keys:
            h = self._hists[key]
            out[key] = {
                "count": h.count,
                "errors": h.errors,
                "mean_s": (h.total_s / h.count) if h.count else 0.0,
                "p50_s": self.percentile(key, 0.50),
                "p95_s": self.percentile(key, 0.95),
                "p99_s": self.percentile(key, 0.99),
                "max_s": h.max_s,
            }
        return out

    def merge(self, other: "LatencyRecorder") -> None:
        with other._lock:
            items = [(k, h) for k, h in other._hists.items()]
        with self._lock:
            for key, h in items:
                mine = self._hists.setdefault(key, _Hist())
                mine.buckets = [a + b for a, b in
                                zip(mine.buckets, h.buckets)]
                mine.count += h.count
                mine.total_s += h.total_s
                mine.max_s = max(mine.max_s, h.max_s)
                mine.errors += h.errors
