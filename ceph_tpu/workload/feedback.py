"""Client half of dmClock's distributed feedback (Gulati et al.,
OSDI'10 section 3.2).

Each OSD runs its tag queue independently; what makes the aggregate
converge to the GLOBAL reservation/weight targets is the client
stamping every request with how much service it received CLUSTER-WIDE
since its previous request to that same server:

- delta: completions from OTHER servers since the last op sent to
  this one (drives the weight/proportional and limit tags), and
- rho:   the subset of those served in the RESERVATION phase (drives
  the reservation tag).

The serving OSD's own completions are excluded: the queue already
prices the op itself into the tag advance ((rho + cost)/rate), so
with a single server delta = rho = 0 and the formulas reduce exactly
to single-server mClock at the configured rate — counting own service
twice would halve every client's effective reservation.

A server seeing a large delta knows its peers already served this
client plenty and advances the client's tags further (deprioritizing
it locally); an idle server sees delta ~ 0 and keeps the client hot.
That asymmetry is exactly what shifts service toward under-served
OSDs with no server-to-server chatter at all.

Units are whole completions (min_cost quanta are applied server-side
from the op's cost); the reply's qos_phase tells us which phase served
each op, closing the rho loop.
"""

from __future__ import annotations

import threading


class DmClockFeedback:
    """Plugs into RadosClient.qos_feedback / AsyncRadosDriver:
    stamp(osd) -> (delta, rho) on send, observe(osd, phase) on reply."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0.0          # completions, cluster-wide
        self._res_total = 0.0      # ... served in reservation phase
        # osd -> [own_total, own_res]: completions THIS osd served us
        self._own: dict[int, list] = {}
        # osd -> (total, res, own_total, own_res) at our last send
        self._last: dict[int, tuple] = {}

    def observe(self, osd: int, phase: str) -> None:
        with self._lock:
            self._total += 1.0
            own = self._own.setdefault(osd, [0.0, 0.0])
            own[0] += 1.0
            if phase == "reservation":
                self._res_total += 1.0
                own[1] += 1.0

    def stamp(self, osd: int) -> tuple[float, float]:
        with self._lock:
            own = self._own.get(osd, [0.0, 0.0])
            pt, pr, pot, por = self._last.get(osd, (0.0,) * 4)
            # service from OTHERS = global growth minus this osd's own
            delta = (self._total - pt) - (own[0] - pot)
            rho = (self._res_total - pr) - (own[1] - por)
            self._last[osd] = (self._total, self._res_total,
                               own[0], own[1])
            return delta, rho

    def totals(self) -> tuple[float, float]:
        with self._lock:
            return self._total, self._res_total
