"""Async mini-objecter: callback completions over a RadosClient's
messenger.

RadosClient.submit_op is synchronous — one blocked thread per op —
which caps a generator at a few hundred concurrent ops. The harness
needs thousands of distinct SESSIONS with open-loop arrivals, so this
driver keeps its own inflight table keyed by tid and completes ops
from the dispatch thread via callbacks: one thread, unbounded
concurrency.

It piggybacks on an existing client: same messenger (so cephx,
throttles and the mon subscription keep working), same tid counter (so
(client, tid) stays unique and OSD-side dedup still recognizes our
resends), but its OWN dispatcher registered at the head — replies to
our tids never reach the client's table, and everything else falls
through untouched. Each op carries the SESSION the caller supplies,
which is how one process impersonates a million principals: the OSD's
perf-query attribution keys on (client, session), not on the TCP
connection.
"""

from __future__ import annotations

import threading
import time

from ..msg.message import MOSDOp

_EAGAIN = -11


class _Pending:
    __slots__ = ("tid", "pool_id", "oid", "ops", "session", "key",
                 "scheduled", "cb", "sent_at", "retry_at", "resends",
                 "flags")

    def __init__(self, tid, pool_id, oid, ops, session, key,
                 scheduled, cb, flags):
        self.tid = tid
        self.pool_id = pool_id
        self.oid = oid
        self.ops = ops
        self.session = session
        self.key = key
        self.scheduled = scheduled
        self.cb = cb
        self.sent_at = 0.0
        self.retry_at = 0.0
        self.resends = 0
        self.flags = flags


class AsyncRadosDriver:
    """submit() never blocks; completions arrive on the messenger's
    dispatch thread as cb(pending, result, data, now)."""

    def __init__(self, client, feedback=None,
                 resend_every: float = 1.0):
        self.client = client
        self.feedback = feedback
        self.resend_every = resend_every
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight: dict[int, _Pending] = {}
        self.peak_inflight = 0
        self.sent = 0
        self.resent = 0
        self.completed = 0
        client.msgr.add_dispatcher_head(self)

    # -- dispatch (runs on the messenger thread) -----------------------

    def ms_dispatch(self, msg) -> bool:
        if msg.get_type() != "MOSDOpReply":
            return False
        with self._lock:
            p = self._inflight.get(msg.tid)
            if p is None:
                return False           # the client's op, not ours
            if msg.result == _EAGAIN:
                # wrong/unready primary: back off, tick() resends
                p.retry_at = time.monotonic() + 0.1
                return True
            del self._inflight[msg.tid]
            self.completed += 1
            if not self._inflight:
                self._idle.notify_all()
        if self.feedback is not None:
            src = getattr(msg, "from_name", None)
            self.feedback.observe(src[1] if src else -1,
                                  getattr(msg, "qos_phase", ""))
        p.cb(p, msg.result, msg.data, time.monotonic())
        return True

    # -- submission ----------------------------------------------------

    def submit(self, pool_id: int, oid: str, ops: list, session: str,
               key: str, scheduled: float, cb, flags: int = 0) -> int:
        tid = next(self.client._tids)
        p = _Pending(tid, pool_id, oid, ops, session, key,
                     scheduled, cb, flags)
        with self._lock:
            self._inflight[tid] = p
            if len(self._inflight) > self.peak_inflight:
                self.peak_inflight = len(self._inflight)
        self._send(p)
        self.sent += 1
        return tid

    def _send(self, p: _Pending) -> None:
        c = self.client
        try:
            pgid, primary = c._target_for(p.pool_id, p.oid)
        except Exception:
            primary = -1
        now = time.monotonic()
        if primary == -1:
            p.retry_at = now + 0.1     # no primary yet: tick() retries
            return
        addrs = c.osdmap.get_addr(primary)
        addr = addrs.get("public") if isinstance(addrs, dict) else addrs
        if addr is None:
            p.retry_at = now + 0.1
            return
        qd = qr = 0.0
        if self.feedback is not None:
            qd, qr = self.feedback.stamp(primary)
        p.sent_at = now
        # exponential backoff, capped: the resend timer exists for
        # LOST ops. An op the server is deliberately holding (dmclock
        # limit, throttle) never replies either — without backoff a
        # parked backlog of N ops becomes a standing N msg/s duplicate
        # storm that perturbs the very experiment throttling it.
        p.retry_at = now + min(
            self.resend_every * (2.0 ** p.resends), 30.0)
        c.msgr.send_message(
            MOSDOp(client_id=c.client_id, tid=p.tid, pgid=pgid,
                   oid=p.oid, ops=p.ops, map_epoch=c.osdmap.epoch,
                   session=p.session, flags=p.flags,
                   qos_delta=qd, qos_rho=qr), addr)

    # -- maintenance ---------------------------------------------------

    def tick(self, now: float | None = None) -> int:
        """Resend scan (Objecter::tick role): anything unanswered past
        its retry deadline goes out again with the SAME tid, so the
        OSD's reqid dedup absorbs duplicates."""
        now = time.monotonic() if now is None else now
        with self._lock:
            due = [p for p in self._inflight.values()
                   if p.retry_at and now >= p.retry_at]
        for p in due:
            self.resent += 1
            p.resends += 1
            self._send(p)
        return len(due)

    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for every outstanding op to complete; ticks while
        waiting so stragglers keep being resent."""
        deadline = time.monotonic() + timeout
        while True:
            with self._idle:
                if not self._inflight:
                    return True
                if time.monotonic() >= deadline:
                    return False
                self._idle.wait(0.1)
            self.tick()
