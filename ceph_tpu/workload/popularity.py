"""Object popularity: which object each request touches.

Storage traces are famously Zipf-like — a small hot set absorbs most
of the IO. Sampling uses the precomputed CDF + bisect so a draw is
O(log n) regardless of skew, and the whole distribution is reproducible
from (n, alpha, seed).
"""

from __future__ import annotations

import bisect
import random


class ZipfPopularity:
    """Rank-frequency Zipf over `n` objects: P(rank k) ~ 1 / k^alpha.
    alpha ~ 0.9-1.2 matches published block/object traces; alpha = 0
    degenerates to uniform."""

    def __init__(self, n: int, alpha: float = 1.1, seed: int = 0):
        if n <= 0:
            raise ValueError("n must be > 0")
        self.n = n
        self.alpha = alpha
        self._rng = random.Random(seed)
        cdf = []
        total = 0.0
        for k in range(1, n + 1):
            total += 1.0 / (k ** alpha)
            cdf.append(total)
        self._cdf = [c / total for c in cdf]

    def sample(self, rng: random.Random | None = None) -> int:
        """Draw an object index in [0, n) — 0 is the hottest."""
        u = (rng or self._rng).random()
        return bisect.bisect_left(self._cdf, u)

    def hot_set(self, fraction: float = 0.9) -> int:
        """How many top-ranked objects absorb `fraction` of the mass —
        handy for sizing caches and for test assertions on skew."""
        return bisect.bisect_left(self._cdf, fraction) + 1


class UniformPopularity:
    """Every object equally likely (the anti-Zipf control group)."""

    def __init__(self, n: int, seed: int = 0):
        if n <= 0:
            raise ValueError("n must be > 0")
        self.n = n
        self._rng = random.Random(seed)

    def sample(self, rng: random.Random | None = None) -> int:
        return (rng or self._rng).randrange(self.n)
