"""WorkloadHarness: N client sessions multiplexed over one messenger.

One process, one RadosClient, one TCP mesh — but every session carries
its own nonce in the MOSDOp envelope, so the OSD's perf-query
attribution (PR-15) sees N distinct principals exactly as if N real
clients had connected. That is what makes "a million clients" a
laptop-sized experiment instead of a datacenter one.

The run loop is a heap-merge of per-session arrival schedules:

    (arrival offset, session) <- heap;  wait until its time;  submit

Submission never waits for completions (open-loop): if the cluster
falls behind, inflight grows and the latency recorder — which clocks
every op from its SCHEDULED arrival — shows the queueing honestly.
Clock and sleep are injectable so the tier-1 smoke test can run a
fixed schedule deterministically.
"""

from __future__ import annotations

import hashlib
import heapq
import http.client
import queue
import random
import threading
import time

from .driver import AsyncRadosDriver
from .recorder import LatencyRecorder


class _Session:
    __slots__ = ("idx", "nonce", "rng", "arrivals")

    def __init__(self, idx: int, nonce: str, rng: random.Random,
                 arrivals):
        self.idx = idx
        self.nonce = nonce
        self.rng = rng
        self.arrivals = arrivals


def session_nonce(idx: int, seed: int = 0) -> str:
    """Deterministic, distinct-in-the-first-8-chars nonce: attribution
    keys on session[:8], so the index goes first and a seed-derived
    tail keeps full nonces unique across harness instances."""
    tail = hashlib.md5(b"wl:%d:%d" % (seed, idx)).hexdigest()[:24]
    return "%08x%s" % (idx, tail)


class WorkloadHarness:
    def __init__(self, client, pool: str, profile, num_sessions: int,
                 arrival_factory, popularity, recorder=None,
                 feedback=None, klass: str = "client", seed: int = 0,
                 clock=time.monotonic, sleep=time.sleep,
                 http_addr=None, http_headers=None,
                 http_workers: int = 8, driver=None):
        """arrival_factory(session_idx) -> iterable of arrival offsets.
        For RADOS-kind profiles ops ride `driver` (an AsyncRadosDriver,
        created on demand over `client`); HTTP-kind profiles need
        `http_addr` = (host, port) of a gateway."""
        self.client = client
        self.pool_id = client.pool_id(pool) if pool else -1
        self.profile = profile
        self.popularity = popularity
        self.recorder = recorder if recorder is not None \
            else LatencyRecorder()
        self.klass = klass
        self.clock = clock
        self.sleep = sleep
        self.http_addr = http_addr
        self.http_headers = dict(http_headers or {})
        self.http_workers = http_workers
        if profile.kind == "rados":
            self.driver = driver if driver is not None else \
                AsyncRadosDriver(client, feedback=feedback)
        else:
            self.driver = driver
        self.sessions = [
            _Session(i, session_nonce(i, seed),
                     random.Random((seed << 20) ^ i),
                     iter(arrival_factory(i)))
            for i in range(num_sessions)]
        self._key = "%s/%s" % (profile.name, klass)
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.errors = 0
        self.bytes_offered = 0
        self._t0 = 0.0
        self._httpq: queue.Queue | None = None
        self._http_threads: list[threading.Thread] = []

    # -- completions ---------------------------------------------------

    def _on_done(self, pending, result, data, _now) -> None:
        lat = self.clock() - pending.scheduled
        if result < 0:
            self.recorder.record_error(self._key)
            with self._lock:
                self.errors += 1
        else:
            self.recorder.record(self._key, max(lat, 0.0))
        with self._lock:
            self.completed += 1

    # -- http leg ------------------------------------------------------

    def _http_worker(self) -> None:
        conn = None
        while True:
            task = self._httpq.get()
            if task is None:
                if conn is not None:
                    conn.close()
                return
            item, scheduled = task
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(*self.http_addr)
                conn.request(item.method, item.path, body=item.body,
                             headers=dict(self.http_headers,
                                          **item.headers))
                resp = conn.getresponse()
                resp.read()
                ok = resp.status < 400
            except Exception:
                ok = False
                try:
                    conn.close()
                except Exception:
                    pass
                conn = None
            lat = self.clock() - scheduled
            if ok:
                self.recorder.record(self._key, max(lat, 0.0))
            else:
                self.recorder.record_error(self._key)
            with self._lock:
                self.completed += 1
                if not ok:
                    self.errors += 1

    def _start_http(self) -> None:
        self._httpq = queue.Queue()
        for _ in range(self.http_workers):
            t = threading.Thread(target=self._http_worker,
                                 daemon=True)
            t.start()
            self._http_threads.append(t)

    def _stop_http(self) -> None:
        for _ in self._http_threads:
            self._httpq.put(None)
        for t in self._http_threads:
            t.join(timeout=10.0)
        self._http_threads = []

    # -- run loop ------------------------------------------------------

    def _submit(self, sess: _Session, scheduled: float) -> None:
        item = self.profile.build(sess.rng, self.popularity)
        with self._lock:
            self.submitted += 1
            self.bytes_offered += item.nbytes
        if item.kind == "rados":
            self.driver.submit(self.pool_id, item.oid, item.ops,
                               sess.nonce, self._key, scheduled,
                               self._on_done)
        else:
            self._httpq.put((item, scheduled))

    def run(self, duration: float | None = None,
            max_ops: int | None = None,
            drain_timeout: float = 30.0) -> dict:
        """Play the merged schedule until `duration` (offset seconds)
        or `max_ops` submissions, then drain and report."""
        if self.profile.kind == "http":
            if self.http_addr is None:
                raise ValueError("http profile needs http_addr")
            self._start_http()
        heap = []
        for s in self.sessions:
            off = next(s.arrivals, None)
            if off is not None:
                heapq.heappush(heap, (off, s.idx))
        self._t0 = self.clock()
        try:
            while heap:
                off, idx = heapq.heappop(heap)
                if duration is not None and off > duration:
                    break
                if max_ops is not None and self.submitted >= max_ops:
                    break
                target = self._t0 + off
                while True:
                    now = self.clock()
                    if now >= target:
                        break
                    if self.driver is not None:
                        self.driver.tick()
                    self.sleep(min(target - now, 0.05))
                sess = self.sessions[idx]
                self._submit(sess, target)
                nxt = next(sess.arrivals, None)
                if nxt is not None:
                    heapq.heappush(heap, (nxt, idx))
        finally:
            drained = True
            if self.driver is not None:
                drained = self.driver.drain(drain_timeout)
            if self._http_threads:
                self._stop_http()
        return self.stats(drained=drained)

    def stats(self, drained: bool = True) -> dict:
        elapsed = max(self.clock() - self._t0, 1e-9)
        out = {
            "profile": self.profile.name,
            "klass": self.klass,
            "sessions": len(self.sessions),
            "submitted": self.submitted,
            "completed": self.completed,
            "errors": self.errors,
            "bytes_offered": self.bytes_offered,
            "duration_s": elapsed,
            "offered_rate": self.submitted / elapsed,
            "drained": drained,
            "latency": self.recorder.summary(),
        }
        if self.driver is not None:
            out["peak_inflight"] = self.driver.peak_inflight
            out["resent"] = self.driver.resent
        return out
