"""Workload profiles: what each scheduled arrival actually does.

A profile is a pure generator — given the per-session RNG and the
popularity sampler it returns one WorkItem (RADOS ops, or an HTTP
request for the gateway fronts). It holds no sockets and no state, so
the same profile object is shared by every session.

Catalog (the shapes the paper's evaluation sweeps):

- rados_read / rados_write / rados_mixed  — raw object IO
- rbd_profile  — block-device IO: random offsets inside a virtual
  image, mapped to `rbd_data.<image>.%016x` chunk objects exactly like
  the librbd striper, so hot-chunk skew matches real RBD traffic
- rgw_s3 / rgw_swift — gateway HTTP traffic for either front
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class WorkItem:
    kind: str                  # "rados" | "http"
    nbytes: int = 0
    # rados
    oid: str = ""
    ops: list = field(default_factory=list)
    # http
    method: str = "GET"
    path: str = ""
    body: bytes = b""
    headers: dict = field(default_factory=dict)


@dataclass
class ProfileSpec:
    name: str
    kind: str                  # "rados" | "http"
    build: Callable            # (rng, popularity) -> WorkItem


def _payload(rng: random.Random, size: int) -> bytes:
    # one random byte repeated: cheap to build, still defeats
    # dedup-by-zero shortcuts in the object store
    return bytes([rng.randrange(256)]) * size


def rados_read(obj_prefix: str = "wl", size: int = 4096) -> ProfileSpec:
    def build(rng, pop):
        oid = "%s.%08d" % (obj_prefix, pop.sample(rng))
        return WorkItem(kind="rados", oid=oid,
                        ops=[("read", 0, size)], nbytes=size)
    return ProfileSpec("rados-read", "rados", build)


def rados_write(obj_prefix: str = "wl",
                size: int = 4096) -> ProfileSpec:
    def build(rng, pop):
        oid = "%s.%08d" % (obj_prefix, pop.sample(rng))
        return WorkItem(kind="rados", oid=oid,
                        ops=[("writefull", _payload(rng, size))],
                        nbytes=size)
    return ProfileSpec("rados-write", "rados", build)


def rados_mixed(obj_prefix: str = "wl", size: int = 4096,
                read_fraction: float = 0.7) -> ProfileSpec:
    def build(rng, pop):
        oid = "%s.%08d" % (obj_prefix, pop.sample(rng))
        if rng.random() < read_fraction:
            return WorkItem(kind="rados", oid=oid,
                            ops=[("read", 0, size)], nbytes=size)
        return WorkItem(kind="rados", oid=oid,
                        ops=[("writefull", _payload(rng, size))],
                        nbytes=size)
    return ProfileSpec("rados-mixed", "rados", build)


def rbd_profile(image: str = "wlimg", image_size: int = 1 << 26,
                order: int = 22, io_size: int = 4096,
                read_fraction: float = 0.5) -> ProfileSpec:
    """Block-style IO: popularity picks the CHUNK (so hot-chunk skew is
    Zipf like real VM images), the offset inside it is uniform. One IO
    never spans chunks — same constraint the striper enforces."""
    chunk = 1 << order
    nchunks = max(1, image_size // chunk)

    def build(rng, pop):
        block = pop.sample(rng) % nchunks
        oid = "rbd_data.%s.%016x" % (image, block)
        off = rng.randrange(max(1, chunk - io_size))
        if rng.random() < read_fraction:
            ops = [("read", off, io_size)]
        else:
            ops = [("write", off, _payload(rng, io_size))]
        return WorkItem(kind="rados", oid=oid, ops=ops,
                        nbytes=io_size)
    return ProfileSpec("rbd", "rados", build)


def rgw_s3(bucket: str = "wlbkt", size: int = 4096,
           read_fraction: float = 0.7) -> ProfileSpec:
    def build(rng, pop):
        key = "o%08d" % pop.sample(rng)
        path = "/%s/%s" % (bucket, key)
        if rng.random() < read_fraction:
            return WorkItem(kind="http", method="GET", path=path,
                            nbytes=size)
        return WorkItem(kind="http", method="PUT", path=path,
                        body=_payload(rng, size), nbytes=size)
    return ProfileSpec("rgw-s3", "http", build)


def rgw_swift(container: str = "wlbkt", size: int = 4096,
              read_fraction: float = 0.7) -> ProfileSpec:
    def build(rng, pop):
        key = "o%08d" % pop.sample(rng)
        path = "/swift/v1/%s/%s" % (container, key)
        if rng.random() < read_fraction:
            return WorkItem(kind="http", method="GET", path=path,
                            nbytes=size)
        return WorkItem(kind="http", method="PUT", path=path,
                        body=_payload(rng, size), nbytes=size)
    return ProfileSpec("rgw-swift", "http", build)
